"""ceph_crc32c — the Castagnoli CRC the reference uses everywhere.

Re-expresses /root/reference/src/include/crc32c.h (`ceph_crc32c(seed, data,
len)`) / src/common/sctp_crc32.c: CRC-32C (polynomial 0x1EDC6F41, reflected
0x82F63B78), bitwise-reflected in/out, NO final inversion — callers seed with
-1 themselves (e.g. the EC deep-scrub shard hashes, ECBackend.cc:2482
`bufferhash(-1)`, and ECUtil::HashInfo's cumulative shard hashes).

The byte loop runs over a numpy view with a 256-entry table, sliced eight
bytes per step (slice-by-8) so scrubbing megabyte shards stays usable from
Python; parity vs the compiled reference sctp_crc32.c is pinned in
tests/test_scrub.py.
"""

from __future__ import annotations

import ctypes

import numpy as np

_POLY = 0x82F63B78


def _make_table() -> np.ndarray:
    table = np.zeros((8, 256), dtype=np.uint32)
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ _POLY if c & 1 else c >> 1
        table[0, n] = c
    for k in range(1, 8):
        for n in range(256):
            c = table[k - 1, n]
            table[k, n] = table[0, c & 0xFF] ^ (c >> 8)
    return table


_TABLE = _make_table()


def _load_native():
    """C slicing-by-8 via ctypes (ceph_tpu/native/crc32c.c): the frame
    checksum and shard hashes are per-byte hot paths that a Python loop
    turns into the daemon's top CPU sink. Falls back to numpy silently
    (same bits either way; parity pinned in tests)."""
    try:
        import ctypes
        import os

        from ceph_tpu.native.build import build_shared

        so = build_shared(
            "crc32c",
            os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)
                )),
                "native", "crc32c.c",
            ),
        )
        if so is None:
            return None
        lib = ctypes.CDLL(so)
        fn = lib.ceph_crc32c_native
        fn.restype = ctypes.c_uint32
        fn.argtypes = [ctypes.c_uint32, ctypes.c_char_p, ctypes.c_size_t]
        return fn
    # cephlint: disable=error-taxonomy (native-impl probe: any failure falls back to the python crc)
    except Exception:
        return None


_NATIVE = _load_native()


def ceph_crc32c(
    seed: int, data: bytes | np.ndarray, length: int | None = None
) -> int:
    """crc32c(seed, data[:length]) with ceph's conventions (no final xor).

    `length` checksums a prefix without materializing the slice — the wire
    read path hands the whole `payload+crc` buffer straight here. Chaining
    is supported the way the reference's bufferlist crc is: the return
    value is the raw register state, so crc(AB) == crc32c(crc32c(seed, A),
    B) — Frame.encode_parts exploits that to checksum a segment list
    without joining it first.
    """
    if _NATIVE is not None:
        if isinstance(data, memoryview) and data.contiguous:
            # the shm-ring receive path checksums loaned views; handing
            # the buffer address over directly keeps it zero-copy
            n = data.nbytes if length is None else min(length, data.nbytes)
            try:
                buf = (ctypes.c_char * data.nbytes).from_buffer(data)
                return int(_NATIVE(seed & 0xFFFFFFFF, buf, n))
            except TypeError:
                pass  # read-only exporter: fall through to the copy
        raw = data if isinstance(data, bytes) else bytes(data)
        n = len(raw) if length is None else min(length, len(raw))
        return int(_NATIVE(seed & 0xFFFFFFFF, raw, n))
    crc = np.uint32(seed & 0xFFFFFFFF)
    buf = np.frombuffer(
        data if isinstance(data, (bytes, bytearray, memoryview))
        else bytes(data),
        dtype=np.uint8,
    )
    if length is not None:
        buf = buf[:length]
    t = _TABLE
    n8 = len(buf) // 8 * 8
    if n8:
        words = buf[:n8].reshape(-1, 8)
        for row in words:
            crc = np.uint32(
                t[7, (crc ^ row[0]) & np.uint32(0xFF)]
                ^ t[6, ((crc >> np.uint32(8)) ^ row[1]) & np.uint32(0xFF)]
                ^ t[5, ((crc >> np.uint32(16)) ^ row[2]) & np.uint32(0xFF)]
                ^ t[4, ((crc >> np.uint32(24)) ^ row[3]) & np.uint32(0xFF)]
                ^ t[3, row[4]] ^ t[2, row[5]] ^ t[1, row[6]] ^ t[0, row[7]]
            )
    for b in buf[n8:]:
        crc = np.uint32(t[0, (crc ^ b) & np.uint32(0xFF)] ^ (crc >> np.uint32(8)))
    return int(crc)
