"""dout-style logging — the reference's src/log + common/debug.h.

Per-subsystem debug levels (`debug_<subsys> = N` config options, declared in
the central schema like every other knob, the `dout(N) << ...` gather/gate
idiom), an always-on in-memory ring of recent entries regardless of the
emission level (Log.cc keeps `m_recent` so crashes can dump context that was
never written out), and a `log dump` admin command that flushes the ring —
mirroring `ceph daemon <x> log dump`.

The gate is the hot-path cost: `logger.dout(level)` returns None when gated,
comparing against a CACHED level (refreshed through the config-observer
mechanism, the way the reference caches gather levels per subsystem) so
callers pay one comparison and skip message formatting entirely:

    log = cluster.logs.get_logger("rados")
    if (d := log.dout(10)) is not None:
        d(f"expensive {state}")
"""

from __future__ import annotations

import sys
import time
from collections import deque
from typing import Callable

from ceph_tpu.common.config import Config, ConfigError, config as global_config
from ceph_tpu.common.tracer import current_trace_id

#: default emitted level 1 / gathered (ring) level 5, like the reference's
#: "1/5"-style subsys defaults (src/common/subsys.h)
DEFAULT_LEVEL = 1
RING_LEVEL = 5
RING_SIZE = 10000


class Logger:
    """One subsystem's gate + sink (the dout side of src/log/Log.cc)."""

    def __init__(self, subsys: str, ring: deque, config: Config):
        self.subsys = subsys
        self._ring = ring
        self._stream = sys.stderr
        self._level = DEFAULT_LEVEL
        option = f"debug_{subsys}"
        try:
            self._level = int(config.get(option))
            config.observe(option, self._on_level_change)
        except ConfigError:
            # unknown subsystem in a custom schema: stay at the default
            pass

    def _on_level_change(self, _name: str, value: int) -> None:
        self._level = int(value)

    def level(self) -> int:
        return self._level

    def dout(self, level: int) -> Callable[[str], None] | None:
        """None when fully gated; else a sink the caller formats into."""
        emit = level <= self._level
        gather = level <= RING_LEVEL
        if not (emit or gather):
            return None

        def sink(message: str) -> None:
            # correlate with dump_tracing: lines logged inside a traced
            # op carry its id (the reference prefixes lttng/jaeger ids
            # the same way); one contextvar read per EMITTED line only
            tid = current_trace_id()
            if tid is not None:
                message = f"trace={tid} {message}"
            record = (time.time(), self.subsys, level, message)
            if gather:
                self._ring.append(record)
            if emit:
                print(
                    f"{record[0]:.6f} {self.subsys} {level} : {message}",
                    file=self._stream,
                )

        return sink


class LogRegistry:
    """All subsystem loggers sharing one recent-entries ring."""

    def __init__(self, config: Config | None = None):
        self._ring: deque = deque(maxlen=RING_SIZE)
        self._config = config if config is not None else global_config
        self._loggers: dict[str, Logger] = {}

    def get_logger(self, subsys: str) -> Logger:
        logger = self._loggers.get(subsys)
        if logger is None:
            logger = self._loggers[subsys] = Logger(
                subsys, self._ring, self._config
            )
        return logger

    def dump_recent(self) -> list[dict]:
        """The crash-dump / `log dump` view of the ring (Log::dump_recent)."""
        return [
            {"stamp": ts, "subsys": s, "level": lv, "message": m}
            for ts, s, lv, m in self._ring
        ]

    def clear(self) -> None:
        self._ring.clear()
