"""Distributed tracing — the reference's src/common/tracer + blkin role.

A Dapper-style tracer (Sigelman et al. 2010): every sampled request gets
a trace id; each timed unit of work is a span (span_id, parent_id) with
tags and timestamped events; the (trace_id, span_id, sampled) context
travels across daemons as an optional field on the wire `Message`, so a
span started in the Rados client continues through the messenger, the
OSD op queue, the encode service, and the object store, and forks a
child span per replica/EC-shard sub-op — the same shape Ceph gets from
jaeger-tracing wired through ProtocolV2 (src/common/tracer.h,
src/msg/async/ProtocolV2.cc encode_trace).

Pieces:

  * `SpanContext` — the wire form, one compact string
    "<trace_id>:<span_id>:<flags>" (flags bit0 = sampled, bit1 =
    flight-recorded), carried by `Message.trace` (msg/frames.py).
  * `Span` — timed unit with tags + events; `finish()` lands it in the
    tracer's bounded completed-span ring, feeds a per-span-name
    PerfCounters latency histogram (picked up by `perf dump` and the
    Prometheus exporter), and appends one Jaeger-compatible JSON line
    to `tracer_export_path` when set (tools/trace_tool.py renders it).
  * `Tracer` — per-daemon factory. Config knobs (central schema):
    `tracer_enabled`, `tracer_sample_rate`, `tracer_ring_size`,
    `tracer_export_path`, plus per-op-type `tracer_sample_rate_<type>`
    root-rate overrides (-1 inherits; recovery reads can run at 100%
    while steady-state IO stays sampled); all observed at runtime like
    debug levels.

Flight recorder / tail sampling (the Canopy shape, Kaldor et al. 2017):
with the tracer enabled, EVERY op records spans — head sampling only
decides which spans are *exported* up front. Unsampled spans carry
``sampled=False`` and land in a separate bounded flight ring (Span
objects, no dict built on the hot path) where the keep/drop decision
moves to op COMPLETION: a tail-eligible root span (``tail=True``) is
promoted when it is slow (`tracer_tail_slow_ms`), among the slowest-N
of its window (`tracer_tail_top_n`/`tracer_tail_window_s`), carries an
error/retry/redirect tag (`tracer_tail_errors`), or matches an
mgr-pushed SLO capture predicate (budgeted per window by
`tracer_tail_capture_per_window`). Promoted traces sit in a small
outbox drained by the daemon's mgr report tick (`drain_promoted`),
their trace ids ride as OpenMetrics exemplars on the latency
histograms (`exemplars`), and the whole flight ring is the crash
black-box payload (`flight_snapshot`) when a daemon fences.

Cost discipline (the dout-gate idiom, common/log.py): the enabled flag
is CACHED and checked first in every factory method, so a disabled
tracer costs one flag check per span site and allocates nothing:

    if (sp := tracer.child("blockstore_read")) is not None:
        sp.set_tag("cache", "hit")
        sp.finish()

The task-local current context (`use`/`use_wire`) rides a contextvar so
awaits and `create_task` propagate it without plumbing; `child()`
returns None when no sampled context is active — interior span sites
never start traces of their own.
"""

from __future__ import annotations

import contextvars
import heapq
import json
import os
import random
import time
from collections import deque
from typing import Any

from ceph_tpu.common.config import Config, ConfigError
from ceph_tpu.common.config import config as global_config
from ceph_tpu.common.perf_counters import PerfCounters

#: the active span context for the op executing in this task/thread
_current: "contextvars.ContextVar[SpanContext | None]" = (
    contextvars.ContextVar("ceph_tracer_ctx", default=None)
)


def current_context() -> "SpanContext | None":
    return _current.get()


def current_trace_id() -> str | None:
    """Trace id of the active context, for log correlation (the
    `trace=<id>` dout prefix); None when untraced."""
    ctx = _current.get()
    return None if ctx is None else ctx.trace_id


#: op types with a `tracer_sample_rate_<type>` schema entry — keeps the
#: cached-rate table in lockstep with common/config.py
_OP_RATE_TYPES = (
    "read", "write", "ops", "delete", "call", "stat", "recovery",
    "command", "balancer",
)


class SpanContext:
    """What propagates: ids + the keep-decision flags, never payload.

    Flags: bit0 = sampled (head decision, export up front), bit1 =
    flight-only (record into the receiver's flight ring; the keep/drop
    decision happens at op completion). A context with neither bit is
    dead weight and decodes to an untraceable context."""

    __slots__ = ("trace_id", "span_id", "sampled", "flight")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True,
                 flight: bool = False):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled
        self.flight = flight

    def encode(self) -> str:
        flags = (1 if self.sampled else 0) | (2 if self.flight else 0)
        return f"{self.trace_id}:{self.span_id}:{flags}"

    @staticmethod
    def decode(raw: str | None) -> "SpanContext | None":
        if not raw:
            return None
        parts = raw.split(":")
        if len(parts) != 3 or not parts[0] or not parts[1]:
            return None
        try:
            flags = int(parts[2] or 0)
        except ValueError:
            return None
        return SpanContext(
            parts[0], parts[1], bool(flags & 1), bool(flags & 2)
        )


class Span:
    __slots__ = (
        "_tracer", "trace_id", "span_id", "parent_id", "name",
        "service", "start", "end", "tags", "events", "sampled", "tail",
    )

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: str | None,
                 tags: dict | None, start: float | None,
                 sampled: bool = True, tail: bool = False):
        self._tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.service = tracer.service
        self.start = time.time() if start is None else start
        self.end: float | None = None
        self.tags: dict[str, Any] = dict(tags) if tags else {}
        self.events: list[tuple[float, str]] = []
        #: head decision: export to ring/JSONL on finish
        self.sampled = sampled
        #: tail-eligible ROOT: finish() runs the keep/drop predicates
        self.tail = tail

    # -- recording ------------------------------------------------------------

    def log(self, event: str) -> None:
        self.events.append((time.time(), event))

    def set_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    def context(self) -> SpanContext:
        return SpanContext(
            self.trace_id, self.span_id, self.sampled,
            flight=not self.sampled,
        )

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None else time.time()) - self.start

    def finish(self) -> None:
        """Close the span (idempotent): ring + perf histogram + export."""
        if self.end is not None:
            return
        self.end = time.time()
        self._tracer._finished(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> bool:
        self.finish()
        return False

    # -- serialization --------------------------------------------------------

    def dump(self) -> dict:
        """The admin-surface (`dump_tracing`) form."""
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "service": self.service,
            "start": self.start,
            "duration": self.duration,
            "tags": {k: _jsonable(v) for k, v in self.tags.items()},
            "events": [
                {"ts": ts, "event": ev} for ts, ev in self.events
            ],
        }

    def to_jaeger(self) -> dict:
        """One span in Jaeger JSON (the jaeger-ui import format; µs)."""
        refs = []
        if self.parent_id:
            refs.append({
                "refType": "CHILD_OF",
                "traceID": self.trace_id,
                "spanID": self.parent_id,
            })
        return {
            "traceID": self.trace_id,
            "spanID": self.span_id,
            "operationName": self.name,
            "references": refs,
            "startTime": int(self.start * 1e6),
            "duration": int(self.duration * 1e6),
            "tags": [
                {"key": k, "type": "string", "value": str(v)}
                for k, v in self.tags.items()
            ],
            "logs": [
                {"timestamp": int(ts * 1e6),
                 "fields": [{"key": "event", "value": ev}]}
                for ts, ev in self.events
            ],
            "process": {"serviceName": self.service, "tags": []},
        }


def _jsonable(v):
    return v if isinstance(v, (str, int, float, bool, type(None))) else str(v)


class Tracer:
    """Per-daemon span factory + bounded completed-span ring."""

    def __init__(self, service: str, config: Config | None = None):
        self.service = service
        cfg = config if config is not None else global_config
        self._rng = random.Random()
        self._on = False
        self._rate = 1.0
        #: per-op-type sample-rate overrides (tracer_sample_rate_<type>):
        #: only types with a non-negative override are present, so the
        #: common case stays one dict-get against an empty dict
        self._op_rates: dict[str, float] = {}
        self._export_path = ""
        ring_size = 1024
        flight_size = 2048
        #: tail-sampling knobs (cached, config-observed)
        self._tail_slow_ms = 1000.0
        self._tail_top_n = 0
        self._tail_window = 10.0
        self._tail_errors = True
        self._tail_budget = 2
        try:
            self._on = bool(cfg.get("tracer_enabled"))
            self._rate = float(cfg.get("tracer_sample_rate"))
            self._export_path = cfg.get("tracer_export_path")
            ring_size = int(cfg.get("tracer_ring_size"))
            cfg.observe("tracer_enabled", self._on_enabled)
            cfg.observe("tracer_sample_rate", self._on_rate)
            cfg.observe("tracer_export_path", self._on_export)
            cfg.observe("tracer_ring_size", self._on_ring)
            for t in _OP_RATE_TYPES:
                name = f"tracer_sample_rate_{t}"
                try:
                    rate = float(cfg.get(name))
                except ConfigError:
                    continue  # older/custom schema without this type
                if rate >= 0:
                    self._op_rates[t] = rate
                cfg.observe(name, self._make_op_rate_cb(t))
            flight_size = int(cfg.get("tracer_flight_ring_size"))
            self._tail_slow_ms = float(cfg.get("tracer_tail_slow_ms"))
            self._tail_top_n = int(cfg.get("tracer_tail_top_n"))
            self._tail_window = float(cfg.get("tracer_tail_window_s"))
            self._tail_errors = bool(cfg.get("tracer_tail_errors"))
            self._tail_budget = int(
                cfg.get("tracer_tail_capture_per_window")
            )
            cfg.observe("tracer_flight_ring_size", self._on_flight_ring)
            cfg.observe("tracer_tail_slow_ms", self._on_tail_slow)
            cfg.observe("tracer_tail_top_n", self._on_tail_top)
            cfg.observe("tracer_tail_window_s", self._on_tail_window)
            cfg.observe("tracer_tail_errors", self._on_tail_errors)
            cfg.observe(
                "tracer_tail_capture_per_window", self._on_tail_budget
            )
        except ConfigError:
            pass  # custom schema without tracer options: stay disabled
        self._ring: deque[dict] = deque(maxlen=max(1, ring_size))
        #: the always-on flight ring: EVERY completed span (Span objects
        #: for our own, dicts for adopted foreign ones); the tail
        #: keep/drop decision and the crash black-box read from here
        self._flight: deque = deque(maxlen=max(1, flight_size))
        #: span name -> cached lat_us_* histogram key (hot-path string
        #: sanitation done once per distinct name)
        self._hist_keys: dict[str, str] = {}
        #: tail window state: slowest-N candidates + capture budgets
        self._win_start = time.time()
        self._win_seq = 0
        self._win_top: list = []
        #: mgr-pushed SLO capture predicates ([{name, min_ms}]) + the
        #: version that acked them over the report channel
        self._captures: list[dict] = []
        self._capture_ver = 0
        self._capture_hits: dict[str, int] = {}
        #: promotion outbox (trace_id -> meta) drained by the mgr
        #: report tick / the client relay, plus an LRU of already
        #: promoted ids so relays and re-decisions never double-ship
        self._promoted: dict[str, dict] = {}
        self._promoted_seen: dict[str, None] = {}
        #: latest promoted exemplar per latency histogram key
        self._exemplars: dict[str, dict] = {}
        #: span latency histograms (lat_us_<name>), adopted into the
        #: daemon's PerfCountersCollection so `perf dump` and the
        #: Prometheus exporter surface span timings as metrics
        self.perf = PerfCounters("tracer")
        self._export_fh = None

    # -- config observers (cached-flag refresh, the dout-gate idiom) ----------

    def _on_enabled(self, _n, v) -> None:
        self._on = bool(v)

    def _on_rate(self, _n, v) -> None:
        self._rate = float(v)

    def _on_export(self, _n, v) -> None:
        if self._export_fh is not None:
            try:
                self._export_fh.close()
            except OSError:
                pass
            self._export_fh = None
        self._export_path = v

    def _on_ring(self, _n, v) -> None:
        self._ring = deque(self._ring, maxlen=max(1, int(v)))

    def _on_flight_ring(self, _n, v) -> None:
        self._flight = deque(self._flight, maxlen=max(1, int(v)))

    def _on_tail_slow(self, _n, v) -> None:
        self._tail_slow_ms = float(v)

    def _on_tail_top(self, _n, v) -> None:
        self._tail_top_n = int(v)

    def _on_tail_window(self, _n, v) -> None:
        self._tail_window = float(v)

    def _on_tail_errors(self, _n, v) -> None:
        self._tail_errors = bool(v)

    def _on_tail_budget(self, _n, v) -> None:
        self._tail_budget = int(v)

    def _make_op_rate_cb(self, op_type: str):
        def cb(_n, v) -> None:
            rate = float(v)
            if rate < 0:
                self._op_rates.pop(op_type, None)  # back to inheriting
            else:
                self._op_rates[op_type] = rate

        return cb

    @property
    def enabled(self) -> bool:
        return self._on

    # -- span factories -------------------------------------------------------

    def start(self, name: str, tags: dict | None = None,
              start: float | None = None,
              op_type: str | None = None) -> Span | None:
        """Root span: begins a NEW trace. With the tracer on, a span is
        ALWAYS returned (the flight recorder records every op); the
        sample rate only decides the head `sampled` flag, i.e. whether
        the trace exports up front. `op_type` selects a
        `tracer_sample_rate_<type>` override when one is set (recovery
        reads at 100% while steady-state IO stays unsampled);
        unknown/unset types inherit the base rate. Roots are
        tail-eligible: finish() runs the keep/drop predicates. None
        only when the tracer is disabled."""
        if not self._on:
            return None
        rate = self._rate
        if op_type is not None and self._op_rates:
            rate = self._op_rates.get(op_type, rate)
        sampled = self._rng.random() < rate
        trace_id = f"{self._rng.getrandbits(64):016x}"
        return Span(self, name, trace_id, self._new_id(), None, tags,
                    start, sampled=sampled, tail=True)

    def child(self, name: str, tags: dict | None = None,
              start: float | None = None) -> Span | None:
        """Child of the task-local current context; None when disabled
        or untraced — interior sites never originate traces. Children
        inherit the parent's head decision (flight-only parents get
        flight-only children)."""
        if not self._on:
            return None
        ctx = _current.get()
        if ctx is None or not (ctx.sampled or ctx.flight):
            return None
        return Span(self, name, ctx.trace_id, self._new_id(),
                    ctx.span_id, tags, start, sampled=ctx.sampled)

    def join(self, wire: str | None, name: str, tags: dict | None = None,
             start: float | None = None, tail: bool = False) -> Span | None:
        """Continue a trace arriving over the wire (`Message.trace`).
        `tail=True` marks the joined span tail-eligible — the server-side
        op execution span (osd_op) runs its own keep/drop decision, so a
        server-slow op promotes even when the client never relays."""
        if not self._on:
            return None
        ctx = SpanContext.decode(wire)
        if ctx is None or not (ctx.sampled or ctx.flight):
            return None
        return Span(self, name, ctx.trace_id, self._new_id(),
                    ctx.span_id, tags, start, sampled=ctx.sampled,
                    tail=tail)

    def _new_id(self) -> str:
        return f"{self._rng.getrandbits(64):016x}"

    # -- current-context plumbing ---------------------------------------------

    def use(self, span: Span):
        """Make `span` the task-local parent for child()/fork sites;
        returns a token for release()."""
        return _current.set(span.context())

    def use_wire(self, wire: str | None):
        """Adopt a wire context as the task-local parent (sub-op
        handlers: their spans hang off the sender's fork span). Returns
        a token, or None when there is nothing to adopt."""
        if not self._on:
            return None
        ctx = SpanContext.decode(wire)
        if ctx is None or not (ctx.sampled or ctx.flight):
            return None
        return _current.set(ctx)

    def release(self, token) -> None:
        if token is not None:
            _current.reset(token)

    # -- completion / ring / export -------------------------------------------

    def _finished(self, span: Span) -> None:
        if span.sampled:
            self._ring.append(span.dump())
        # the always-on flight ring keeps the Span OBJECT — no dict is
        # built on the unsampled hot path; dumps materialize lazily at
        # promotion / black-box time
        self._flight.append(span)
        key = self._hist_keys.get(span.name)
        if key is None:
            key = "lat_us_" + "".join(
                c if c.isalnum() else "_" for c in span.name
            )
            self._hist_keys[span.name] = key
        if key not in self.perf._counters:
            self.perf.add_histogram(
                key, f"span {span.name!r} latency (µs, log2 buckets)"
            )
        us = max(1, int(span.duration * 1e6))
        self.perf.hinc(key, us)
        if span.sampled and self._export_path:
            self._export_jsonl(span)
        if span.tail:
            self._tail_decide(span, key, us)

    # -- tail sampling (keep/drop at op completion) ---------------------------

    def _tail_decide(self, span: Span, key: str, us: int) -> None:
        """Run the promotion predicates on a completed tail-eligible
        root: error tags first (an operator always wants those), then
        the slow threshold, then the mgr's SLO capture predicates
        (budgeted per window), else feed the slowest-N window heap."""
        now = span.end if span.end is not None else time.time()
        if now - self._win_start >= self._tail_window:
            self._flush_window(now)
        dur_ms = us / 1000.0
        tags = span.tags
        reason = None
        if self._tail_errors and (
            "error" in tags or "retried" in tags
            or "redirected" in tags or "aborted" in tags
        ):
            reason = "error"
        elif self._tail_slow_ms and dur_ms >= self._tail_slow_ms:
            reason = "slow"
        elif self._captures:
            for pred in self._captures:
                if dur_ms < float(pred.get("min_ms") or 0.0):
                    continue
                pname = pred.get("name", "slo")
                hits = self._capture_hits.get(pname, 0)
                if hits >= self._tail_budget:
                    continue
                self._capture_hits[pname] = hits + 1
                reason = f"slo:{pname}"
                break
        if reason is not None:
            self._promote_span(span, key, us, reason)
        elif self._tail_top_n:
            self._win_seq += 1
            item = (dur_ms, self._win_seq, span, key, us)
            if len(self._win_top) < self._tail_top_n:
                heapq.heappush(self._win_top, item)
            elif dur_ms > self._win_top[0][0]:
                heapq.heapreplace(self._win_top, item)

    def _flush_window(self, now: float) -> None:
        """Roll the tail window: promote the slowest-N candidates of
        the closing window and reset the per-predicate capture budgets."""
        self._win_start = now
        self._capture_hits.clear()
        top, self._win_top = self._win_top, []
        for _dur, _seq, span, key, us in top:
            self._promote_span(span, key, us, "slowest_n")

    def _promote_span(self, span: Span, key: str, us: int,
                      reason: str) -> None:
        if self._promote(span.trace_id, reason, root=span.dump()):
            self._exemplars[key] = {
                "trace_id": span.trace_id, "value": us,
                "ts": span.end if span.end is not None else time.time(),
            }

    def _promote(self, trace_id: str, reason: str,
                 root: dict | None = None) -> bool:
        if trace_id in self._promoted or trace_id in self._promoted_seen:
            return False
        self._promoted_seen[trace_id] = None
        while len(self._promoted_seen) > 512:
            self._promoted_seen.pop(next(iter(self._promoted_seen)))
        self._promoted[trace_id] = {
            "trace_id": trace_id, "reason": reason,
            "promoted_at": time.time(), "root": root,
        }
        while len(self._promoted) > 64:  # outbox bound: oldest drop
            self._promoted.pop(next(iter(self._promoted)))
        if "tail_promoted" not in self.perf._counters:
            self.perf.add_u64_counter(
                "tail_promoted",
                "traces promoted by the tail sampler",
            )
        self.perf.inc("tail_promoted")
        return True

    def promote(self, trace_id: str, reason: str = "relay",
                root: dict | None = None) -> bool:
        """Promote a trace by id — the relay path: a client that kept
        its trace ships the decision (trace_report) to the primary OSD,
        which promotes the same trace locally so its own flight spans —
        and the adopted client spans — ride the next mgr report. Also
        records an exemplar from OUR slowest tail-eligible flight span
        of the trace, so the server-side latency histogram carries the
        drill-down id too."""
        if not self._on or not trace_id:
            return False
        if not self._promote(trace_id, reason, root=root):
            return False
        best: Span | None = None
        for s in self._flight:
            if (
                isinstance(s, Span) and s.trace_id == trace_id
                and s.tail and (best is None or s.duration > best.duration)
            ):
                best = s
        if best is not None:
            key = self._hist_keys.get(best.name)
            if key is not None:
                self._exemplars[key] = {
                    "trace_id": trace_id,
                    "value": max(1, int(best.duration * 1e6)),
                    "ts": best.end if best.end is not None
                    else time.time(),
                }
        return True

    def flight_spans_of(self, trace_id: str) -> list[dict]:
        """Every flight-ring span of one trace as dump dicts, oldest
        first, deduped by span_id (relays may have adopted copies)."""
        out: list[dict] = []
        seen: set[str] = set()
        for s in self._flight:
            d = s.dump() if isinstance(s, Span) else s
            if d.get("trace_id") != trace_id or d["span_id"] in seen:
                continue
            seen.add(d["span_id"])
            out.append(d)
        out.sort(key=lambda d: d.get("start") or 0.0)
        return out

    def flight_has(self, trace_id: str) -> bool:
        """Does the flight ring still hold any span of this trace?
        (dump_historic_ops cross-links entries while it does.)"""
        return any(
            (s.trace_id if isinstance(s, Span) else s.get("trace_id"))
            == trace_id
            for s in self._flight
        )

    def adopt_flight(self, spans: list[dict]) -> None:
        """Accept foreign finished spans into the FLIGHT ring (the
        promotion relay: a client's unsampled spans must be present
        when its promoted trace is gathered) without touching the
        sampled ring — an unpromoted flight trace still leaves nothing
        behind in `dump_tracing`."""
        if not self._on:
            return
        for s in spans:
            if isinstance(s, dict) and "trace_id" in s and "span_id" in s:
                self._flight.append(s)

    def take_promoted(self, trace_id: str) -> dict | None:
        """Pop ONE promoted entry with its flight spans — the client
        relay path (no mgr report loop drains a client's tracer)."""
        meta = self._promoted.pop(trace_id, None)
        if meta is None:
            return None
        return {**meta, "spans": self._gathered(meta)}

    def drain_promoted(self) -> list[dict]:
        """Collect the promotion outbox (the daemon's mgr report tick):
        each entry carries every flight-ring span of its trace, gathered
        NOW so stragglers that finished after the keep decision are
        included. Also lazily rolls the tail window, so slowest-N
        promotion happens even when traffic stopped mid-window."""
        if self._win_top or self._capture_hits:
            now = time.time()
            if now - self._win_start >= self._tail_window:
                self._flush_window(now)
        if not self._promoted:
            return []
        out = [
            {**meta, "spans": self._gathered(meta)}
            for meta in self._promoted.values()
        ]
        self._promoted = {}
        return out

    def _gathered(self, meta: dict) -> list[dict]:
        spans = self.flight_spans_of(meta["trace_id"])
        root = meta.get("root")
        if root is not None and all(
            s["span_id"] != root["span_id"] for s in spans
        ):
            spans.insert(0, root)  # ring already evicted the root
        return spans

    def exemplars(self) -> dict[str, dict]:
        """Latest promoted-trace exemplar per latency histogram key
        ({trace_id, value µs, ts}) — ships on the mgr report and rides
        the Prometheus histograms as OpenMetrics exemplars."""
        return {k: dict(v) for k, v in self._exemplars.items()}

    def set_capture_predicates(self, preds, version) -> None:
        """Adopt mgr-pushed SLO capture predicates ([{name, min_ms}]):
        while a rule is in violation the mgr asks daemons to keep up to
        tracer_tail_capture_per_window matching traces per window."""
        self._captures = [
            p for p in (preds or [])
            if isinstance(p, dict) and p.get("name")
        ]
        self._capture_hits.clear()
        self._capture_ver = int(version)

    @property
    def capture_version(self) -> int:
        return self._capture_ver

    def flight_snapshot(self) -> list[dict]:
        """The crash black-box view: every flight-ring span as a dump
        dict, oldest first (finished spans only — in-flight ops come
        from the OpTracker's side of the black box)."""
        return [
            s.dump() if isinstance(s, Span) else s for s in self._flight
        ]

    def _export_jsonl(self, span: Span) -> None:
        try:
            if self._export_fh is None:
                # O_APPEND: many daemons may share one collector file
                self._export_fh = open(self._export_path, "a")
            self._export_fh.write(json.dumps(span.to_jaeger()) + "\n")
            self._export_fh.flush()
        except OSError:
            self._export_path = ""  # unwritable path: disable, not crash

    def adopt(self, spans: list[dict]) -> None:
        """Accept foreign finished spans into the ring — the Jaeger
        collector role: clients report their half of a trace to the
        primary OSD so `dump_tracing` there holds the complete tree."""
        if not self._on:
            return
        for s in spans:
            if isinstance(s, dict) and "trace_id" in s and "span_id" in s:
                self._ring.append(s)

    def spans_of(self, trace_id: str) -> list[dict]:
        return [s for s in self._ring if s["trace_id"] == trace_id]

    def dump_tracing(self, drain: bool = True) -> dict:
        """The `dump_tracing` admin command: completed spans grouped by
        trace, oldest span first within each; drains the ring."""
        spans = list(self._ring)
        if drain:
            self._ring.clear()
        traces: dict[str, list[dict]] = {}
        for s in spans:
            traces.setdefault(s["trace_id"], []).append(s)
        return {
            "num_traces": len(traces),
            "num_spans": len(spans),
            "traces": [
                {"trace_id": tid,
                 "spans": sorted(ss, key=lambda s: s["start"])}
                for tid, ss in traces.items()
            ],
        }

    def close(self) -> None:
        if self._export_fh is not None:
            try:
                self._export_fh.close()
            except OSError:
                pass
            self._export_fh = None


#: export path env override helper for tools; kept trivial on purpose
def default_tracer(service: str) -> Tracer:
    return Tracer(service)
