"""Distributed tracing — the reference's src/common/tracer + blkin role.

A Dapper-style tracer (Sigelman et al. 2010): every sampled request gets
a trace id; each timed unit of work is a span (span_id, parent_id) with
tags and timestamped events; the (trace_id, span_id, sampled) context
travels across daemons as an optional field on the wire `Message`, so a
span started in the Rados client continues through the messenger, the
OSD op queue, the encode service, and the object store, and forks a
child span per replica/EC-shard sub-op — the same shape Ceph gets from
jaeger-tracing wired through ProtocolV2 (src/common/tracer.h,
src/msg/async/ProtocolV2.cc encode_trace).

Pieces:

  * `SpanContext` — the wire form, one compact string
    "<trace_id>:<span_id>:<flags>" (flags bit0 = sampled), carried by
    `Message.trace` (msg/frames.py).
  * `Span` — timed unit with tags + events; `finish()` lands it in the
    tracer's bounded completed-span ring, feeds a per-span-name
    PerfCounters latency histogram (picked up by `perf dump` and the
    Prometheus exporter), and appends one Jaeger-compatible JSON line
    to `tracer_export_path` when set (tools/trace_tool.py renders it).
  * `Tracer` — per-daemon factory. Config knobs (central schema):
    `tracer_enabled`, `tracer_sample_rate`, `tracer_ring_size`,
    `tracer_export_path`, plus per-op-type `tracer_sample_rate_<type>`
    root-rate overrides (-1 inherits; recovery reads can run at 100%
    while steady-state IO stays sampled); all observed at runtime like
    debug levels.

Cost discipline (the dout-gate idiom, common/log.py): the enabled flag
is CACHED and checked first in every factory method, so a disabled
tracer costs one flag check per span site and allocates nothing:

    if (sp := tracer.child("blockstore_read")) is not None:
        sp.set_tag("cache", "hit")
        sp.finish()

The task-local current context (`use`/`use_wire`) rides a contextvar so
awaits and `create_task` propagate it without plumbing; `child()`
returns None when no sampled context is active — interior span sites
never start traces of their own.
"""

from __future__ import annotations

import contextvars
import json
import os
import random
import time
from collections import deque
from typing import Any

from ceph_tpu.common.config import Config, ConfigError
from ceph_tpu.common.config import config as global_config
from ceph_tpu.common.perf_counters import PerfCounters

#: the active span context for the op executing in this task/thread
_current: "contextvars.ContextVar[SpanContext | None]" = (
    contextvars.ContextVar("ceph_tracer_ctx", default=None)
)


def current_context() -> "SpanContext | None":
    return _current.get()


def current_trace_id() -> str | None:
    """Trace id of the active context, for log correlation (the
    `trace=<id>` dout prefix); None when untraced."""
    ctx = _current.get()
    return None if ctx is None else ctx.trace_id


#: op types with a `tracer_sample_rate_<type>` schema entry — keeps the
#: cached-rate table in lockstep with common/config.py
_OP_RATE_TYPES = (
    "read", "write", "ops", "delete", "call", "stat", "recovery",
    "command", "balancer",
)


class SpanContext:
    """What propagates: ids + the sampled decision, never payload."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def encode(self) -> str:
        return f"{self.trace_id}:{self.span_id}:{1 if self.sampled else 0}"

    @staticmethod
    def decode(raw: str | None) -> "SpanContext | None":
        if not raw:
            return None
        parts = raw.split(":")
        if len(parts) != 3 or not parts[0] or not parts[1]:
            return None
        return SpanContext(parts[0], parts[1], parts[2] == "1")


class Span:
    __slots__ = (
        "_tracer", "trace_id", "span_id", "parent_id", "name",
        "service", "start", "end", "tags", "events",
    )

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: str | None,
                 tags: dict | None, start: float | None):
        self._tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.service = tracer.service
        self.start = time.time() if start is None else start
        self.end: float | None = None
        self.tags: dict[str, Any] = dict(tags) if tags else {}
        self.events: list[tuple[float, str]] = []

    # -- recording ------------------------------------------------------------

    def log(self, event: str) -> None:
        self.events.append((time.time(), event))

    def set_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, True)

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None else time.time()) - self.start

    def finish(self) -> None:
        """Close the span (idempotent): ring + perf histogram + export."""
        if self.end is not None:
            return
        self.end = time.time()
        self._tracer._finished(self)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, *exc) -> bool:
        self.finish()
        return False

    # -- serialization --------------------------------------------------------

    def dump(self) -> dict:
        """The admin-surface (`dump_tracing`) form."""
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "service": self.service,
            "start": self.start,
            "duration": self.duration,
            "tags": {k: _jsonable(v) for k, v in self.tags.items()},
            "events": [
                {"ts": ts, "event": ev} for ts, ev in self.events
            ],
        }

    def to_jaeger(self) -> dict:
        """One span in Jaeger JSON (the jaeger-ui import format; µs)."""
        refs = []
        if self.parent_id:
            refs.append({
                "refType": "CHILD_OF",
                "traceID": self.trace_id,
                "spanID": self.parent_id,
            })
        return {
            "traceID": self.trace_id,
            "spanID": self.span_id,
            "operationName": self.name,
            "references": refs,
            "startTime": int(self.start * 1e6),
            "duration": int(self.duration * 1e6),
            "tags": [
                {"key": k, "type": "string", "value": str(v)}
                for k, v in self.tags.items()
            ],
            "logs": [
                {"timestamp": int(ts * 1e6),
                 "fields": [{"key": "event", "value": ev}]}
                for ts, ev in self.events
            ],
            "process": {"serviceName": self.service, "tags": []},
        }


def _jsonable(v):
    return v if isinstance(v, (str, int, float, bool, type(None))) else str(v)


class Tracer:
    """Per-daemon span factory + bounded completed-span ring."""

    def __init__(self, service: str, config: Config | None = None):
        self.service = service
        cfg = config if config is not None else global_config
        self._rng = random.Random()
        self._on = False
        self._rate = 1.0
        #: per-op-type sample-rate overrides (tracer_sample_rate_<type>):
        #: only types with a non-negative override are present, so the
        #: common case stays one dict-get against an empty dict
        self._op_rates: dict[str, float] = {}
        self._export_path = ""
        ring_size = 1024
        try:
            self._on = bool(cfg.get("tracer_enabled"))
            self._rate = float(cfg.get("tracer_sample_rate"))
            self._export_path = cfg.get("tracer_export_path")
            ring_size = int(cfg.get("tracer_ring_size"))
            cfg.observe("tracer_enabled", self._on_enabled)
            cfg.observe("tracer_sample_rate", self._on_rate)
            cfg.observe("tracer_export_path", self._on_export)
            cfg.observe("tracer_ring_size", self._on_ring)
            for t in _OP_RATE_TYPES:
                name = f"tracer_sample_rate_{t}"
                try:
                    rate = float(cfg.get(name))
                except ConfigError:
                    continue  # older/custom schema without this type
                if rate >= 0:
                    self._op_rates[t] = rate
                cfg.observe(name, self._make_op_rate_cb(t))
        except ConfigError:
            pass  # custom schema without tracer options: stay disabled
        self._ring: deque[dict] = deque(maxlen=max(1, ring_size))
        #: span latency histograms (lat_us_<name>), adopted into the
        #: daemon's PerfCountersCollection so `perf dump` and the
        #: Prometheus exporter surface span timings as metrics
        self.perf = PerfCounters("tracer")
        self._export_fh = None

    # -- config observers (cached-flag refresh, the dout-gate idiom) ----------

    def _on_enabled(self, _n, v) -> None:
        self._on = bool(v)

    def _on_rate(self, _n, v) -> None:
        self._rate = float(v)

    def _on_export(self, _n, v) -> None:
        if self._export_fh is not None:
            try:
                self._export_fh.close()
            except OSError:
                pass
            self._export_fh = None
        self._export_path = v

    def _on_ring(self, _n, v) -> None:
        self._ring = deque(self._ring, maxlen=max(1, int(v)))

    def _make_op_rate_cb(self, op_type: str):
        def cb(_n, v) -> None:
            rate = float(v)
            if rate < 0:
                self._op_rates.pop(op_type, None)  # back to inheriting
            else:
                self._op_rates[op_type] = rate

        return cb

    @property
    def enabled(self) -> bool:
        return self._on

    # -- span factories -------------------------------------------------------

    def start(self, name: str, tags: dict | None = None,
              start: float | None = None,
              op_type: str | None = None) -> Span | None:
        """Root span: begins a NEW trace, subject to the sample rate.
        `op_type` selects a `tracer_sample_rate_<type>` override when one
        is set (recovery reads at 100% while steady-state IO stays
        sampled); unknown/unset types inherit the base rate. None when
        disabled or not sampled — the whole trace then costs nothing
        anywhere downstream (the context never propagates)."""
        if not self._on:
            return None
        rate = self._rate
        if op_type is not None and self._op_rates:
            rate = self._op_rates.get(op_type, rate)
        if self._rng.random() >= rate:
            return None
        trace_id = f"{self._rng.getrandbits(64):016x}"
        return Span(self, name, trace_id, self._new_id(), None, tags, start)

    def child(self, name: str, tags: dict | None = None,
              start: float | None = None) -> Span | None:
        """Child of the task-local current context; None when disabled
        or untraced — interior sites never originate traces."""
        if not self._on:
            return None
        ctx = _current.get()
        if ctx is None or not ctx.sampled:
            return None
        return Span(self, name, ctx.trace_id, self._new_id(),
                    ctx.span_id, tags, start)

    def join(self, wire: str | None, name: str, tags: dict | None = None,
             start: float | None = None) -> Span | None:
        """Continue a trace arriving over the wire (`Message.trace`)."""
        if not self._on:
            return None
        ctx = SpanContext.decode(wire)
        if ctx is None or not ctx.sampled:
            return None
        return Span(self, name, ctx.trace_id, self._new_id(),
                    ctx.span_id, tags, start)

    def _new_id(self) -> str:
        return f"{self._rng.getrandbits(64):016x}"

    # -- current-context plumbing ---------------------------------------------

    def use(self, span: Span):
        """Make `span` the task-local parent for child()/fork sites;
        returns a token for release()."""
        return _current.set(span.context())

    def use_wire(self, wire: str | None):
        """Adopt a wire context as the task-local parent (sub-op
        handlers: their spans hang off the sender's fork span). Returns
        a token, or None when there is nothing to adopt."""
        if not self._on:
            return None
        ctx = SpanContext.decode(wire)
        if ctx is None or not ctx.sampled:
            return None
        return _current.set(ctx)

    def release(self, token) -> None:
        if token is not None:
            _current.reset(token)

    # -- completion / ring / export -------------------------------------------

    def _finished(self, span: Span) -> None:
        self._ring.append(span.dump())
        key = "lat_us_" + "".join(
            c if c.isalnum() else "_" for c in span.name
        )
        if key not in self.perf._counters:
            self.perf.add_histogram(
                key, f"span {span.name!r} latency (µs, log2 buckets)"
            )
        self.perf.hinc(key, max(1, int(span.duration * 1e6)))
        if self._export_path:
            self._export_jsonl(span)

    def _export_jsonl(self, span: Span) -> None:
        try:
            if self._export_fh is None:
                # O_APPEND: many daemons may share one collector file
                self._export_fh = open(self._export_path, "a")
            self._export_fh.write(json.dumps(span.to_jaeger()) + "\n")
            self._export_fh.flush()
        except OSError:
            self._export_path = ""  # unwritable path: disable, not crash

    def adopt(self, spans: list[dict]) -> None:
        """Accept foreign finished spans into the ring — the Jaeger
        collector role: clients report their half of a trace to the
        primary OSD so `dump_tracing` there holds the complete tree."""
        if not self._on:
            return
        for s in spans:
            if isinstance(s, dict) and "trace_id" in s and "span_id" in s:
                self._ring.append(s)

    def spans_of(self, trace_id: str) -> list[dict]:
        return [s for s in self._ring if s["trace_id"] == trace_id]

    def dump_tracing(self, drain: bool = True) -> dict:
        """The `dump_tracing` admin command: completed spans grouped by
        trace, oldest span first within each; drains the ring."""
        spans = list(self._ring)
        if drain:
            self._ring.clear()
        traces: dict[str, list[dict]] = {}
        for s in spans:
            traces.setdefault(s["trace_id"], []).append(s)
        return {
            "num_traces": len(traces),
            "num_spans": len(spans),
            "traces": [
                {"trace_id": tid,
                 "spans": sorted(ss, key=lambda s: s["start"])}
                for tid, ss in traces.items()
            ],
        }

    def close(self) -> None:
        if self._export_fh is not None:
            try:
                self._export_fh.close()
            except OSError:
                pass
            self._export_fh = None


#: export path env override helper for tools; kept trivial on purpose
def default_tracer(service: str) -> Tracer:
    return Tracer(service)
