"""KeyValueDB: the KV abstraction under the object store and monitor.

The reference routes all small persistent state through a `KeyValueDB`
interface (src/kv/KeyValueDB.h) with RocksDB behind it
(src/kv/RocksDBStore.cc): atomic write batches, prefix-scoped keys, ordered
iteration. BlueStore keeps its metadata there; the monitor's entire state is
one (MonitorDBStore over the same interface).

Two backends here:

  * `MemDB` — dict-backed (the reference ships one too, src/kv/MemDB.cc);
    used by tests and by in-memory object stores.
  * `FileDB` — durable single-file store: a snapshot plus an append-only
    write-ahead log of denc-encoded batches, each protected by crc32c and
    applied atomically on replay (a truncated/corrupt tail — the torn-write
    crash case — is discarded whole, never half-applied). `compact()` folds
    the log into a new snapshot via write-to-temp + rename. This is the WAL
    discipline RocksDB gives the reference, sized for our state (maps,
    object metadata, mon store), not an LSM tree — scans are served from the
    in-memory table.

Keys are (prefix, key) pairs of bytes, matching the reference's
prefix-per-subsystem convention ("osdmap", "pgmeta", ...).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from ceph_tpu.common.crc import ceph_crc32c
from ceph_tpu.common.encoding import DecodeError, Decoder, Encoder


class KVTransaction:
    """An atomic batch (KeyValueDB::Transaction): ops apply all-or-nothing."""

    def __init__(self) -> None:
        #: (op, prefix, key, value) with op in {"set", "rm", "rm_prefix",
        #: "setr"}
        self.ops: list[tuple[str, bytes, bytes, bytes]] = []

    def set(self, prefix: bytes, key: bytes, value: bytes) -> "KVTransaction":
        self.ops.append(("set", bytes(prefix), bytes(key), bytes(value)))
        return self

    def set_range(
        self, prefix: bytes, key: bytes, off: int, value: bytes
    ) -> "KVTransaction":
        """Patch `value` into the row at byte offset `off` (zero-extending a
        shorter row). The WAL records only the delta, which is what makes a
        sub-stripe EC overwrite's store traffic proportional to the bytes
        touched instead of the object size (RocksDB merge-operator role)."""
        self.ops.append((
            "setr", bytes(prefix), bytes(key),
            Encoder().u64(off).blob(bytes(value)).bytes(),
        ))
        return self

    def rm(self, prefix: bytes, key: bytes) -> "KVTransaction":
        self.ops.append(("rm", bytes(prefix), bytes(key), b""))
        return self

    def rm_prefix(self, prefix: bytes) -> "KVTransaction":
        self.ops.append(("rm_prefix", bytes(prefix), b"", b""))
        return self

    def encode(self) -> bytes:
        def one(e, op):
            kind, prefix, key, value = op
            e.string(kind).blob(prefix).blob(key).blob(value)

        return Encoder().list(self.ops, one).bytes()

    @staticmethod
    def decode(raw: bytes) -> "KVTransaction":
        t = KVTransaction()

        def one(d):
            return (d.string(), d.blob(), d.blob(), d.blob())

        t.ops = Decoder(raw).list(one)
        return t


class KeyValueDB:
    """Interface: submit_transaction is the only mutator."""

    def get(self, prefix: bytes, key: bytes) -> bytes | None:
        raise NotImplementedError

    def iterate(self, prefix: bytes):
        """Yield (key, value) in key order."""
        raise NotImplementedError

    def submit_transaction(self, txn: KVTransaction) -> None:
        raise NotImplementedError

    # -- shared in-memory application ----------------------------------------

    def _apply(self, table: dict, txn: KVTransaction) -> None:
        for kind, prefix, key, value in txn.ops:
            if kind == "set":
                table[(prefix, key)] = value
            elif kind == "setr":
                d = Decoder(value)
                off, data = d.u64(), d.blob()
                cur = table.get((prefix, key), b"")
                if len(cur) < off + len(data):
                    cur = cur + b"\x00" * (off + len(data) - len(cur))
                table[(prefix, key)] = (
                    cur[:off] + data + cur[off + len(data):]
                )
            elif kind == "rm":
                table.pop((prefix, key), None)
            elif kind == "rm_prefix":
                for k in [k for k in table if k[0] == prefix]:
                    del table[k]
            else:
                raise ValueError(f"unknown kv op {kind!r}")


@dataclass
class MemDB(KeyValueDB):
    table: dict = field(default_factory=dict)
    #: bytes a durable backend would have logged for the same batches —
    #: len(encode()) per batch, so tests can assert store-traffic scaling
    #: identically against MemDB and FileDB
    bytes_logged: int = 0

    def get(self, prefix: bytes, key: bytes) -> bytes | None:
        return self.table.get((bytes(prefix), bytes(key)))

    def iterate(self, prefix: bytes):
        prefix = bytes(prefix)
        for (p, k) in sorted(k for k in self.table if k[0] == prefix):
            yield (p, k), self.table[(p, k)]

    def submit_transaction(self, txn: KVTransaction) -> None:
        self.bytes_logged += len(txn.encode())
        self._apply(self.table, txn)


class FileDB(KeyValueDB):
    """Snapshot + crc-framed WAL in `path/`; see module docstring."""

    SNAP = "snapshot"
    WAL = "wal"

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.table: dict = {}
        self.bytes_logged = 0
        self._load()
        self._wal = open(os.path.join(path, self.WAL), "ab")

    # -- recovery -------------------------------------------------------------

    def _load(self) -> None:
        snap_path = os.path.join(self.path, self.SNAP)
        if os.path.exists(snap_path):
            with open(snap_path, "rb") as f:
                raw = f.read()
            d = Decoder(raw)

            def entry(dd):
                return (dd.blob(), dd.blob()), dd.blob()

            for k, v in d.list(entry):
                self.table[k] = v
        wal_path = os.path.join(self.path, self.WAL)
        if os.path.exists(wal_path):
            with open(wal_path, "rb") as f:
                raw = f.read()
            off = 0
            while off < len(raw):
                try:
                    d = Decoder(raw, off)
                    body = d.blob()
                    crc = d.u32()
                except DecodeError:
                    break  # torn tail: discard
                if ceph_crc32c(0xFFFFFFFF, body) != crc:
                    break  # corrupt tail: discard whole record
                self._apply(self.table, KVTransaction.decode(body))
                off = d.offset

    # -- api ------------------------------------------------------------------

    def get(self, prefix: bytes, key: bytes) -> bytes | None:
        return self.table.get((bytes(prefix), bytes(key)))

    def iterate(self, prefix: bytes):
        prefix = bytes(prefix)
        for (p, k) in sorted(k for k in self.table if k[0] == prefix):
            yield (p, k), self.table[(p, k)]

    def submit_transaction(self, txn: KVTransaction) -> None:
        body = txn.encode()
        rec = (
            Encoder().blob(body).u32(ceph_crc32c(0xFFFFFFFF, body)).bytes()
        )
        self._wal.write(rec)
        self._wal.flush()
        os.fsync(self._wal.fileno())
        self.bytes_logged += len(body)  # same measure as MemDB
        self._apply(self.table, txn)

    def compact(self) -> None:
        """Fold the WAL into a fresh snapshot (temp + rename + truncate)."""
        snap_path = os.path.join(self.path, self.SNAP)
        tmp = snap_path + ".tmp"

        def entry(e, item):
            (prefix, key), value = item
            e.blob(prefix).blob(key).blob(value)

        raw = Encoder().list(sorted(self.table.items()), entry).bytes()
        with open(tmp, "wb") as f:
            f.write(raw)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, snap_path)
        # fsync the parent directory: rename() durability is a property
        # of the DIRECTORY entry, not the file — without this a power
        # loss can revert the snapshot to the old (or no) inode even
        # though the new bytes were fsynced (the classic rename-without-
        # dirsync hole; process death alone never hits it)
        dirfd = os.open(self.path, os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
        self._wal.close()
        self._wal = open(os.path.join(self.path, self.WAL), "wb")
        self._wal.flush()
        os.fsync(self._wal.fileno())

    def close(self) -> None:
        self._wal.close()
