"""Seeded wire-fault schedules (the chaos harness's decision engine).

The PR 5 device-fault pattern (`blockstore_inject_*`: 1-in-N rates,
one cached flag check when disarmed) applied to the wire: a
`ms_inject_chaos_schedule` string compiles into per-(src, dst) fault
streams that the messenger consults once per outgoing corked frame run.
Each peer pair draws from its OWN `random.Random`, seeded from
(`ms_inject_chaos_seed`, src, dst) — so the decision sequence a pair
sees depends only on how many frames IT sent, never on global
interleaving, and a run replays bit-identically from the seed.

Schedule grammar (';'-separated rules; entity names are comma-separated
fnmatch globs like ``osd.1``, ``osd.*``, ``*``):

    drop:SRC>DST[:prob]             sever the connection (frame lost;
                                    lossless sessions replay on
                                    reconnect, lossy sessions lose it)
    delay:SRC>DST[:prob[:max_s]]    stall the write up to max_s seconds
    dup:SRC>DST[:prob]              send the frame run twice (receiver
                                    seq-dedup must absorb it)
    partition:A|B                   every A->B AND B->A send fails
    partition:A>B                   one-way: A cannot reach B, B still
                                    reaches A (asymmetric partition)

Probabilities default to 1.0 (drop/dup/partition) and delays to 50 ms.
Multiple matching rules are evaluated in schedule order per decision;
the first that fires wins (partition is checked first — it is not
probabilistic).
"""

from __future__ import annotations

import random
import zlib
from dataclasses import dataclass
from fnmatch import fnmatchcase

__all__ = ["FaultRule", "WireFaults", "parse_schedule"]

#: decision kinds returned by _PairFaults.next_action()
DROP = "drop"
DELAY = "delay"
DUP = "dup"

_DEFAULT_DELAY_MAX = 0.05


@dataclass(frozen=True)
class FaultRule:
    kind: str  # drop | delay | dup | partition
    src: tuple[str, ...]  # glob patterns
    dst: tuple[str, ...]
    prob: float = 1.0
    param: float = _DEFAULT_DELAY_MAX  # delay: max seconds
    both_ways: bool = False  # partition:A|B

    def matches(self, src: str, dst: str) -> bool:
        if _match(self.src, src) and _match(self.dst, dst):
            return True
        return self.both_ways and (
            _match(self.src, dst) and _match(self.dst, src)
        )


def _match(patterns: tuple[str, ...], name: str) -> bool:
    return any(fnmatchcase(name, p) for p in patterns)


def _globs(spec: str) -> tuple[str, ...]:
    out = tuple(s.strip() for s in spec.split(",") if s.strip())
    if not out:
        raise ValueError(f"empty entity spec in {spec!r}")
    return out


def parse_schedule(text: str) -> list[FaultRule]:
    """Compile a schedule string; raises ValueError on bad grammar (a
    typo'd schedule must fail loudly at arm time, not silently inject
    nothing)."""
    rules: list[FaultRule] = []
    for raw in (text or "").split(";"):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        kind = parts[0].strip()
        if kind == "partition":
            if len(parts) != 2:
                raise ValueError(f"partition takes no args: {raw!r}")
            spec = parts[1]
            if "|" in spec:
                a, b = spec.split("|", 1)
                rules.append(FaultRule(
                    "partition", _globs(a), _globs(b), both_ways=True,
                ))
            elif ">" in spec:
                a, b = spec.split(">", 1)
                rules.append(
                    FaultRule("partition", _globs(a), _globs(b))
                )
            else:
                raise ValueError(
                    f"partition needs A|B or A>B: {raw!r}"
                )
            continue
        if kind not in (DROP, DELAY, DUP):
            raise ValueError(f"unknown fault kind {kind!r} in {raw!r}")
        if len(parts) < 2 or ">" not in parts[1]:
            raise ValueError(f"{kind} needs SRC>DST: {raw!r}")
        a, b = parts[1].split(">", 1)
        prob = float(parts[2]) if len(parts) > 2 and parts[2] else 1.0
        if not 0.0 <= prob <= 1.0:
            raise ValueError(f"probability out of [0,1]: {raw!r}")
        param = (
            float(parts[3]) if len(parts) > 3 and parts[3]
            else _DEFAULT_DELAY_MAX
        )
        rules.append(
            FaultRule(kind, _globs(a), _globs(b), prob, param)
        )
    return rules


class _PairFaults:
    """The fault stream one (src, dst) direction draws from: its own
    RNG keyed by (seed, src, dst), so decisions replay per pair."""

    __slots__ = ("rules", "rng", "decisions")

    def __init__(self, rules: list[FaultRule], seed: int,
                 src: str, dst: str):
        self.rules = rules
        key = zlib.crc32(f"{src}>{dst}".encode()) & 0xFFFFFFFF
        self.rng = random.Random((seed << 32) ^ key)
        self.decisions = 0  # frames judged (replay/debug surface)

    def next_action(self):
        """Fault for the next outgoing frame run, or None. One of:
        ("drop",) | ("delay", seconds) | ("dup",)."""
        self.decisions += 1
        for r in self.rules:
            if r.kind == "partition":
                return (DROP,)
            # one draw per rule per frame keeps streams aligned with
            # the schedule (rules consume randomness deterministically)
            roll = self.rng.random()
            if roll >= r.prob:
                continue
            if r.kind == DROP:
                return (DROP,)
            if r.kind == DUP:
                return (DUP,)
            return (DELAY, self.rng.uniform(0.0, r.param))
        return None


class WireFaults:
    """Compiled schedule + per-pair stream cache. Built once per
    messenger when `ms_inject_chaos_schedule` is non-empty; the
    messenger keeps None when disarmed so the hot path pays one
    attribute check."""

    def __init__(self, schedule: str, seed: int = 0):
        self.schedule = schedule
        self.seed = int(seed)
        self.rules = parse_schedule(schedule)
        self._pairs: dict[tuple[str, str], _PairFaults | None] = {}

    def pair(self, src: str, dst: str) -> _PairFaults | None:
        """The fault stream for src->dst sends, or None when no rule
        matches the pair (cached — the common no-match case costs one
        dict hit after the first send)."""
        key = (src, dst)
        got = self._pairs.get(key, False)
        if got is not False:
            return got
        matched = [r for r in self.rules if r.matches(src, dst)]
        pf = (
            _PairFaults(matched, self.seed, src, dst)
            if matched else None
        )
        self._pairs[key] = pf
        return pf
