"""Common runtime (the L0 layer analogue: src/common in the reference):

  hash          — ceph_str_hash_rjenkins (object name -> ps)
  config        — typed option schema + layered resolution + observers
                  (options.cc / config_proxy.h / config_obs.h)
  perf_counters — PerfCounters blocks with perf-dump JSON (perf_counters.h)
  admin         — admin command hub + TrackedOp/OpTracker op timeline
                  (admin_socket.cc, TrackedOp.h)
  crc           — ceph_crc32c (crc32c.h / sctp_crc32.c)
  compressor    — compression plugin registry (src/compressor/)
  throttle      — counting backpressure (src/common/Throttle)
  log           — dout-style subsystem logging + recent ring (src/log)
"""
