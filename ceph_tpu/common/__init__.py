"""Common runtime utilities (the L0 layer analogue: src/common in the
reference). Grows config/perf-counter subsystems as the framework widens."""
