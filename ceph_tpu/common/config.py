"""Typed config schema + layered resolution — the reference's option system.

Re-expresses /root/reference/src/common/options.cc (1535 `Option(...)` schema
entries with type/level/default/min-max/description/see_also) and
config_proxy.h/config_obs.h:

  * `Option` — one typed schema entry (TYPE_*, LEVEL_basic/advanced/dev,
    default, optional min/max, description, see_also);
  * `SCHEMA` — the framework's option inventory: every knob a subsystem
    actually reads lives here, so `config show` is the source of truth
    (the reference's EC/CRUSH/injection-relevant entries are mirrored by
    name: erasure_code_dir options.cc:533, osd_erasure_code_plugins 2519,
    osd_pool_default_erasure_code_profile, ms_inject_* 1044-1066,
    heartbeat_inject_failure 822);
  * `Config` — layered resolution: compiled default < config file values <
    environment (CEPH_TPU_<NAME>) < runtime `set` (mon/admin-socket tier);
    typed parsing + range validation on every write;
  * observers — `md_config_obs_t`-style callbacks fired on runtime changes
    (config_obs.h), keyed by option name.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Callable

TYPE_UINT = "uint"
TYPE_INT = "int"
TYPE_STR = "str"
TYPE_FLOAT = "float"
TYPE_BOOL = "bool"

LEVEL_BASIC = "basic"
LEVEL_ADVANCED = "advanced"
LEVEL_DEV = "dev"


class ConfigError(ValueError):
    pass


@dataclass(frozen=True)
class Option:
    name: str
    type: str
    level: str
    default: Any
    description: str = ""
    min: float | None = None
    max: float | None = None
    see_also: tuple[str, ...] = ()

    def parse(self, value: Any) -> Any:
        try:
            if self.type == TYPE_BOOL:
                if isinstance(value, str):
                    if value.lower() in ("true", "1", "yes", "on"):
                        return True
                    if value.lower() in ("false", "0", "no", "off"):
                        return False
                    raise ConfigError(f"{self.name}: bad bool {value!r}")
                return bool(value)
            if self.type in (TYPE_UINT, TYPE_INT):
                v = int(value)
                if self.type == TYPE_UINT and v < 0:
                    raise ConfigError(f"{self.name}: must be >= 0")
            elif self.type == TYPE_FLOAT:
                v = float(value)
            else:
                return str(value)
        except (TypeError, ValueError) as e:
            raise ConfigError(f"{self.name}: {e}") from None
        if self.min is not None and v < self.min:
            raise ConfigError(f"{self.name}: {v} < min {self.min}")
        if self.max is not None and v > self.max:
            raise ConfigError(f"{self.name}: {v} > max {self.max}")
        return v


def _opt(name, type_, level, default, desc="", **kw):
    return Option(name, type_, level, default, desc, **kw)


#: the option inventory (names shared with the reference where the concept
#: maps 1:1, so operators can carry their mental model over)
SCHEMA: dict[str, Option] = {
    o.name: o
    for o in [
        # erasure code (options.cc:533, 2519)
        # declared-but-dead on purpose: the reference dlopen()s plugins
        # from this dir; ours are python imports
        # cephlint: disable=knob-registry
        _opt("erasure_code_dir", TYPE_STR, LEVEL_ADVANCED, "",
             "unused placeholder: plugins are python entry points here"),
        _opt("osd_erasure_code_plugins", TYPE_STR, LEVEL_ADVANCED,
             "jerasure isa lrc shec clay tpu",
             "plugins allowed in profiles"),
        _opt("osd_pool_default_erasure_code_profile", TYPE_STR,
             LEVEL_ADVANCED,
             "plugin=tpu technique=isa_cauchy k=8 m=3",
             "default EC profile for new pools"),
        # placement / mapping
        _opt("crush_chunk_size", TYPE_UINT, LEVEL_DEV, 0,
             "x-batch cap (pow2) per device launch in the vectorized "
             "mapper; 0 = backend default (2^18 on TPU, 2^16 on CPU)"),
        # fault injection (options.cc:1044-1066, 822)
        _opt("ms_compress_mode", TYPE_STR, LEVEL_ADVANCED, "none",
             "on-wire frame compression codec (none|zlib|snappy-like "
             "names from the compressor registry) — msgr2 compression"),
        _opt("ms_compress_min_size", TYPE_UINT, LEVEL_ADVANCED, 4096,
             "frames below this size are never compressed"),
        # wire fast path (the msgr2 frames_v2 / AsyncConnection
        # write-coalescing analogues)
        _opt("ms_envelope_format", TYPE_STR, LEVEL_ADVANCED, "binary",
             "op envelope encoding on feature-negotiated sessions "
             "(binary = denc-lite structs + raw as its own frame "
             "segment; json = the legacy text envelopes). Peers without "
             "the feature bit always get json regardless"),
        _opt("ms_cork_max_frames", TYPE_UINT, LEVEL_ADVANCED, 64,
             "max frames drained from the send queue per write wakeup; "
             "a corked run goes out as ONE socket write + drain (and one "
             "signed batch frame when the peer negotiated it). 1 = one "
             "write+drain per frame, the uncorked legacy behavior",
             min=1),
        _opt("ms_local_stack", TYPE_BOOL, LEVEL_ADVANCED, True,
             "negotiate the LocalStack (Unix socket + shared-memory "
             "ring) for co-located peers that advertise a uds:// "
             "endpoint; false pins every session to TCP, bit-identical "
             "to the pre-stack wire behavior"),
        _opt("ms_shm_ring_bytes", TYPE_UINT, LEVEL_ADVANCED, 8 << 20,
             "per-direction shared-memory ring capacity for upgraded "
             "local sessions; values below 16KiB disable the ring (the "
             "session stays on the Unix socket)"),
        _opt("ms_uds_dir", TYPE_STR, LEVEL_ADVANCED, "",
             "directory for messenger Unix sockets and ring files; "
             "empty = a per-process tmp dir. AF_UNIX caps socket paths "
             "at ~100 bytes, so keep it shallow"),
        _opt("ms_subop_batch", TYPE_BOOL, LEVEL_ADVANCED, True,
             "coalesce same-peer sub-ops issued within one event-loop "
             "tick into a single multi-op frame with a batched reply "
             "(the EncodeService kernel-launch coalescing shape, applied "
             "to the fan-out wire path)"),
        _opt("ms_inject_socket_failures", TYPE_UINT, LEVEL_DEV, 0,
             "inject a transient store failure every Nth op"),
        _opt("ms_inject_delay_probability", TYPE_FLOAT, LEVEL_DEV, 0.0,
             "probability of injecting a delay per op", min=0.0, max=1.0),
        _opt("ms_inject_delay_max", TYPE_FLOAT, LEVEL_DEV, 1.0,
             "max injected delay (seconds)"),
        _opt("ms_inject_internal_delays", TYPE_FLOAT, LEVEL_DEV, 0.0,
             "inject internal delays to induce races (seconds)"),
        # wire chaos schedules (common/faults.py): scripted per-peer
        # fault streams, seeded so a run replays bit-identically
        _opt("ms_inject_chaos_schedule", TYPE_STR, LEVEL_DEV, "",
             "';'-separated wire-fault rules applied per outgoing "
             "frame run: drop:SRC>DST[:prob], "
             "delay:SRC>DST[:prob[:max_s]], dup:SRC>DST[:prob], "
             "partition:A|B (both ways) or partition:A>B (one-way "
             "— DST still reaches SRC). SRC/DST are comma-separated "
             "entity-name globs (osd.1, osd.*, *). Empty disarms; "
             "armed or not, the hook is one cached attribute check "
             "per corked run",
             see_also=("ms_inject_chaos_seed",)),
        _opt("ms_inject_chaos_seed", TYPE_UINT, LEVEL_DEV, 0,
             "seed for the chaos schedule's per-(src,dst) decision "
             "streams: same seed + schedule -> the same fault "
             "sequence per peer pair, independent of global timing"),
        _opt("heartbeat_inject_failure", TYPE_UINT, LEVEL_DEV, 0,
             "inject heartbeat failures for N seconds"),
        _opt("objecter_inject_no_watch_ping", TYPE_BOOL, LEVEL_DEV, False,
             "suppress watch pings"),
        # device-fault injection (the filestore_debug_inject_read_err /
        # bluestore debug-omit family): 1-in-N rates per device IO; 0
        # disables and the hook costs one cached flag check per site
        _opt("blockstore_inject_read_eio", TYPE_UINT, LEVEL_DEV, 0,
             "raise EIO on 1-in-N BlockStore device/payload reads "
             "(self-healing read path exercise); 0 disables",
             see_also=("blockstore_inject_write_eio",)),
        _opt("blockstore_inject_write_eio", TYPE_UINT, LEVEL_DEV, 0,
             "fail 1-in-N BlockStore device writes; a write error FENCES "
             "the store (fail-stop: no further acks); 0 disables"),
        _opt("blockstore_inject_fsync_fail", TYPE_UINT, LEVEL_DEV, 0,
             "fail 1-in-N BlockStore device fsyncs; an fsync error FENCES "
             "the store — never retried-and-forgotten (Rebello et al., "
             "ATC '20); 0 disables"),
        # data path
        _opt("osd_pool_default_size", TYPE_UINT, LEVEL_BASIC, 3,
             "replicas per replicated pool"),
        _opt("osd_pool_default_pg_num", TYPE_UINT, LEVEL_BASIC, 32,
             "PGs per new pool"),
        _opt("osd_recovery_max_active", TYPE_UINT, LEVEL_ADVANCED, 3,
             "concurrent recovery ops per OSD"),
        _opt("osd_op_queue", TYPE_STR, LEVEL_ADVANCED, "wpq",
             "op scheduler inside each OSD op shard: wpq | mclock"),
        _opt("osd_statfs_total_bytes", TYPE_UINT, LEVEL_ADVANCED,
             1 << 34,
             "advertised store capacity per OSD (the role of the real "
             "disk size BlueStore reads; configurable so tests can fill "
             "a tiny OSD to the full ratios)"),
        _opt("osd_statfs_cache_sec", TYPE_FLOAT, LEVEL_ADVANCED, 0.5,
             "seconds a statfs scan stays cached (the used-bytes scan "
             "is O(kv rows)); 0 recomputes every call, which tier-1 "
             "full/nearfull tests use instead of sleeping the TTL out",
             min=0.0),
        _opt("mon_osd_nearfull_ratio", TYPE_FLOAT, LEVEL_BASIC, 0.85,
             "usage ratio above which an OSD is NEARFULL "
             "(OSDMonitor.cc:365)"),
        _opt("mon_osd_backfillfull_ratio", TYPE_FLOAT, LEVEL_BASIC, 0.90,
             "usage ratio above which an OSD refuses to be a backfill "
             "target"),
        _opt("mon_osd_full_ratio", TYPE_FLOAT, LEVEL_BASIC, 0.95,
             "usage ratio above which client writes are refused with "
             "ENOSPC (deletes still allowed)"),
        _opt("osd_objectstore", TYPE_STR, LEVEL_BASIC, "kstore-file",
             "backing store a daemon-main OSD boots with: kstore-file "
             "(crash-safe WAL FileDB, the default) | memstore "
             "(reference vstart.sh --memstore analogue for benching) | "
             "blockstore (allocator + block file + at-rest crc32c, "
             "the BlueStore analogue)"),
        # blockstore (the bluestore_* option family, options.cc:4252+)
        _opt("blockstore_min_alloc_size", TYPE_UINT, LEVEL_ADVANCED, 4096,
             "allocation granularity of the block file; writes below it "
             "take the deferred (KV WAL) path — bluestore_min_alloc_size",
             min=512),
        _opt("blockstore_csum_block_size", TYPE_UINT, LEVEL_ADVANCED,
             4096,
             "bytes covered by one stored crc32c "
             "(bluestore_csum_* block granularity)", min=512),
        _opt("blockstore_compression_mode", TYPE_STR, LEVEL_ADVANCED,
             "none",
             "compression-on-write policy: none | passive | aggressive "
             "| force (Compressor.h modes)",
             see_also=("blockstore_compression_algorithm",)),
        _opt("blockstore_compression_algorithm", TYPE_STR,
             LEVEL_ADVANCED, "zlib",
             "codec from the compressor registry used when "
             "blockstore_compression_mode compresses"),
        _opt("blockstore_compression_min_blob_size", TYPE_UINT,
             LEVEL_ADVANCED, 4096,
             "blobs below this size never attempt compression"),
        _opt("blockstore_deferred_batch_bytes", TYPE_UINT,
             LEVEL_ADVANCED, 65536,
             "deferred-write backlog that triggers a flush to the block "
             "file (bluestore deferred_batch role)"),
        _opt("blockstore_deferred_max_age_ms", TYPE_UINT,
             LEVEL_ADVANCED, 500,
             "oldest deferred write may sit in the KV WAL this long "
             "before the background flusher drains the backlog to the "
             "device, independent of byte pressure; 0 disables the "
             "flusher (byte-threshold-only, the PR-1 behavior)",
             see_also=("blockstore_deferred_batch_bytes",)),
        _opt("blockstore_onode_cache_size", TYPE_UINT, LEVEL_ADVANCED,
             1024,
             "decoded onodes (extent map + csums) kept in an LRU so hot "
             "objects skip the KV fetch + decode "
             "(bluestore_onode_cache_size role); 0 disables"),
        _opt("blockstore_buffer_cache_bytes", TYPE_UINT, LEVEL_ADVANCED,
             32 << 20,
             "bytes of recently read/written object data kept in a "
             "write-through LRU so re-reads skip the device and the "
             "checksum re-verify (bluestore buffer cache role); 0 "
             "disables — fsck, deep scrub, and read_verify always read "
             "device truth regardless"),
        _opt("blockstore_block_path", TYPE_STR, LEVEL_ADVANCED, "",
             "explicit block file path; empty = <kv dir>/block beside a "
             "FileDB, or an in-memory device over MemDB"),
        _opt("blockstore_block_size", TYPE_UINT, LEVEL_ADVANCED, 0,
             "hard cap on the block file size (the fixed-disk role): "
             "allocation beyond it fails cleanly with ENOSPC — never "
             "EIO, never a fence — and frees make the store writable "
             "again; 0 = grow-on-demand (unbounded)"),
        _opt("osd_min_pg_log_entries", TYPE_UINT, LEVEL_ADVANCED, 500,
             "log entries retained per PG; peers further behind than "
             "this take a full backfill instead of log recovery"),
        _opt("osd_max_backfills", TYPE_UINT, LEVEL_ADVANCED, 1,
             "concurrent backfills one OSD will source (reservations)"),
        _opt("osd_recovery_batch_max", TYPE_UINT, LEVEL_ADVANCED, 16,
             "objects pulled/pushed per recovery batch: the batch's "
             "sub-ops coalesce into subop_batch frames and its "
             "concurrent EC shard rebuilds coalesce into one batched "
             "decode launch; 1 restores one-object-at-a-time healing",
             min=1, see_also=("osd_max_backfills",)),
        _opt("osd_mon_report_interval", TYPE_FLOAT, LEVEL_ADVANCED, 2.0,
             "seconds between PG stats reports to the mon (health "
             "checks aggregate these)"),
        _opt("auth_service_ticket_ttl", TYPE_FLOAT, LEVEL_ADVANCED,
             3600.0,
             "cephx service ticket lifetime; clients renew at half-life"),
        _opt("mgr_beacon_interval", TYPE_FLOAT, LEVEL_ADVANCED, 0.5,
             "seconds between mgr liveness beacons to the mon "
             "(MgrMonitor beacon cadence)"),
        _opt("mgr_beacon_grace", TYPE_FLOAT, LEVEL_ADVANCED, 3.0,
             "silence after which the active mgr is considered dead "
             "and a standby promotes"),
        _opt("mgr_report_interval", TYPE_FLOAT, LEVEL_ADVANCED, 1.0,
             "seconds between perf-counter delta reports from each "
             "daemon to the active mgr (MgrClient report cadence)"),
        _opt("mgr_metrics_window", TYPE_UINT, LEVEL_ADVANCED, 120,
             "samples retained per counter in the mgr's per-daemon "
             "ring time-series (bounds memory; rates/percentiles are "
             "computed over this window)"),
        _opt("mgr_slo_rules", TYPE_STR, LEVEL_ADVANCED, "",
             "semicolon-separated SLO rules evaluated by the mgr "
             "metrics module, e.g. 'op_latency.p99 < 2s @ 30; "
             "read_redirected/read_balanced < 0.05'; violations "
             "surface as MGR_SLO_VIOLATION health checks"),
        _opt("mgr_recovery_slow_warn", TYPE_FLOAT, LEVEL_ADVANCED, 1.0,
             "objects/s below which the mgr raises RECOVERY_SLOW while "
             "any OSD reports degraded objects; 0 disables the check"),
        _opt("mgr_trace_store_max", TYPE_UINT, LEVEL_ADVANCED, 256,
             "promoted traces the mgr trace collector retains "
             "(oldest-first eviction; `ceph trace ls|show` serves from "
             "this bounded store)", min=1),
        _opt("mgr_trace_ttl", TYPE_FLOAT, LEVEL_ADVANCED, 600.0,
             "seconds a promoted trace stays in the mgr collector "
             "before aging out; 0 keeps traces until evicted by "
             "mgr_trace_store_max", min=0.0),
        _opt("mgr_prometheus_exemplars", TYPE_BOOL, LEVEL_ADVANCED,
             False,
             "attach OpenMetrics exemplars ({trace_id=...}) to latency "
             "histogram buckets and serve /metrics as "
             "application/openmetrics-text"),
        _opt("mds_beacon_interval", TYPE_FLOAT, LEVEL_ADVANCED, 0.5,
             "seconds between MDS beacons to the mon"),
        _opt("mds_max_active", TYPE_UINT, LEVEL_BASIC, 1,
             "active metadata daemons (FSMap max_mds): ranks partition "
             "the namespace by top-level directory hash"),
        _opt("mds_bal_split_size", TYPE_UINT, LEVEL_ADVANCED, 10000,
             "dentries in one directory fragment before the MDS splits "
             "it (CDir fragmentation, mds_bal_split_size)"),
        _opt("mds_blocklist_expire", TYPE_FLOAT, LEVEL_ADVANCED, 3600.0,
             "seconds an MDS-evicted client stays blocklisted in the "
             "OSDMap (mds_session_blacklist_on_evict + "
             "mon_osd_blacklist_default_expire)"),
        _opt("mds_beacon_grace", TYPE_FLOAT, LEVEL_ADVANCED, 3.0,
             "beacon silence before the mon fails the active MDS over"),
        _opt("osd_ec_batch_window", TYPE_FLOAT, LEVEL_ADVANCED, 0.002,
             "seconds the first EC op of a batch waits so concurrent "
             "objects share one planar device launch"),
        _opt("osd_heartbeat_grace", TYPE_UINT, LEVEL_ADVANCED, 20,
             "seconds before an unresponsive OSD is reported down"),
        _opt("osd_heartbeat_interval", TYPE_FLOAT, LEVEL_ADVANCED, 6.0,
             "seconds between peer pings"),
        # monitor (mon_lease: Paxos.cc lease_interval; the election timeout
        # plays Elector.cc's plugged election_timeout role)
        _opt("mon_lease", TYPE_FLOAT, LEVEL_ADVANCED, 5.0,
             "leader lease renewal interval (seconds)"),
        _opt("mon_lease_ack_timeout_factor", TYPE_FLOAT, LEVEL_ADVANCED,
             4.0, "lease multiples a peon waits before calling an election"),
        _opt("mon_election_timeout", TYPE_FLOAT, LEVEL_ADVANCED, 5.0,
             "seconds an election proposal waits for a quorum"),
        _opt("mon_osd_min_down_reporters", TYPE_UINT, LEVEL_ADVANCED, 2,
             "distinct reporters required to mark an OSD down (the "
             "reference's default; one stalled reporter must not be able "
             "to down a healthy daemon)"),
        # distributed tracing (src/common/tracer: jaeger_tracing_enable
        # and friends; see ceph_tpu.common.tracer)
        _opt("tracer_enabled", TYPE_BOOL, LEVEL_ADVANCED, False,
             "emit Dapper-style spans for sampled ops "
             "(jaeger_tracing_enable role); disabled cost is one cached "
             "flag check per span site",
             see_also=("tracer_sample_rate", "tracer_export_path")),
        _opt("tracer_sample_rate", TYPE_FLOAT, LEVEL_ADVANCED, 1.0,
             "fraction of root ops that start a trace; children follow "
             "the root's decision", min=0.0, max=1.0),
        _opt("tracer_ring_size", TYPE_UINT, LEVEL_ADVANCED, 1024,
             "completed spans retained per daemon for `dump_tracing`",
             min=1),
        # per-op-type sample-rate overrides: recovery reads can be traced
        # at 100% while steady-state IO stays sampled; -1 inherits the
        # base tracer_sample_rate
        *[
            _opt(f"tracer_sample_rate_{t}", TYPE_FLOAT, LEVEL_ADVANCED,
                 -1.0,
                 f"sample-rate override for {t!r} root ops; -1 inherits "
                 "tracer_sample_rate", min=-1.0, max=1.0,
                 see_also=("tracer_sample_rate",))
            for t in ("read", "write", "ops", "delete", "call", "stat",
                      "recovery", "command", "balancer")
        ],
        _opt("tracer_export_path", TYPE_STR, LEVEL_ADVANCED, "",
             "append finished spans as Jaeger-compatible JSONL here "
             "(tools/trace_tool.py renders trace trees from it); empty "
             "disables export"),
        # tail sampling (the flight recorder: Canopy-style keep/drop at
        # op COMPLETION instead of at op start; see common/tracer.py)
        _opt("tracer_flight_ring_size", TYPE_UINT, LEVEL_ADVANCED, 2048,
             "completed spans retained in the always-on flight ring "
             "(recorded for EVERY op regardless of sample rate; the "
             "tail keep/drop decision and the crash black-box read "
             "from here)", min=1,
             see_also=("tracer_tail_slow_ms",)),
        _opt("tracer_tail_slow_ms", TYPE_FLOAT, LEVEL_ADVANCED, 1000.0,
             "promote a trace at completion when its root span took "
             "longer than this many milliseconds; 0 disables the "
             "slow-duration predicate", min=0.0),
        _opt("tracer_tail_top_n", TYPE_UINT, LEVEL_ADVANCED, 0,
             "also promote the N slowest root spans per "
             "tracer_tail_window_s window even when none crossed "
             "tracer_tail_slow_ms; 0 disables slowest-N promotion"),
        _opt("tracer_tail_window_s", TYPE_FLOAT, LEVEL_ADVANCED, 10.0,
             "window length for slowest-N and SLO-capture promotion "
             "budgets", min=0.1),
        _opt("tracer_tail_errors", TYPE_BOOL, LEVEL_ADVANCED, True,
             "promote every trace whose root span carries an error, "
             "retry, or redirect tag (EIO/resend/abort: the ops an "
             "operator always wants the trace for)"),
        _opt("tracer_tail_capture_per_window", TYPE_UINT,
             LEVEL_ADVANCED, 2,
             "per-predicate promotion budget per window for mgr-pushed "
             "SLO capture predicates (bounds trace volume while a rule "
             "is in violation)", min=1),
        _opt("tracer_crash_dump_dir", TYPE_STR, LEVEL_ADVANCED, "",
             "directory for the crash black-box: on StoreFatalError/"
             "fence the daemon dumps its flight ring, in-flight ops and "
             "recent log lines to <dir>/<daemon>.blackbox.json and "
             "clogs the pointer; empty disables the dump"),
        _opt("slow_op_seconds", TYPE_FLOAT, LEVEL_ADVANCED, 30.0,
             "in-flight op age that triggers an immediate `slow "
             "request` warning line (osd_op_complaint_time role)",
             min=0.0),
        _opt("osd_scrub_auto_repair", TYPE_BOOL, LEVEL_ADVANCED, False,
             "deep scrub that finds a repairable inconsistency "
             "(digest mismatch, read EIO, missing hinfo) kicks off the "
             "primary-driven repair in place instead of only flagging "
             "it (the reference's osd_scrub_auto_repair)"),
        _opt("mon_cluster_log_entries", TYPE_UINT, LEVEL_ADVANCED, 1000,
             "cluster-log lines the mon leader retains for "
             "`log last <n>` (LogMonitor summary role)", min=1),
        # scale-out read path (the reference's Octopus balanced reads:
        # osd_read_from_replica / CEPH_OSD_FLAG_BALANCE_READS)
        _opt("rados_read_policy", TYPE_STR, LEVEL_ADVANCED, "primary",
             "client read-target policy: 'primary' sends every read to "
             "the PG primary (classic path); 'balance' spreads reads "
             "round-robin over all clean acting members; 'localize' "
             "prefers an acting member colocated on this host (its "
             "LocalStack uds endpoint exists locally), falling back to "
             "balance. A non-primary target only serves a read when its "
             "copy is provably current — anything else bounces back to "
             "the primary with a redirect, never wrong data",
             see_also=("rados_ec_direct_reads",)),
        _opt("rados_ec_direct_reads", TYPE_BOOL, LEVEL_ADVANCED, True,
             "with a non-primary rados_read_policy on an EC pool whose "
             "acting set is whole, compute the stripe layout client-side "
             "and read the k data shards directly from their home OSDs "
             "in parallel (no primary gather, no decode launch); any "
             "shard error, stale shard, or degraded interval falls back "
             "to the primary decode path",
             see_also=("rados_read_policy",)),
        _opt("rados_backfill_hint_ttl", TYPE_FLOAT, LEVEL_ADVANCED, 10.0,
             "seconds the objecter trusts a redirect reply's backfill "
             "hint and steers balanced reads past the named backfill "
             "targets; after expiry the next read probes the target "
             "again (one redirect round-trip) to learn whether the "
             "backfill drained", min=0.0,
             see_also=("rados_read_policy",)),
        # checkpoint store (ceph_tpu.ckpt: Orbax/TensorStore-style
        # manifest + chunk layout over RADOS)
        _opt("ckpt_chunk_target_bytes", TYPE_UINT, LEVEL_ADVANCED,
             1 << 20,
             "target chunk-object size for checkpoint saves; rounded "
             "up to a full EC stripe so chunk puts never read-modify-"
             "write", min=4096),
        _opt("ckpt_max_inflight", TYPE_UINT, LEVEL_ADVANCED, 8,
             "bounded window of concurrent chunk puts/gets per "
             "checkpoint save/restore", min=1),
        _opt("ckpt_compression_algorithm", TYPE_STR, LEVEL_ADVANCED, "",
             "compress checkpoint chunks with this algorithm "
             "(zlib|lzma|zstd); empty disables compression"),
        _opt("ckpt_incremental", TYPE_BOOL, LEVEL_ADVANCED, True,
             "diff each save against the previous committed manifest "
             "by chunk content fingerprint and reference unchanged "
             "chunks from the prior save instead of re-uploading them "
             "(CheckFreq-style incremental checkpointing)"),
        _opt("ckpt_async_max_pending", TYPE_UINT, LEVEL_ADVANCED, 2,
             "save_async() backpressure: at most this many snapshots "
             "may be persisting in the background; a further submit "
             "blocks until the oldest completes, so a slow cluster "
             "throttles the training loop instead of accumulating "
             "host-memory snapshots", min=1),
        _opt("ckpt_restore_readahead", TYPE_UINT, LEVEL_ADVANCED, 0,
             "bounded readahead window of in-flight chunk reads during "
             "restore (decompress/crc/placement overlap with the reads "
             "still in flight); 0 inherits ckpt_max_inflight"),
        _opt("ckpt_gc_keep_last", TYPE_UINT, LEVEL_ADVANCED, 1,
             "gc retention: keep the newest N committed saves (HEAD is "
             "always kept); chunks stay live while ANY retained "
             "manifest references them", min=1),
        _opt("ckpt_gc_keep_every_nth", TYPE_UINT, LEVEL_ADVANCED, 0,
             "gc retention: additionally keep every Nth committed save "
             "from the name's commit history (0 disables)"),
        # dataset store (ceph_tpu.data: record-sharded training-data
        # ingestion + prefetching iterator over RADOS)
        _opt("data_shard_bytes", TYPE_UINT, LEVEL_ADVANCED, 4 << 20,
             "target shard-object size for dataset ingests; each shard's "
             "striper sub-objects are rounded up to a full EC stripe so "
             "shard puts never read-modify-write", min=4096),
        _opt("data_compression_algorithm", TYPE_STR, LEVEL_ADVANCED, "",
             "compress dataset records with this algorithm "
             "(zlib|lzma|zstd); empty disables compression"),
        _opt("data_max_inflight", TYPE_UINT, LEVEL_ADVANCED, 8,
             "bounded window of concurrent shard/index puts per dataset "
             "ingest", min=1),
        _opt("data_prefetch_batches", TYPE_UINT, LEVEL_ADVANCED, 2,
             "background batch-prefetch depth of the dataset iterator: "
             "this many upcoming batches may have their ranged shard "
             "reads in flight while the training step consumes the "
             "current one; 0 disables prefetch (serial fetch-on-demand)"),
        _opt("data_cache_bytes", TYPE_UINT, LEVEL_ADVANCED, 64 << 20,
             "client-side block cache of the prefetching dataset "
             "iterator: readahead fetches whole striper sub-objects "
             "(one EC decode per block at the OSD, amortized over every "
             "record inside) and keeps up to this many bytes LRU-"
             "resident; 0 falls back to exact per-record ranged reads"),
        _opt("osd_mclock_data_weight", TYPE_FLOAT, LEVEL_ADVANCED, 0.25,
             "mclock weight of the background data-prefetch client "
             "class (op_queue.QOS_DATA_PREFETCH): bulk dataset reads get "
             "this proportional share against weight-1 foreground "
             "clients, so prefetch cannot starve ckpt/RBD traffic",
             min=0.01),
        _opt("osd_mclock_recovery_weight", TYPE_FLOAT, LEVEL_ADVANCED,
             0.25,
             "mclock weight of the recovery class "
             "(op_queue.QOS_RECOVERY): recovery pulls/rebuild reads/"
             "batched pushes get this proportional share against "
             "weight-1 client classes — a recovery storm cannot starve "
             "client ops", min=0.01,
             see_also=("osd_mclock_recovery_reservation",)),
        _opt("osd_mclock_recovery_reservation", TYPE_FLOAT,
             LEVEL_ADVANCED, 10.0,
             "mclock reservation floor (ops/s) for the recovery class: "
             "sustained client load squeezes healing down to this "
             "minimum but never to zero (dmclock phase-1)", min=0.0),
        # coordination (ceph_tpu.coord: cls_lock leases, leader election,
        # fleet roster/barriers for multi-host training)
        _opt("cls_clock_offset", TYPE_FLOAT, LEVEL_DEV, 0.0,
             "seconds added to the primary's clock when stamping "
             "MethodContext.now for object-class calls; lets tests "
             "advance lease time deterministically without sleeping"),
        _opt("coord_lease", TYPE_FLOAT, LEVEL_ADVANCED, 5.0,
             "lease duration (seconds) for coordination locks: fleet "
             "member heartbeats, leader election, and the checkpoint "
             "committer lock; an expired lease makes the lock breakable "
             "by survivors", min=0.1),
        _opt("coord_renew_factor", TYPE_FLOAT, LEVEL_ADVANCED, 0.34,
             "a Lock's renew loop re-locks every coord_lease * this "
             "fraction, so a holder survives a couple of missed renewals "
             "before its lease lapses", min=0.05, max=0.9),
        _opt("coord_barrier_poll", TYPE_FLOAT, LEVEL_ADVANCED, 1.0,
             "fallback poll interval (seconds) for barrier/lock waiters; "
             "watch/notify wakeups make this the slow path, only taken "
             "when a notify is lost to a primary change", min=0.01),
        # balancer / placement simulator (ceph_tpu.crush.balance,
        # ceph_tpu.sim, tools/psim.py; reference mgr/balancer options)
        _opt("balancer_max_deviation", TYPE_FLOAT, LEVEL_ADVANCED, 1.0,
             "PG-count deviation from the weight-proportional target "
             "every OSD must reach before a balancer pass stops "
             "(upmap_max_deviation role)", min=0.0,
             see_also=("balancer_max_changes", "balancer_mode")),
        _opt("balancer_max_changes", TYPE_UINT, LEVEL_ADVANCED, 256,
             "pg_upmap_items budget per balancer tick; the batched move "
             "scorer makes hundreds per tick affordable "
             "(upmap_max_optimizations role)", min=1),
        _opt("balancer_mode", TYPE_STR, LEVEL_ADVANCED, "upmap",
             "balancer optimization mode: upmap (per-PG exception "
             "table) or crush-compat (choose_args weight-set feedback "
             "that pre-upmap clients honor)"),
        _opt("psim_default_osds", TYPE_UINT, LEVEL_DEV, 1024,
             "cluster size tools/psim.py builds when --osds is not "
             "given", min=1),
        _opt("psim_default_seed", TYPE_UINT, LEVEL_DEV, 1,
             "event-script RNG seed tools/psim.py uses when --seed is "
             "not given"),
        _opt("psim_bytes_per_pg", TYPE_UINT, LEVEL_DEV, 8 << 30,
             "assumed bytes stored per PG for psim's backfill-storm "
             "estimate (PGs moved x this = data moved per epoch)",
             min=1),
        # bench / profiling
        _opt("bench_profile_trace_dir", TYPE_STR, LEVEL_DEV, "",
             "write jax.profiler traces here when set",
             see_also=("bench_profile",)),
        _opt("bench_profile", TYPE_BOOL, LEVEL_DEV, False,
             "capture a jax.profiler trace around benchmark loops"),
        # dout subsystem levels (src/common/subsys.h-style "1/5" defaults:
        # emitted at the configured level, ring-gathered up to 5; see
        # ceph_tpu.common.log)
        *[
            _opt(f"debug_{subsys}", TYPE_INT, LEVEL_ADVANCED, 1,
                 f"emitted debug level for the {subsys} subsystem")
            for subsys in ("osd", "crush", "ec", "rados", "bench")
        ],
    ]
}


class Config:
    """Layered, observed, typed configuration (config_proxy.h analogue)."""

    ENV_PREFIX = "CEPH_TPU_"

    def __init__(self, schema: dict[str, Option] | None = None):
        self.schema = schema if schema is not None else SCHEMA
        self._file: dict[str, Any] = {}
        #: mon centralized-config tier (ConfigMonitor): below the local
        #: conf file, above compiled defaults — local settings win
        self._mon: dict[str, Any] = {}
        self._runtime: dict[str, Any] = {}
        self._observers: dict[str, list[Callable[[str, Any], None]]] = {}

    # -- reads --------------------------------------------------------------

    def _opt(self, name: str) -> Option:
        opt = self.schema.get(name)
        if opt is None:
            raise ConfigError(f"unknown option {name!r}")
        return opt

    def get(self, name: str) -> Any:
        opt = self._opt(name)
        if name in self._runtime:
            return self._runtime[name]
        env = os.environ.get(self.ENV_PREFIX + name.upper())
        if env is not None:
            return opt.parse(env)
        if name in self._file:
            return self._file[name]
        if name in self._mon:
            return self._mon[name]
        return opt.default

    def source_of(self, name: str) -> str:
        self._opt(name)
        if name in self._runtime:
            return "override"
        if os.environ.get(self.ENV_PREFIX + name.upper()) is not None:
            return "env"
        if name in self._file:
            return "file"
        if name in self._mon:
            return "mon"
        return "default"

    # -- writes -------------------------------------------------------------

    def set(self, name: str, value: Any) -> None:
        """Runtime override (the mon/injectargs tier); fires observers."""
        opt = self._opt(name)
        self._runtime[name] = opt.parse(value)
        for cb in self._observers.get(name, []):
            cb(name, self._runtime[name])

    def rm(self, name: str) -> None:
        self._opt(name)
        self._runtime.pop(name, None)

    def load_file_values(self, values: dict[str, Any]) -> None:
        """Conf-file tier (between defaults and env)."""
        for name, value in values.items():
            self._file[name] = self._opt(name).parse(value)

    def apply_mon_values(self, values: dict[str, Any]) -> None:
        """Replace the mon centralized-config tier (MonClient applies the
        committed config map); observers fire for keys whose EFFECTIVE
        value changed."""
        before = {
            name: self.get(name)
            for name in set(self._mon) | set(values)
            if name in self.schema
        }
        self._mon = {
            name: self._opt(name).parse(v)
            for name, v in values.items()
            if name in self.schema
        }
        for name, old in before.items():
            new = self.get(name)
            if new != old:
                for cb in self._observers.get(name, []):
                    cb(name, new)

    def observe(self, name: str, cb: Callable[[str, Any], None]) -> None:
        self._opt(name)
        self._observers.setdefault(name, []).append(cb)

    # -- dumps --------------------------------------------------------------

    def show(self) -> dict[str, Any]:
        """`config show`: effective value + source per option."""
        return {
            name: {"value": self.get(name), "source": self.source_of(name)}
            for name in sorted(self.schema)
        }

    def dump_schema(self) -> dict[str, Any]:
        return {
            name: {
                "type": o.type,
                "level": o.level,
                "default": o.default,
                "description": o.description,
                **({"min": o.min} if o.min is not None else {}),
                **({"max": o.max} if o.max is not None else {}),
                **({"see_also": list(o.see_also)} if o.see_also else {}),
            }
            for name, o in sorted(self.schema.items())
        }


#: process-wide config, like the CephContext-owned ConfigProxy
config = Config()
