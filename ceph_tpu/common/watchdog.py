"""SharedWatchdog: one timer for any number of awaited futures.

`asyncio.wait_for(fut, t)` arms and cancels a TimerHandle per call — on the
op submit path and the sub-op fan-out that is timer churn per op (k+m
handles per EC write). The reference sidesteps the same cost with one
SafeTimer sweeping all outstanding op deadlines (Objecter::tick); this is
that shape: deadlines live in a dict, one task sweeps them at a coarse
granularity, and expiry fails the future with asyncio.TimeoutError so
existing `except asyncio.TimeoutError` retry paths work unchanged.

Only suitable where the timeout is a retry pacer, not a precise deadline:
expiry lands up to one sweep-granularity late.
"""

from __future__ import annotations

import asyncio
import itertools


class SharedWatchdog:
    def __init__(self, granularity: float = 0.25):
        self._granularity = granularity
        self._entries: dict[int, tuple[float, asyncio.Future]] = {}
        self._ids = itertools.count(1)
        self._task: asyncio.Task | None = None

    async def wait(self, fut: asyncio.Future, timeout: float):
        """Drop-in for `asyncio.wait_for(fut, timeout)` on futures that
        are resolved elsewhere (dispatch handlers): zero TimerHandles,
        one shared sweep."""
        loop = asyncio.get_event_loop()
        handle = next(self._ids)
        self._entries[handle] = (loop.time() + timeout, fut)
        if self._task is None or self._task.done():
            self._task = asyncio.create_task(self._sweep())
        try:
            return await fut
        finally:
            self._entries.pop(handle, None)

    async def _sweep(self) -> None:
        loop = asyncio.get_event_loop()
        while self._entries:
            await asyncio.sleep(self._granularity)
            now = loop.time()
            for handle, (deadline, fut) in list(self._entries.items()):
                if fut.done():
                    self._entries.pop(handle, None)
                elif now >= deadline:
                    self._entries.pop(handle, None)
                    fut.set_exception(asyncio.TimeoutError())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self._entries.clear()
