"""denc-lite: deterministic versioned binary encoding.

The reference threads every wire/disk struct through bufferlist encoders with
a versioned envelope (ENCODE_START/DECODE_START in
/root/reference/src/include/encoding.h): each struct writes

    u8 struct_v . u8 struct_compat . u32 struct_len . <payload>

so old decoders can (a) refuse blobs whose `struct_compat` is newer than what
they understand and (b) skip trailing payload bytes a newer encoder appended —
that skip rule is what makes rolling upgrades possible, and the
`ceph-dencoder` + ceph-object-corpus harness pins the exact bytes across
releases (SURVEY §4 tier 2).

This module re-expresses that contract: little-endian fixed-width primitives
(the reference encodes everything little-endian via ceph_le types), u32
length-prefixed blobs/strings/containers (matching encode(std::vector) /
encode(std::map) shapes), and the versioned envelope with the same
skip-unknown-suffix semantics. No reference bytes are reproduced — the layout
rules are the contract, the structs encoded with it are ours.

tests/test_encoding.py carries a small golden corpus (hex blobs committed in
the repo) playing the role of ceph-object-corpus: any byte drift fails.
"""

from __future__ import annotations

import struct


class DecodeError(ValueError):
    pass


class Encoder:
    def __init__(self) -> None:
        self._parts: list[bytes] = []

    # -- primitives (little-endian, like ceph_le##) ---------------------------

    def u8(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<B", v))
        return self

    def u16(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<H", v))
        return self

    def u32(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<I", v))
        return self

    def u64(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<Q", v))
        return self

    def s32(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<i", v))
        return self

    def s64(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<q", v))
        return self

    def f64(self, v: float) -> "Encoder":
        self._parts.append(struct.pack("<d", v))
        return self

    def boolean(self, v: bool) -> "Encoder":
        return self.u8(1 if v else 0)

    # -- length-prefixed payloads ---------------------------------------------

    def blob(self, v: bytes) -> "Encoder":
        self.u32(len(v))
        self._parts.append(bytes(v))
        return self

    def string(self, v: str) -> "Encoder":
        return self.blob(v.encode("utf-8"))

    def raw(self, v: bytes) -> "Encoder":
        self._parts.append(bytes(v))
        return self

    def list(self, items, item_fn) -> "Encoder":
        """u32 count + items, the encode(std::vector) shape."""
        self.u32(len(items))
        for it in items:
            item_fn(self, it)
        return self

    def mapping(self, items: dict, key_fn, val_fn) -> "Encoder":
        """u32 count + sorted (key, value) pairs.

        std::map iterates in key order, which is what makes the reference's
        map encodings deterministic; dicts are sorted here for the same
        guarantee.
        """
        keys = sorted(items)
        self.u32(len(keys))
        for k in keys:
            key_fn(self, k)
            val_fn(self, items[k])
        return self

    # -- versioned envelope (ENCODE_START semantics) --------------------------

    def struct(self, version: int, compat: int, body_fn) -> "Encoder":
        body = Encoder()
        body_fn(body)
        payload = body.bytes()
        self.u8(version).u8(compat).u32(len(payload))
        self._parts.append(payload)
        return self

    def bytes(self) -> bytes:
        return b"".join(self._parts)


class Decoder:
    def __init__(self, data: bytes, offset: int = 0, end: int | None = None):
        self._data = data
        self._off = offset
        self._end = len(data) if end is None else end

    def _take(self, n: int) -> bytes:
        if self._off + n > self._end:
            raise DecodeError(
                f"buffer underrun: need {n} bytes at {self._off}, end {self._end}"
            )
        v = self._data[self._off : self._off + n]
        self._off += n
        return v

    def remaining(self) -> int:
        return self._end - self._off

    @property
    def offset(self) -> int:
        """Current read position (for record-stream consumers)."""
        return self._off

    def u8(self) -> int:
        return struct.unpack("<B", self._take(1))[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def s32(self) -> int:
        return struct.unpack("<i", self._take(4))[0]

    def s64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def boolean(self) -> bool:
        return self.u8() != 0

    def blob(self) -> bytes:
        return self._take(self.u32())

    def string(self) -> str:
        return self.blob().decode("utf-8")

    def raw(self, n: int) -> bytes:
        return self._take(n)

    def list(self, item_fn) -> list:
        return [item_fn(self) for _ in range(self.u32())]

    def mapping(self, key_fn, val_fn) -> dict:
        n = self.u32()
        out = {}
        for _ in range(n):
            k = key_fn(self)
            out[k] = val_fn(self)
        return out

    def struct(self, understood_version: int, body_fn):
        """DECODE_START: refuse blobs from a future incompatible encoder,
        decode the payload, skip any suffix a newer-but-compatible encoder
        appended."""
        version = self.u8()
        compat = self.u8()
        length = self.u32()
        if compat > understood_version:
            raise DecodeError(
                f"struct compat {compat} > understood version {understood_version}"
            )
        if self._off + length > self._end:
            raise DecodeError("struct length exceeds buffer")
        body = Decoder(self._data, self._off, self._off + length)
        result = body_fn(body, version)
        self._off += length  # skip anything body_fn did not consume
        return result
