"""denc-lite: deterministic versioned binary encoding.

The reference threads every wire/disk struct through bufferlist encoders with
a versioned envelope (ENCODE_START/DECODE_START in
/root/reference/src/include/encoding.h): each struct writes

    u8 struct_v . u8 struct_compat . u32 struct_len . <payload>

so old decoders can (a) refuse blobs whose `struct_compat` is newer than what
they understand and (b) skip trailing payload bytes a newer encoder appended —
that skip rule is what makes rolling upgrades possible, and the
`ceph-dencoder` + ceph-object-corpus harness pins the exact bytes across
releases (SURVEY §4 tier 2).

This module re-expresses that contract: little-endian fixed-width primitives
(the reference encodes everything little-endian via ceph_le types), u32
length-prefixed blobs/strings/containers (matching encode(std::vector) /
encode(std::map) shapes), and the versioned envelope with the same
skip-unknown-suffix semantics. No reference bytes are reproduced — the layout
rules are the contract, the structs encoded with it are ours.

tests/test_encoding.py carries a small golden corpus (hex blobs committed in
the repo) playing the role of ceph-object-corpus: any byte drift fails.
"""

from __future__ import annotations

import struct


class DecodeError(ValueError):
    pass


class Encoder:
    def __init__(self) -> None:
        self._parts: list[bytes] = []

    # -- primitives (little-endian, like ceph_le##) ---------------------------

    def u8(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<B", v))
        return self

    def u16(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<H", v))
        return self

    def u32(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<I", v))
        return self

    def u64(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<Q", v))
        return self

    def s32(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<i", v))
        return self

    def s64(self, v: int) -> "Encoder":
        self._parts.append(struct.pack("<q", v))
        return self

    def f64(self, v: float) -> "Encoder":
        self._parts.append(struct.pack("<d", v))
        return self

    def boolean(self, v: bool) -> "Encoder":
        return self.u8(1 if v else 0)

    # -- length-prefixed payloads ---------------------------------------------

    def blob(self, v: bytes) -> "Encoder":
        self.u32(len(v))
        self._parts.append(bytes(v))
        return self

    def string(self, v: str) -> "Encoder":
        return self.blob(v.encode("utf-8"))

    def raw(self, v: bytes) -> "Encoder":
        self._parts.append(bytes(v))
        return self

    def list(self, items, item_fn) -> "Encoder":
        """u32 count + items, the encode(std::vector) shape."""
        self.u32(len(items))
        for it in items:
            item_fn(self, it)
        return self

    def mapping(self, items: dict, key_fn, val_fn) -> "Encoder":
        """u32 count + sorted (key, value) pairs.

        std::map iterates in key order, which is what makes the reference's
        map encodings deterministic; dicts are sorted here for the same
        guarantee.
        """
        keys = sorted(items)
        self.u32(len(keys))
        for k in keys:
            key_fn(self, k)
            val_fn(self, items[k])
        return self

    # -- versioned envelope (ENCODE_START semantics) --------------------------

    def struct(self, version: int, compat: int, body_fn) -> "Encoder":
        body = Encoder()
        body_fn(body)
        payload = body.bytes()
        self.u8(version).u8(compat).u32(len(payload))
        self._parts.append(payload)
        return self

    def bytes(self) -> bytes:
        return b"".join(self._parts)


class Decoder:
    def __init__(self, data: bytes, offset: int = 0, end: int | None = None):
        self._data = data
        self._off = offset
        self._end = len(data) if end is None else end

    def _take(self, n: int) -> bytes:
        if self._off + n > self._end:
            raise DecodeError(
                f"buffer underrun: need {n} bytes at {self._off}, end {self._end}"
            )
        v = self._data[self._off : self._off + n]
        self._off += n
        return v

    def remaining(self) -> int:
        return self._end - self._off

    @property
    def offset(self) -> int:
        """Current read position (for record-stream consumers)."""
        return self._off

    def u8(self) -> int:
        return struct.unpack("<B", self._take(1))[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def s32(self) -> int:
        return struct.unpack("<i", self._take(4))[0]

    def s64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self._take(8))[0]

    def boolean(self) -> bool:
        return self.u8() != 0

    def blob(self) -> bytes:
        return self._take(self.u32())

    def string(self) -> str:
        return self.blob().decode("utf-8")

    def raw(self, n: int) -> bytes:
        return self._take(n)

    def list(self, item_fn) -> list:
        return [item_fn(self) for _ in range(self.u32())]

    def mapping(self, key_fn, val_fn) -> dict:
        n = self.u32()
        out = {}
        for _ in range(n):
            k = key_fn(self)
            out[k] = val_fn(self)
        return out

    def struct(self, understood_version: int, body_fn):
        """DECODE_START: refuse blobs from a future incompatible encoder,
        decode the payload, skip any suffix a newer-but-compatible encoder
        appended."""
        version = self.u8()
        compat = self.u8()
        length = self.u32()
        if compat > understood_version:
            raise DecodeError(
                f"struct compat {compat} > understood version {understood_version}"
            )
        if self._off + length > self._end:
            raise DecodeError("struct length exceeds buffer")
        body = Decoder(self._data, self._off, self._off + length)
        result = body_fn(body, version)
        self._off += length  # skip anything body_fn did not consume
        return result


# -- dynamic values (the binary op-envelope codec) ----------------------------
#
# Op payloads are JSON-shaped dicts built ad hoc per op type; the wire fast
# path replaces `json.dumps`/`json.loads` per hop with this tagged compact
# encoding. The value model is EXACTLY json's so the two formats are
# interchangeable per connection: tuples encode as lists, non-string dict
# keys stringify the way json.dumps coerces them, and decode always returns
# what json.loads would have (so handlers never see a format difference).
# `bytes` is the one extension (no base64/hex inflation) — op payloads only
# use it for values that never cross into a JSON-encoded hop.

_V_NONE = 0
_V_TRUE = 1
_V_FALSE = 2
_V_INT = 3       # s64
_V_FLOAT = 4     # f64
_V_STR = 5
_V_BYTES = 6
_V_LIST = 7
_V_DICT = 8
_V_BIGINT = 9    # |v| >= 2^63: decimal string


def _json_key(k) -> str:
    """Coerce a dict key the way json.dumps does (parity requirement:
    binary and JSON envelopes must decode to identical payloads)."""
    if isinstance(k, str):
        return k
    if k is True:
        return "true"
    if k is False:
        return "false"
    if k is None:
        return "null"
    if isinstance(k, (int, float)):
        return repr(k) if isinstance(k, float) else str(k)
    raise TypeError(f"unencodable dict key: {k!r}")


def encode_value(e: "Encoder", v) -> None:
    if v is None:
        e.u8(_V_NONE)
    elif v is True:
        e.u8(_V_TRUE)
    elif v is False:
        e.u8(_V_FALSE)
    elif isinstance(v, int):
        if -(1 << 63) <= v < (1 << 63):
            e.u8(_V_INT).s64(v)
        else:
            e.u8(_V_BIGINT).string(str(v))
    elif isinstance(v, float):
        e.u8(_V_FLOAT).f64(v)
    elif isinstance(v, str):
        e.u8(_V_STR).string(v)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        e.u8(_V_BYTES).blob(v)
    elif isinstance(v, (list, tuple)):
        e.u8(_V_LIST).u32(len(v))
        for it in v:
            encode_value(e, it)
    elif isinstance(v, dict):
        e.u8(_V_DICT).u32(len(v))
        for k, val in v.items():
            e.string(_json_key(k))
            encode_value(e, val)
    else:
        raise TypeError(f"unencodable value: {type(v).__name__}")


def decode_value(d: "Decoder"):
    tag = d.u8()
    if tag == _V_NONE:
        return None
    if tag == _V_TRUE:
        return True
    if tag == _V_FALSE:
        return False
    if tag == _V_INT:
        return d.s64()
    if tag == _V_FLOAT:
        return d.f64()
    if tag == _V_STR:
        return d.string()
    if tag == _V_BYTES:
        return bytes(d.blob())
    if tag == _V_LIST:
        return [decode_value(d) for _ in range(d.u32())]
    if tag == _V_DICT:
        return {d.string(): decode_value(d) for _ in range(d.u32())}
    if tag == _V_BIGINT:
        return int(d.string())
    raise DecodeError(f"unknown value tag {tag}")


# The Encoder/Decoder-based encode_value/decode_value above are the
# readable spec (and what the golden corpus pins); the helpers below are
# byte-identical tight-loop implementations used on the per-op hot path,
# where this codec has to beat C json to be worth the wire flag.

_B_NONE = bytes((_V_NONE,))
_B_TRUE = bytes((_V_TRUE,))
_B_FALSE = bytes((_V_FALSE,))


def _enc_val(out: bytearray, v, pack=struct.pack) -> None:
    t = type(v)
    if t is str:
        b = v.encode("utf-8")
        out += pack("<BI", _V_STR, len(b))
        out += b
    elif t is int:
        if -(1 << 63) <= v < (1 << 63):
            out += pack("<Bq", _V_INT, v)
        else:
            b = str(v).encode("utf-8")
            out += pack("<BI", _V_BIGINT, len(b))
            out += b
    elif t is dict:
        out += pack("<BI", _V_DICT, len(v))
        for k, val in v.items():
            if type(k) is not str:
                k = _json_key(k)
            kb = k.encode("utf-8")
            out += pack("<I", len(kb))
            out += kb
            _enc_val(out, val)
    elif v is None:
        out += _B_NONE
    elif v is True:
        out += _B_TRUE
    elif v is False:
        out += _B_FALSE
    elif t is float:
        out += pack("<Bd", _V_FLOAT, v)
    elif t is list or t is tuple:
        out += pack("<BI", _V_LIST, len(v))
        for it in v:
            _enc_val(out, it)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        out += pack("<BI", _V_BYTES, len(v))
        out += v
    else:
        # subclasses (IntEnum, bools reached via int subtypes, str
        # subclasses...) — route through the generic spec encoder so the
        # bytes stay identical to encode_value's isinstance dispatch
        e = Encoder()
        encode_value(e, v)
        out += e.bytes()


def _dec_val(buf: bytes, off: int, unpack=struct.unpack_from):
    tag = buf[off]
    off += 1
    if tag == _V_DICT:
        (n,) = unpack("<I", buf, off)
        off += 4
        out = {}
        for _ in range(n):
            (kl,) = unpack("<I", buf, off)
            off += 4
            k = buf[off : off + kl].decode("utf-8")
            off += kl
            out[k], off = _dec_val(buf, off)
        return out, off
    if tag == _V_STR:
        (n,) = unpack("<I", buf, off)
        off += 4
        end = off + n
        if end > len(buf):
            raise DecodeError("string exceeds buffer")
        return buf[off:end].decode("utf-8"), end
    if tag == _V_INT:
        return unpack("<q", buf, off)[0], off + 8
    if tag == _V_LIST:
        (n,) = unpack("<I", buf, off)
        off += 4
        out = [None] * n
        for i in range(n):
            out[i], off = _dec_val(buf, off)
        return out, off
    if tag == _V_NONE:
        return None, off
    if tag == _V_TRUE:
        return True, off
    if tag == _V_FALSE:
        return False, off
    if tag == _V_FLOAT:
        return unpack("<d", buf, off)[0], off + 8
    if tag == _V_BYTES:
        (n,) = unpack("<I", buf, off)
        off += 4
        end = off + n
        if end > len(buf):
            raise DecodeError("blob exceeds buffer")
        return buf[off:end], end
    if tag == _V_BIGINT:
        (n,) = unpack("<I", buf, off)
        off += 4
        return int(buf[off : off + n].decode("utf-8")), off + n
    raise DecodeError(f"unknown value tag {tag}")


def encode_payload(obj) -> bytes:
    """One op payload as a self-contained versioned blob."""
    out = bytearray(6)  # envelope header patched in below
    _enc_val(out, obj)
    struct.pack_into("<BBI", out, 0, 1, 1, len(out) - 6)
    return bytes(out)


def decode_payload(raw) -> object:
    if not isinstance(raw, bytes):
        raw = bytes(raw)
    try:
        _ver, compat, _length = struct.unpack_from("<BBI", raw, 0)
        if compat > 1:
            raise DecodeError(f"struct compat {compat} > understood version 1")
        v, _ = _dec_val(raw, 6)
        return v
    except (struct.error, IndexError) as e:
        raise DecodeError(f"truncated payload: {e}") from e
