"""Perf counters — the reference's PerfCounters/PerfCountersCollection.

Re-expresses /root/reference/src/common/perf_counters.{h,cc}: subsystems
build a named counter block with `PerfCountersBuilder` (add_u64_counter /
add_u64 gauge / add_time_avg / add_histogram), bump them on the hot path
(`inc`, `set`, `tinc`, `hinc`), and operators read everything as the JSON tree
`perf dump` emits over the admin socket (avgcount/sum pairs for LONGRUNAVG,
perf_counters.cc:410-447).

Python-side cost discipline: counters are plain ints behind method calls; no
locks (the data path is single-process) and no formatting until dump time.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

U64 = "u64"           # gauge (PERFCOUNTER_U64)
U64_COUNTER = "ctr"   # monotonically increasing (| PERFCOUNTER_COUNTER)
TIME_AVG = "timeavg"  # (avgcount, sum-seconds) pair (PERFCOUNTER_LONGRUNAVG)
HISTOGRAM = "hist"    # value -> power-of-two bucket counts


@dataclass
class _Counter:
    type: str
    description: str = ""
    value: int = 0
    avgcount: int = 0
    sum: float = 0.0
    buckets: dict[int, int] = field(default_factory=dict)  # log2 -> count


class PerfCounters:
    """One named block of counters (reference: class PerfCounters)."""

    def __init__(self, name: str):
        self.name = name
        self._counters: dict[str, _Counter] = {}

    # -- builder ------------------------------------------------------------

    def add_u64(self, key: str, description: str = "") -> None:
        self._counters[key] = _Counter(U64, description)

    def add_u64_counter(self, key: str, description: str = "") -> None:
        self._counters[key] = _Counter(U64_COUNTER, description)

    def add_time_avg(self, key: str, description: str = "") -> None:
        self._counters[key] = _Counter(TIME_AVG, description)

    def add_histogram(self, key: str, description: str = "") -> None:
        self._counters[key] = _Counter(HISTOGRAM, description)

    # -- hot-path updates ---------------------------------------------------

    def inc(self, key: str, amount: int = 1) -> None:
        self._counters[key].value += amount

    def dec(self, key: str, amount: int = 1) -> None:
        self._counters[key].value -= amount

    def set(self, key: str, value: int) -> None:
        self._counters[key].value = value

    def set_max(self, key: str, value: int) -> None:
        """Raise a gauge to `value` only if it is higher — the
        peak/high-watermark gauge idiom (queue depth peaks, max backlog)
        that a plain `set` would overwrite on every sample."""
        c = self._counters[key]
        if value > c.value:
            c.value = value

    def tinc(self, key: str, seconds: float) -> None:
        c = self._counters[key]
        c.avgcount += 1
        c.sum += seconds

    def hinc(self, key: str, value: int) -> None:
        c = self._counters[key]
        bucket = max(0, int(value).bit_length() - 1)
        c.buckets[bucket] = c.buckets.get(bucket, 0) + 1

    def time(self, key: str):
        """Context manager: `with counters.time("op_latency"): ...`"""
        return _Timer(self, key)

    # -- dump ---------------------------------------------------------------

    def dump(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for key, c in self._counters.items():
            if c.type == TIME_AVG:
                out[key] = {"avgcount": c.avgcount, "sum": c.sum}
            elif c.type == HISTOGRAM:
                out[key] = {
                    str(1 << b): n for b, n in sorted(c.buckets.items())
                }
            else:
                out[key] = c.value
        return out

    def schema(self) -> dict[str, Any]:
        return {
            key: {"type": c.type, "description": c.description}
            for key, c in self._counters.items()
        }


class _Timer:
    def __init__(self, counters: PerfCounters, key: str):
        self._c, self._key = counters, key

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._c.tinc(self._key, time.perf_counter() - self._t0)
        return False


class PerfCountersCollection:
    """All blocks of a process (reference: PerfCountersCollection); `perf
    dump` walks every registered block."""

    def __init__(self):
        self._loggers: dict[str, PerfCounters] = {}

    def create(self, name: str) -> PerfCounters:
        logger = PerfCounters(name)
        self.add(logger)
        return logger

    def add(self, logger: PerfCounters) -> None:
        self._loggers[logger.name] = logger

    def remove(self, name: str) -> None:
        self._loggers.pop(name, None)

    def get(self, name: str) -> PerfCounters | None:
        return self._loggers.get(name)

    def dump(self) -> dict[str, Any]:
        return {name: l.dump() for name, l in sorted(self._loggers.items())}

    def schema(self) -> dict[str, Any]:
        return {name: l.schema() for name, l in sorted(self._loggers.items())}


#: process-wide default collection, like the CephContext-owned one
collection = PerfCountersCollection()
