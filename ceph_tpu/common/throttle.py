"""Throttle — counting backpressure, the reference's src/common/Throttle.

`Throttle(max)` admits up to `max` units; `get(c)` blocks while the budget
is exhausted (Throttle::get), `get_or_fail(c)` never blocks (Throttle.h's
get_or_fail), `put(c)` returns budget and wakes waiters. Used by the OSD and
messenger to bound in-flight bytes/ops; here it bounds whatever the host
orchestration wants to cap (e.g. concurrent recovery pushes under
osd_recovery_max_active)."""

from __future__ import annotations

import threading


class Throttle:
    def __init__(self, max_units: int, name: str = "throttle"):
        if max_units < 0:
            raise ValueError("max must be >= 0")
        self.name = name
        self._max = max_units
        self._count = 0
        self._cond = threading.Condition()

    @property
    def current(self) -> int:
        return self._count

    @property
    def max(self) -> int:
        return self._max

    def _should_wait(self, c: int) -> bool:
        # Throttle::_should_wait: a request larger than max is admitted
        # alone (when the throttle is empty) rather than deadlocking
        if not self._max:
            return False
        return (
            self._count + c > self._max
            and not (c > self._max and self._count == 0)
        )

    def get(self, c: int = 1, timeout: float | None = None) -> bool:
        """Block until `c` units fit; False on timeout."""
        with self._cond:
            ok = self._cond.wait_for(
                lambda: not self._should_wait(c), timeout=timeout
            )
            if not ok:
                return False
            self._count += c
            return True

    def get_or_fail(self, c: int = 1) -> bool:
        with self._cond:
            if self._should_wait(c):
                return False
            self._count += c
            return True

    def put(self, c: int = 1) -> int:
        with self._cond:
            if c > self._count:
                raise ValueError("putting back more than taken")
            self._count -= c
            self._cond.notify_all()
            return self._count

    def reset_max(self, max_units: int) -> None:
        with self._cond:
            self._max = max_units
            self._cond.notify_all()

    def __enter__(self):
        self.get()
        return self

    def __exit__(self, *exc):
        self.put()
        return False
