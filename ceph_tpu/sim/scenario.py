"""Deterministic event scripts + balancer convergence over synthetic maps.

run_scenario drives a build_cluster map through seeded churn epochs —
each epoch flaps OSDs out/in and reweights a few survivors, then remaps
every pool with the batched mapper and diffs placements against the
previous epoch: PGs whose up set changed are the backfill a real cluster
would schedule, and moved-PGs x bytes-per-PG is the storm estimate the
operator cares about. After the churn the batched balancer
(crush/balance.calc_pg_upmaps) runs and the report records convergence:
spread before/after, moves committed, rounds/launches spent.

Determinism contract: everything derives from numpy's seeded Generator
and the map's own placement function — the SAME seed and parameters
produce a byte-identical report. Wall-clock numbers (mapping rate,
balance time) exist only under measure=True and live in a separate
"timing" key so deterministic consumers can compare reports wholesale.
"""

from __future__ import annotations

import time

import numpy as np

from ceph_tpu.crush import balance
from ceph_tpu.osd.osdmap import CRUSH_ITEM_NONE, OSDMap
from ceph_tpu.sim.cluster import build_cluster


def _map_pools(osdmap: OSDMap) -> dict[int, np.ndarray]:
    return {
        pid: np.asarray(osdmap.pool_mappings(pid))
        for pid in sorted(osdmap.pools)
    }


def _spread(osdmap: OSDMap, rows: dict[int, np.ndarray]) -> float:
    """Max |PG-count deviation| from the weight-share target (the
    balancer's convergence metric, over the in+up devices)."""
    n = osdmap.max_osd
    weights = np.asarray(
        osdmap.osd_weight * (osdmap.osd_exists & osdmap.osd_up),
        dtype=np.int64,
    )
    wtotal = int(weights.sum())
    counts = np.zeros(n, dtype=np.int64)
    total = 0
    for pid, r in rows.items():
        pool = osdmap.pools[pid]
        total += pool.pg_num * pool.size
        flat = r[r != CRUSH_ITEM_NONE]
        counts += np.bincount(flat, minlength=n)[:n]
    if wtotal == 0 or total == 0:
        return 0.0
    target = weights.astype(np.float64) * (total / wtotal)
    mask = (weights > 0) | (counts > 0)
    return float(np.abs((counts - target)[mask]).max()) if mask.any() else 0.0


def run_scenario(
    n_osd: int = 64,
    osds_per_host: int = 8,
    hosts_per_rack: int = 4,
    rep_pg_num: int = 256,
    ec_pg_num: int = 128,
    seed: int = 1,
    epochs: int = 3,
    flap_fraction: float = 0.02,
    reweight_fraction: float = 0.02,
    bytes_per_pg: int = 8 << 30,
    balance_after: bool = True,
    max_deviation: float = 1.0,
    max_changes: int = 512,
    measure: bool = False,
) -> dict:
    """One full simulator run; returns the (deterministic) report dict."""
    t_start = time.perf_counter() if measure else 0.0
    rng = np.random.default_rng(seed)
    osdmap = build_cluster(
        n_osd, osds_per_host=osds_per_host, hosts_per_rack=hosts_per_rack,
        rep_pg_num=rep_pg_num, ec_pg_num=ec_pg_num,
    )
    report: dict = {
        "seed": int(seed),
        "osds": int(n_osd),
        "hosts": sum(
            1 for b in osdmap.crush.buckets.values() if b.type == 1
        ),
        "racks": sum(
            1 for b in osdmap.crush.buckets.values() if b.type == 3
        ),
        "pools": {
            str(pid): {
                "type": "erasure" if p.is_erasure() else "replicated",
                "pg_num": p.pg_num,
                "size": p.size,
            }
            for pid, p in sorted(osdmap.pools.items())
        },
        "pg_instances": sum(
            p.pg_num * p.size for p in osdmap.pools.values()
        ),
        "epochs": [],
    }

    t_map0 = time.perf_counter() if measure else 0.0
    rows = _map_pools(osdmap)
    map_seconds = (time.perf_counter() - t_map0) if measure else 0.0
    pgs_mapped = sum(r.shape[0] for r in rows.values())
    out: set[int] = set()

    for e in range(epochs):
        events: list[list] = []
        # flap out: healthy OSDs lose their in-weight this epoch
        alive = [o for o in range(n_osd) if o not in out]
        n_flap = max(1, int(n_osd * flap_fraction)) if alive else 0
        for o in rng.choice(
            alive, size=min(n_flap, len(alive)), replace=False
        ):
            o = int(o)
            osdmap.osd_weight[o] = 0
            out.add(o)
            events.append(["out", o])
        # flap back in: previously-out OSDs return at full weight
        returners = [o for o in sorted(out) if rng.random() < 0.5]
        for o in returners:
            osdmap.osd_weight[o] = 0x10000
            out.discard(o)
            events.append(["in", o])
        # reweight: a few survivors drop to a random fraction
        alive = [o for o in range(n_osd) if o not in out]
        n_rw = max(1, int(n_osd * reweight_fraction)) if alive else 0
        for o in rng.choice(
            alive, size=min(n_rw, len(alive)), replace=False
        ):
            o = int(o)
            frac = 0.5 + 0.5 * float(rng.random())
            osdmap.osd_weight[o] = int(frac * 0x10000)
            events.append(["reweight", o, round(frac, 4)])
        osdmap.epoch += 1

        t0 = time.perf_counter() if measure else 0.0
        new_rows = _map_pools(osdmap)
        if measure:
            map_seconds += time.perf_counter() - t0
        pgs_mapped += sum(r.shape[0] for r in new_rows.values())
        moved = sum(
            int((new_rows[pid] != rows[pid]).any(axis=1).sum())
            for pid in rows
        )
        rows = new_rows
        report["epochs"].append({
            "epoch": e + 1,
            "events": events,
            "pgs_moved": moved,
            "bytes_moved": moved * int(bytes_per_pg),
        })

    if balance_after:
        t0 = time.perf_counter() if measure else 0.0
        changes = osdmap.calc_pg_upmaps(
            max_deviation=max_deviation, max_changes=max_changes
        )
        balance_seconds = (time.perf_counter() - t0) if measure else 0.0
        r = osdmap.last_balance
        rows = _map_pools(osdmap)
        report["balance"] = {
            "changes": int(changes),
            "rounds": int(r.rounds),
            "launches": int(r.launches),
            "spread_before": float(r.spread_before),
            "spread_after": float(r.spread_after),
            "converged": bool(r.spread_after <= max_deviation),
            "upmap_entries": len(osdmap.pg_upmap_items),
        }
        if measure:
            report.setdefault("timing", {})[
                "balance_seconds"
            ] = balance_seconds
            report["timing"]["score_seconds"] = float(r.score_seconds)
    report["final_spread"] = _spread(osdmap, rows)

    if measure:
        timing = report.setdefault("timing", {})
        timing["map_seconds"] = map_seconds
        timing["pgs_mapped"] = int(pgs_mapped)
        timing["pgs_mapped_per_s"] = (
            pgs_mapped / map_seconds if map_seconds > 0 else 0.0
        )
        timing["total_seconds"] = time.perf_counter() - t_start
    return report
