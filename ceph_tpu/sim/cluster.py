"""Synthetic cluster maps for the placement simulator.

build_cluster grows the osdmaptool build_simple shape to reference
scale: OSDs under hosts under racks under one root (straw2 all the way),
a replicated chooseleaf-firstn-host rule, an erasure chooseleaf-indep-
host rule, and both pool kinds — the map a thousand-OSD production
cluster actually hands the balancer.
"""

from __future__ import annotations

from ceph_tpu.crush import builder as cb
from ceph_tpu.crush.types import BucketAlg, CrushMap, Tunables
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import TYPE_ERASURE, TYPE_REPLICATED, PgPool

#: CRUSH type ids (the reference's default types table)
TYPE_OSD, TYPE_HOST, TYPE_RACK, TYPE_ROOT = 0, 1, 3, 10

REP_RULE, EC_RULE = 0, 1


def build_cluster(
    n_osd: int,
    osds_per_host: int = 8,
    hosts_per_rack: int = 4,
    rep_pg_num: int = 0,
    rep_size: int = 3,
    ec_pg_num: int = 0,
    ec_k: int = 4,
    ec_m: int = 2,
) -> OSDMap:
    """An OSDMap with osd -> host -> rack -> root hierarchy and (when the
    pg counts are non-zero) pool 1 replicated / pool 2 erasure.

    Bucket ids: hosts -(2+h), racks then root below those — ids only
    need to be unique and negative. Every bucket is straw2 so the
    batched mapper's fast path covers the whole map.
    """
    cmap = CrushMap(tunables=Tunables.jewel())
    cmap.type_names = {
        TYPE_OSD: "osd", TYPE_HOST: "host",
        TYPE_RACK: "rack", TYPE_ROOT: "root",
    }
    n_hosts = max(1, (n_osd + osds_per_host - 1) // osds_per_host)
    host_ids, host_ws = [], []
    osd = 0
    for h in range(n_hosts):
        items = list(range(osd, min(osd + osds_per_host, n_osd)))
        if not items:
            break
        osd += len(items)
        b = cb.make_bucket(
            cmap, -(2 + h), BucketAlg.STRAW2, TYPE_HOST, items,
            [0x10000] * len(items),
        )
        cmap.item_names[b.id] = f"host{h}"
        host_ids.append(b.id)
        host_ws.append(b.weight)
    n_racks = max(1, (len(host_ids) + hosts_per_rack - 1) // hosts_per_rack)
    rack_ids, rack_ws = [], []
    for r in range(n_racks):
        hs = host_ids[r * hosts_per_rack : (r + 1) * hosts_per_rack]
        if not hs:
            break
        ws = host_ws[r * hosts_per_rack : (r + 1) * hosts_per_rack]
        b = cb.make_bucket(
            cmap, -(2 + n_hosts + r), BucketAlg.STRAW2, TYPE_RACK, hs, ws,
        )
        cmap.item_names[b.id] = f"rack{r}"
        rack_ids.append(b.id)
        rack_ws.append(b.weight)
    root = cb.make_bucket(
        cmap, -1, BucketAlg.STRAW2, TYPE_ROOT, rack_ids, rack_ws
    )
    cmap.item_names[root.id] = "default"
    for o in range(n_osd):
        cmap.item_names[o] = f"osd.{o}"

    # replicas spread across HOSTS (racks would cap rep_size at the rack
    # count; host is the reference's default failure domain)
    cb.make_simple_rule(cmap, REP_RULE, -1, TYPE_HOST, "firstn", 0)
    cmap.rule_names[REP_RULE] = "replicated_rule"
    cb.make_simple_rule(cmap, EC_RULE, -1, TYPE_HOST, "indep", 0)
    cmap.rule_names[EC_RULE] = "erasure_rule"

    m = OSDMap(crush=cmap, max_osd=n_osd)
    if rep_pg_num:
        m.pools[1] = PgPool(
            pg_num=rep_pg_num, size=rep_size, min_size=2,
            type=TYPE_REPLICATED, crush_rule=REP_RULE,
        )
    if ec_pg_num:
        m.pools[2] = PgPool(
            pg_num=ec_pg_num, size=ec_k + ec_m, min_size=ec_k + 1,
            type=TYPE_ERASURE, crush_rule=EC_RULE,
        )
    return m
