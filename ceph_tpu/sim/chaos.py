"""Deterministic chaos scenarios — one seeded script, two executors.

`chaos_script` compiles a seed into a fixed timeline of chaos events:
OSD flaps, an asymmetric (one-way) partition, a kill -9 of a backfill
source mid-push, and probabilistic wire-fault storms.  Every event
carries the `ms_inject_chaos_schedule` string that arms it on a live
fleet, so the SAME script drives both executors:

* `tools/chaos_tool.py` runs it against a live MiniCluster — real
  daemons, real TCP, a consistency oracle asserting zero acked-data
  loss, convergence to clean, and bounded client p99;
* `run_chaos` (this module) replays it daemon-free over a
  `build_cluster` map and reports the placement-level damage — degraded
  PGs/objects per step, placement moves, the recovery debt an amnesiac
  kill creates — plus the exact wire-fault decision stream every armed
  (src, dst) pair would draw from `common/faults.py`.

Determinism contract (same as scenario.run_scenario): everything
derives from `random.Random(seed)` and the map's placement function, so
one seed produces a byte-identical script and report.  Wall-clock
numbers exist only under measure=True in a separate "timing" key.
"""

from __future__ import annotations

import random
import time

from ceph_tpu.common.faults import WireFaults
from ceph_tpu.sim.cluster import build_cluster
from ceph_tpu.sim.scenario import _map_pools

#: frames each armed (src, dst) pair is judged per step in run_chaos —
#: enough draws that probabilistic rules show up in the histogram
FRAMES_PER_PAIR = 16

#: redundancy floor the script promises never to exceed concurrently
#: (rep size 3 and EC m=2 both absorb two simultaneous losses)
MAX_CONCURRENT_DOWN = 2


def chaos_script(seed: int, n_osd: int = 6, steps: int = 8) -> dict:
    """Compile `seed` into a deterministic chaos timeline.

    The first three events always cover the crash matrix — a flap, a
    one-way partition, and a kill -9 of a backfill source — in a
    seed-shuffled order; remaining steps draw from the full menu.
    `fallback_osd` on the kill event is the victim when the live
    executor finds no backfill in flight at that moment.
    """
    rng = random.Random(int(seed))
    osds = list(range(n_osd))
    steps = max(3, int(steps))
    kinds = ["flap", "partition_oneway", "kill_backfill_source"]
    rng.shuffle(kinds)
    menu = ["flap", "partition_sym", "storm_drop", "storm_delay",
            "storm_dup"]
    while len(kinds) < steps:
        kinds.append(rng.choice(menu))

    events: list[dict] = []
    down_until: dict[int, int] = {}  # osd -> first step it is back
    for step, kind in enumerate(kinds):
        alive = [o for o in osds if down_until.get(o, 0) <= step]
        n_down = sum(1 for s in down_until.values() if s > step)
        if kind == "flap":
            if n_down >= MAX_CONCURRENT_DOWN or not alive:
                continue  # redundancy floor: skip this flap
            osd = rng.choice(alive)
            d = rng.randint(1, 2)
            down_until[osd] = step + 1 + d
            events.append({
                "step": step, "kind": "flap", "osd": osd,
                "down_steps": d,
            })
        elif kind == "kill_backfill_source":
            if n_down >= MAX_CONCURRENT_DOWN or not alive:
                continue
            osd = rng.choice(alive)
            d = rng.randint(1, 2)
            down_until[osd] = step + 1 + d
            events.append({
                "step": step, "kind": "kill_backfill_source",
                "fallback_osd": osd, "down_steps": d,
            })
        elif kind in ("partition_oneway", "partition_sym"):
            if len(alive) < 2:
                continue
            a, b = rng.sample(alive, 2)
            hold = rng.randint(1, 2)
            if kind == "partition_oneway":
                sched = f"partition:osd.{a}>osd.{b}"
            else:
                sched = f"partition:osd.{a}|osd.{b}"
            events.append({
                "step": step, "kind": kind, "src": a, "dst": b,
                "hold_steps": hold, "schedule": sched,
            })
        else:  # storm_drop / storm_delay / storm_dup
            target = rng.choice(osds)
            prob = round(rng.uniform(0.05, 0.25), 3)
            hold = rng.randint(1, 2)
            fault = kind.split("_", 1)[1]
            sched = f"{fault}:osd.*>osd.{target}:{prob}"
            events.append({
                "step": step, "kind": kind, "target": target,
                "prob": prob, "hold_steps": hold, "schedule": sched,
            })
    return {
        "seed": int(seed), "n_osd": int(n_osd), "steps": steps,
        "events": events,
    }


def _pairs_for(event: dict, n_osd: int) -> list[tuple[str, str]]:
    """Concrete (src, dst) messenger-name pairs an armed event covers."""
    if event["kind"] == "partition_oneway":
        return [(f"osd.{event['src']}", f"osd.{event['dst']}")]
    if event["kind"] == "partition_sym":
        return [
            (f"osd.{event['src']}", f"osd.{event['dst']}"),
            (f"osd.{event['dst']}", f"osd.{event['src']}"),
        ]
    t = event["target"]
    return [
        (f"osd.{i}", f"osd.{t}") for i in range(n_osd) if i != t
    ]


def run_chaos(
    seed: int = 1,
    n_osd: int = 16,
    osds_per_host: int = 4,
    rep_pg_num: int = 32,
    ec_pg_num: int = 16,
    steps: int = 8,
    objects_per_pg: int = 64,
    measure: bool = False,
) -> dict:
    """Daemon-free replay of `chaos_script(seed)`: placement damage plus
    wire-fault decision histograms, byte-identical per seed."""
    t0 = time.perf_counter() if measure else 0.0
    script = chaos_script(seed, n_osd=n_osd, steps=steps)
    osdmap = build_cluster(
        n_osd, osds_per_host=osds_per_host,
        rep_pg_num=rep_pg_num, ec_pg_num=ec_pg_num,
    )
    rows = _map_pools(osdmap)
    by_step: dict[int, list[dict]] = {}
    for e in script["events"]:
        by_step.setdefault(e["step"], []).append(e)

    report: dict = {
        "seed": int(seed), "osds": int(n_osd),
        "script_events": len(script["events"]),
        "steps": [],
    }
    down_until: dict[int, int] = {}     # osd -> step it revives
    amnesiac: set[int] = set()          # kill -9 victims (store lost)
    armed: list[tuple[dict, int, WireFaults]] = []  # (event, until, wf)
    max_down = 0

    for step in range(script["steps"] + 3):  # +3 drains the tail
        # revivals due this step (amnesiac victims return empty:
        # their whole placement share is recovery debt)
        recovery_debt = 0
        for osd, until in sorted(down_until.items()):
            if until == step:
                osdmap.osd_weight[osd] = 0x10000
                if osd in amnesiac:
                    amnesiac.discard(osd)
                    owned = sum(
                        int((r == osd).any(axis=1).sum())
                        for r in rows.values()
                    )
                    recovery_debt = owned * objects_per_pg
        down_until = {o: u for o, u in down_until.items() if u > step}
        armed = [(e, u, wf) for e, u, wf in armed if u > step]

        entry: dict = {"step": step, "events": []}
        degraded_pgs = 0
        for e in by_step.get(step, ()):  # arm this step's events
            entry["events"].append(e)
            if e["kind"] in ("flap", "kill_backfill_source"):
                osd = e.get("osd", e.get("fallback_osd"))
                degraded_pgs += sum(
                    int((r == osd).any(axis=1).sum())
                    for r in rows.values()
                )
                osdmap.osd_weight[osd] = 0
                down_until[osd] = step + 1 + e["down_steps"]
                if e["kind"] == "kill_backfill_source":
                    amnesiac.add(osd)
            else:
                armed.append((
                    e, step + e["hold_steps"],
                    WireFaults(e["schedule"], seed=script["seed"]),
                ))
        max_down = max(max_down, len(down_until))

        # every armed schedule judges FRAMES_PER_PAIR frames per
        # concrete pair this step — the deterministic decision stream a
        # live fleet would draw
        wire = {"drop": 0, "delay": 0, "dup": 0, "none": 0}
        for e, _until, wf in armed:
            for src, dst in _pairs_for(e, n_osd):
                pf = wf.pair(src, dst)
                for _ in range(FRAMES_PER_PAIR):
                    act = pf.next_action() if pf else None
                    wire[act[0] if act else "none"] += 1

        osdmap.epoch += 1
        new_rows = _map_pools(osdmap)
        moved = sum(
            int((new_rows[pid] != rows[pid]).any(axis=1).sum())
            for pid in rows
        )
        rows = new_rows
        entry.update({
            "pgs_degraded": degraded_pgs,
            "objects_degraded": degraded_pgs * objects_per_pg,
            "recovery_debt_objects": recovery_debt,
            "pgs_moved": moved,
            "wire_decisions": wire,
        })
        report["steps"].append(entry)

    report["final"] = {
        "max_concurrent_down": max_down,
        "data_safe": max_down <= MAX_CONCURRENT_DOWN,
        "converged": not down_until and not armed,
    }
    if measure:
        report["timing"] = {
            "total_seconds": time.perf_counter() - t0,
        }
    return report
