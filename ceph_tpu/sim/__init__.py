"""ceph_tpu.sim — the big-cluster placement simulator.

The standing rig for exercising placement at reference scale without
daemons: synthetic thousand-OSD maps with a host/rack hierarchy
(cluster.build_cluster), deterministic seeded event scripts — OSD flaps
out/in, reweights, map churn epochs — with per-epoch backfill-storm
estimation, and batched-balancer convergence reporting
(scenario.run_scenario). tools/psim.py is the CLI front.

Everything is seeded and wall-clock free: the same seed produces a
byte-identical report (timing fields appear only under measure=True),
so tier-1 can assert on a mini scenario while the bench drives the
1000-OSD / million-PG scale.
"""

from ceph_tpu.sim.chaos import chaos_script, run_chaos
from ceph_tpu.sim.cluster import build_cluster
from ceph_tpu.sim.scenario import run_scenario

__all__ = ["build_cluster", "chaos_script", "run_chaos", "run_scenario"]
