/* ceph_crc32c — CRC-32C (Castagnoli) with the reference's conventions:
 * reflected, NO final inversion, caller seeds (-1 for bufferhash).
 * Mirrors /root/reference/src/common/sctp_crc32.c semantics (the table
 * algorithm re-derived, nothing copied); slicing-by-8 so the messenger's
 * per-frame checksum and the scrubber's shard hashes run at C speed.
 *
 * Built by ceph_tpu/native/build.py into libcrc32c.so and loaded with
 * ctypes (ceph_tpu/common/crc.py); the numpy path is the fallback.
 */

#include <stddef.h>
#include <stdint.h>

static uint32_t table[8][256];
static int initialized = 0;

static void init_tables(void) {
    const uint32_t poly = 0x82F63B78u; /* reflected 0x1EDC6F41 */
    for (int n = 0; n < 256; n++) {
        uint32_t c = (uint32_t)n;
        for (int i = 0; i < 8; i++)
            c = (c & 1) ? (c >> 1) ^ poly : c >> 1;
        table[0][n] = c;
    }
    for (int k = 1; k < 8; k++)
        for (int n = 0; n < 256; n++)
            table[k][n] = table[0][table[k - 1][n] & 0xFF]
                          ^ (table[k - 1][n] >> 8);
    initialized = 1;
}

uint32_t ceph_crc32c_native(uint32_t seed, const uint8_t *data,
                            size_t len) {
    if (!initialized)
        init_tables();
    uint32_t crc = seed;
    while (len >= 8) {
        crc = table[7][(crc ^ data[0]) & 0xFF]
            ^ table[6][((crc >> 8) ^ data[1]) & 0xFF]
            ^ table[5][((crc >> 16) ^ data[2]) & 0xFF]
            ^ table[4][((crc >> 24) ^ data[3]) & 0xFF]
            ^ table[3][data[4]] ^ table[2][data[5]]
            ^ table[1][data[6]] ^ table[0][data[7]];
        data += 8;
        len -= 8;
    }
    while (len--) {
        crc = table[0][(crc ^ *data++) & 0xFF] ^ (crc >> 8);
    }
    return crc;
}
