"""Native (C++) components: the erasure-code plugin ABI and CPU codec.

The reference's native layer ships codecs as dlopened libec_*.so plugins
(ErasureCodePlugin.cc); this package holds the framework's equivalents —
ec_plugin.cpp (GF(2^8) RS codec behind the same version/init/register
handshake) and build.py (the g++ build driver). Python-side loading lives in
ceph_tpu.ec.native.
"""
