"""Build driver for the native components: g++ -> libec_<name>.so.

The reference ships its native codecs as autotools/cmake targets producing
libec_*.so under <libdir>/erasure-code (loaded by ErasureCodePluginRegistry
at runtime); here a single g++ invocation produces the same artifact shape
next to the sources, rebuilt only when the source is newer (the pattern the
test oracle shim uses, tests/c_oracle). No compiler -> None, and callers
surface the reference's dlopen error path.
"""

from __future__ import annotations

import os
import shutil
import subprocess

NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))

#: the reference's naming contract: PLUGIN_PREFIX "libec_" PLUGIN_SUFFIX ".so"
PLUGIN_PREFIX = "libec_"
PLUGIN_SUFFIX = ".so"


def plugin_path(name: str, directory: str | None = None) -> str:
    return os.path.join(
        directory or NATIVE_DIR, f"{PLUGIN_PREFIX}{name}{PLUGIN_SUFFIX}"
    )


def build_shared(name: str, source: str) -> str | None:
    """Compile a standalone helper .so (crc32c etc.); returns the path or
    None without a toolchain. Same rebuild-on-mtime rule as plugins."""
    cc = shutil.which("cc") or shutil.which("gcc") or shutil.which("g++")
    if cc is None:
        return None
    out = os.path.join(NATIVE_DIR, f"lib{name}.so")
    if (
        os.path.exists(out)
        and os.path.getmtime(out) >= os.path.getmtime(source)
    ):
        return out
    try:
        subprocess.run(
            [cc, "-O3", "-shared", "-fPIC", "-o", out, source],
            check=True, capture_output=True, text=True,
        )
    except subprocess.CalledProcessError:
        return None
    return out


def build_plugin(
    name: str = "native",
    source: str | None = None,
    directory: str | None = None,
) -> str | None:
    """Compile `source` into libec_<name>.so; returns the path or None when
    no toolchain is available. Rebuilds only when the source is newer."""
    cxx = shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        return None
    source = source or os.path.join(NATIVE_DIR, "ec_plugin.cpp")
    out = plugin_path(name, directory)
    if (
        os.path.exists(out)
        and os.path.getmtime(out) >= os.path.getmtime(source)
    ):
        return out
    from ceph_tpu import __version__

    cmd = [
        cxx, "-O3", "-shared", "-fPIC", "-std=c++17",
        f'-DCEPH_TPU_PLUGIN_VERSION="ceph-tpu-{__version__}"',
        "-o", out, source,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError as e:
        # never fall back silently to a stale .so: surface the diagnostics
        raise RuntimeError(
            f"building {out} failed:\n{e.stderr}"
        ) from None
    return out
