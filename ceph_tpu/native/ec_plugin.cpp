// libec_native: the framework's native-code erasure-code plugin.
//
// Implements the reference's dlopen plugin ABI (ErasureCodePlugin.cc:126-180 /
// ErasureCodePlugin.h:24-27): the loader dlopens libec_<name>.so, checks
// __erasure_code_version() against its own version string, calls
// __erasure_code_init(name, directory), and then asks for the registered
// entry points. The reference's plugins register a C++ factory with an
// in-process registry; here registration is exposing a C vtable
// (__erasure_code_ops) the Python loader binds with ctypes — same contract
// (init that "forgets" to register is detected), C ABI instead of C++.
//
// The codec is a straightforward GF(2^8) matrix RS coder over the same
// matrix families as the Python/TPU `isa` codec (gf_gen_rs_matrix /
// gf_gen_cauchy1_matrix semantics, ErasureCodeIsa.cc:384-393), so its output
// is asserted bit-identical to the TPU kernels in tests — the CPU fallback
// backend for hosts without an accelerator, and the in-repo native analogue
// of the reference's vendored ISA-L/jerasure codecs.
//
// Build: ceph_tpu/native/build.py (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// -- GF(2^8), polynomial 0x11d (matches ceph_tpu.ops.gf) ---------------------

uint8_t gf_exp[512];
uint8_t gf_log[256];
uint8_t gf_inv_tbl[256];
bool tables_ready = false;

void build_tables() {
  if (tables_ready) return;
  int x = 1;
  for (int i = 0; i < 255; i++) {
    gf_exp[i] = (uint8_t)x;
    gf_log[x] = (uint8_t)i;
    x <<= 1;
    if (x & 0x100) x ^= 0x11d;
  }
  for (int i = 255; i < 512; i++) gf_exp[i] = gf_exp[i - 255];
  gf_log[0] = 0;
  gf_inv_tbl[0] = 0;
  for (int i = 1; i < 256; i++) gf_inv_tbl[i] = gf_exp[255 - gf_log[i]];
  tables_ready = true;
}

inline uint8_t gf_mul(uint8_t a, uint8_t b) {
  if (!a || !b) return 0;
  return gf_exp[gf_log[a] + gf_log[b]];
}

inline uint8_t gf_div(uint8_t a, uint8_t b) {
  if (!a) return 0;
  return gf_exp[(gf_log[a] + 255 - gf_log[b]) % 255];
}

// -- coding matrices (ErasureCodeIsa.cc:384-393 semantics) -------------------

// gf_gen_rs_matrix parity rows: row i = powers of 2^i
void vandermonde_parity(int k, int m, uint8_t* out) {
  uint8_t gen = 1;
  for (int i = 0; i < m; i++) {
    uint8_t p = 1;
    for (int j = 0; j < k; j++) {
      out[i * k + j] = p;
      p = gf_mul(p, gen);
    }
    gen = gf_mul(gen, 2);
  }
}

// gf_gen_cauchy1_matrix parity rows: a[i][j] = inv((k+i) ^ j)
void cauchy_parity(int k, int m, uint8_t* out) {
  for (int i = 0; i < m; i++)
    for (int j = 0; j < k; j++)
      out[i * k + j] = gf_inv_tbl[(uint8_t)((k + i) ^ j)];
}

// Gauss-Jordan inversion over GF(2^8); returns false when singular
bool gf_invert(std::vector<uint8_t>& a, int n, std::vector<uint8_t>& inv) {
  inv.assign(n * n, 0);
  for (int i = 0; i < n; i++) inv[i * n + i] = 1;
  for (int col = 0; col < n; col++) {
    int pivot = -1;
    for (int row = col; row < n; row++)
      if (a[row * n + col]) { pivot = row; break; }
    if (pivot < 0) return false;
    if (pivot != col) {
      for (int j = 0; j < n; j++) {
        std::swap(a[col * n + j], a[pivot * n + j]);
        std::swap(inv[col * n + j], inv[pivot * n + j]);
      }
    }
    uint8_t d = a[col * n + col];
    for (int j = 0; j < n; j++) {
      a[col * n + j] = gf_div(a[col * n + j], d);
      inv[col * n + j] = gf_div(inv[col * n + j], d);
    }
    for (int row = 0; row < n; row++) {
      uint8_t f = a[row * n + col];
      if (row == col || !f) continue;
      for (int j = 0; j < n; j++) {
        a[row * n + j] ^= gf_mul(f, a[col * n + j]);
        inv[row * n + j] ^= gf_mul(f, inv[col * n + j]);
      }
    }
  }
  return true;
}

// -- codec instances ---------------------------------------------------------

struct Codec {
  int k = 0, m = 0;
  std::vector<uint8_t> gen;  // (k+m, k) systematic generator
};

std::vector<Codec*> instances;

// region op: out[.] ^= gf_mul(c, in[.]) via a 256-byte product table
void mul_acc_region(uint8_t c, const uint8_t* in, uint8_t* out, size_t len) {
  if (!c) return;
  uint8_t tbl[256];
  tbl[0] = 0;
  for (int v = 1; v < 256; v++) tbl[v] = gf_exp[gf_log[c] + gf_log[v]];
  for (size_t i = 0; i < len; i++) out[i] ^= tbl[in[i]];
}

}  // namespace

extern "C" {

// version handshake (reference: __erasure_code_version vs CEPH_GIT_NICE_VER,
// ErasureCodePlugin.cc:140-149); build.py injects the package version so
// there is a single source of truth (ceph_tpu.__version__)
#ifndef CEPH_TPU_PLUGIN_VERSION
#define CEPH_TPU_PLUGIN_VERSION "ceph-tpu-unversioned"
#endif
const char* __erasure_code_version() { return CEPH_TPU_PLUGIN_VERSION; }

static bool initialized = false;

int __erasure_code_init(const char* plugin_name, const char* directory) {
  (void)plugin_name;
  (void)directory;
  build_tables();
  initialized = true;
  return 0;
}

// create a codec: technique 0 = vandermonde (reed_sol_van), 1 = cauchy.
// Returns a handle >= 0, or -EINVAL (-22) on bad parameters.
int ec_create(int k, int m, int technique) {
  if (!initialized || k < 2 || m < 1 || k + m > 256) return -22;
  if (technique != 0 && technique != 1) return -22;
  Codec* c = new Codec;
  c->k = k;
  c->m = m;
  c->gen.assign((k + m) * k, 0);
  for (int i = 0; i < k; i++) c->gen[i * k + i] = 1;
  if (technique == 0)
    vandermonde_parity(k, m, c->gen.data() + k * k);
  else
    cauchy_parity(k, m, c->gen.data() + k * k);
  instances.push_back(c);
  return (int)instances.size() - 1;
}

void ec_destroy(int h) {
  if (h >= 0 && h < (int)instances.size() && instances[h]) {
    delete instances[h];
    instances[h] = nullptr;
  }
}

// data: k contiguous chunks of chunk_len; parity: m contiguous chunks (out)
int ec_encode(int h, const uint8_t* data, uint8_t* parity, size_t chunk_len) {
  if (h < 0 || h >= (int)instances.size() || !instances[h]) return -22;
  Codec* c = instances[h];
  std::memset(parity, 0, (size_t)c->m * chunk_len);
  for (int i = 0; i < c->m; i++)
    for (int j = 0; j < c->k; j++)
      mul_acc_region(c->gen[(c->k + i) * c->k + j], data + j * chunk_len,
                     parity + i * chunk_len, chunk_len);
  return 0;
}

// Rebuild `targets` from the first k of `present` (logical chunk indices,
// ascending): survivors are n_present contiguous chunks in `present` order.
// Mirrors the reference's decode-table construction (ErasureCodeIsa.cc:
// 253-302): invert the survivor rows of the generator, then lost-data rows
// come from the inverse and lost-coding rows from gen_row @ inverse.
int ec_decode(int h, const int* present, int n_present, const int* targets,
              int n_targets, const uint8_t* survivors, uint8_t* out,
              size_t chunk_len) {
  if (h < 0 || h >= (int)instances.size() || !instances[h]) return -22;
  Codec* c = instances[h];
  int k = c->k;
  if (n_present < k) return -5;  // EIO: not enough survivors
  std::vector<uint8_t> b(k * k);
  for (int r = 0; r < k; r++)
    for (int j = 0; j < k; j++) b[r * k + j] = c->gen[present[r] * k + j];
  std::vector<uint8_t> inv;
  if (!gf_invert(b, k, inv)) return -5;
  for (int t = 0; t < n_targets; t++) {
    std::vector<uint8_t> row(k);
    if (targets[t] < k) {
      for (int j = 0; j < k; j++) row[j] = inv[targets[t] * k + j];
    } else {
      for (int j = 0; j < k; j++) {
        uint8_t acc = 0;
        for (int l = 0; l < k; l++)
          acc ^= gf_mul(c->gen[targets[t] * k + l], inv[l * k + j]);
        row[j] = acc;
      }
    }
    uint8_t* dst = out + (size_t)t * chunk_len;
    std::memset(dst, 0, chunk_len);
    for (int j = 0; j < k; j++)
      mul_acc_region(row[j], survivors + (size_t)j * chunk_len, dst,
                     chunk_len);
  }
  return 0;
}

// registration: the loader asks for the ops table after init; returning the
// entry points is this ABI's equivalent of the reference plugin calling
// registry.add() — a plugin whose init "succeeds" but exposes no ops is
// rejected with the reference's "did not register" error.
struct ec_plugin_ops {
  int (*create)(int, int, int);
  void (*destroy)(int);
  int (*encode)(int, const uint8_t*, uint8_t*, size_t);
  int (*decode)(int, const int*, int, const int*, int, const uint8_t*,
                uint8_t*, size_t);
};

static const ec_plugin_ops OPS = {ec_create, ec_destroy, ec_encode, ec_decode};

const ec_plugin_ops* __erasure_code_ops() {
  return initialized ? &OPS : nullptr;
}

}  // extern "C"
