"""ObjectStore: the OSD's transactional persistence contract, two backends.

The reference's `ObjectStore` interface (src/os/ObjectStore.h +
Transaction.h) is the OSD's only persistence contract: every mutation —
object data, xattrs, omap, collection membership, and the PG log itself —
rides one `Transaction` applied atomically, which is what makes PG state
crash-consistent (SURVEY §5 checkpoint/resume: durability *is* the
transaction log). Implementations differ in media: BlueStore (raw block),
FileStore, MemStore, and KStore, which stores everything in the KV layer.

Two backends implement the contract here, selected by the
`osd_objectstore` config option (`create_store`):

  * `KStore` (this module; src/os/kstore design): objects, attrs, and omap
    are rows in a `KeyValueDB`, a Transaction compiles to one KV batch, and
    the KV WAL (ceph_tpu.common.kv.FileDB) provides atomicity + crash
    recovery. Backed by `MemDB` it is the MemStore equivalent; backed by
    `FileDB` it survives process death — an OSD daemon reopening its store
    resumes from the last committed transaction exactly like an OSD restart
    replaying its journal.
  * `BlockStore` (ceph_tpu.osd.blockstore; src/os/bluestore design): object
    *data* lives as allocator-managed extents in a raw block file with a
    crc32c per checksum block, verified on every read; *metadata* (onode
    extent maps, attrs, omap, the free list) stays in the KV layer —
    BlueStore's data/RocksDB split. Sub-min_alloc writes ride the KV WAL
    batch (deferred writes) and `fsck(deep=True)` re-reads every blob
    against its stored checksum.

The per-op compilation is factored through `_compile_op`/`_begin_batch`/
`_commit_batch` so BlockStore overrides only the data-bearing ops and
inherits collection/attr/omap handling unchanged.

Object identity is (collection, name) where a collection is a PG
(coll_t, src/osd/osd_types.h); keys are denc-encoded so ordered KV
iteration yields collection listings.
"""

from __future__ import annotations

from ceph_tpu.common.encoding import Decoder, Encoder
from ceph_tpu.common.kv import KeyValueDB, KVTransaction, MemDB

_DATA = b"dat"  # object payload rows
_ATTR = b"atr"  # xattr rows
_OMAP = b"omp"  # omap rows
_COLL = b"col"  # collection existence rows


class StoreError(Exception):
    """Typed store error — the errno taxonomy the OSD's handling keys on:

      * ``ENOENT`` / ``EEXIST`` — namespace errors, client-visible as-is.
      * ``ENOSPC`` — allocation failed against a capacity-capped device.
        Transient by contract: nothing is fenced, reads keep working, and
        frees make the store writable again.
      * ``EIO`` on a READ — at-rest corruption or a device read error.
        Recoverable above the store: the primary heals the object from
        replicas/EC survivors before the client ever sees it.
      * ``EIO`` on a WRITE/FSYNC path — raised as `StoreFatalError`: the
        store can no longer promise that an ack implies durability, so it
        fences itself (fail-stop) and the owning daemon must go down.
      * ``EROFS`` — the store is already fenced; every further write is
        refused up front so no ack can lie about durability.
    """

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code  # "ENOENT" | "EEXIST" | "ENOSPC" | "EIO" | "EROFS"


class StoreFatalError(StoreError):
    """A write-path or fsync device error (the fail-stop class): the
    lesson of Rebello et al., "Can Applications Recover from fsync
    Failures?" (ATC '20) layered on RADOS's fail-stop OSD assumption —
    an fsync error must never be retried-and-forgotten. The store fences
    itself before raising this; the owning OSD reports itself to the mon
    and shuts down rather than ack another write."""


def _okey(coll: str, name: str, extra: bytes = b"") -> bytes:
    return Encoder().string(coll).string(name).raw(extra).bytes()


def _okey_decode(key: bytes) -> tuple[str, str]:
    d = Decoder(key)
    return d.string(), d.string()


class Transaction:
    """An ordered op list applied atomically (ObjectStore::Transaction).

    Ops mirror the reference's: create/remove collection, write (full
    object — the EC data path always writes whole shards), remove, setattrs,
    omap set/rm. `touch` is write-if-absent of an empty object."""

    def __init__(self) -> None:
        self.ops: list[tuple] = []

    def create_collection(self, coll: str) -> "Transaction":
        self.ops.append(("mkcoll", coll))
        return self

    def remove_collection(self, coll: str) -> "Transaction":
        self.ops.append(("rmcoll", coll))
        return self

    def touch(self, coll: str, name: str) -> "Transaction":
        self.ops.append(("touch", coll, name))
        return self

    def write(
        self, coll: str, name: str, data: bytes, attrs: dict | None = None
    ) -> "Transaction":
        self.ops.append(("write", coll, name, bytes(data), attrs))
        return self

    def write_at(
        self, coll: str, name: str, off: int, data: bytes
    ) -> "Transaction":
        """Patch `data` into the object at byte offset `off` without
        rewriting the rest (ObjectStore::Transaction::write(off,len) — the
        sub-extent shape ECBackend's overwrite path ships,
        src/osd/ECTransaction.cc:101). Compiles to a KV set_range so both
        the WAL record and the wire stay proportional to len(data)."""
        self.ops.append(("write_at", coll, name, off, bytes(data)))
        return self

    def remove(self, coll: str, name: str) -> "Transaction":
        self.ops.append(("remove", coll, name))
        return self

    def setattrs(self, coll: str, name: str, attrs: dict) -> "Transaction":
        self.ops.append(("setattrs", coll, name, attrs))
        return self

    def omap_setkeys(
        self, coll: str, name: str, kv: dict[bytes, bytes]
    ) -> "Transaction":
        self.ops.append(("omap_set", coll, name, dict(kv)))
        return self

    def omap_rmkeys(self, coll: str, name: str, keys) -> "Transaction":
        self.ops.append(("omap_rm", coll, name, list(keys)))
        return self


def _encode_attrs(attrs: dict) -> bytes:
    """Attrs are xattr blobs in the reference; ours carry version stamps and
    HashInfo, encoded with typed denc tags so the bytes are deterministic
    and decoding never runs arbitrary constructors."""
    from ceph_tpu.osd.ecutil import HashInfo

    def value(e, v):
        if isinstance(v, bool):
            e.u8(4).boolean(v)
        elif isinstance(v, int):
            e.u8(1).s64(v)
        elif isinstance(v, bytes):
            e.u8(2).blob(v)
        elif isinstance(v, str):
            e.u8(3).string(v)
        elif isinstance(v, HashInfo):
            e.u8(5).u64(v.total_chunk_size).list(
                v.cumulative_shard_hashes, lambda ee, h: ee.u64(h)
            )
        else:
            raise TypeError(f"unencodable attr value type {type(v)!r}")

    return (
        Encoder()
        .mapping(attrs, lambda e, k: e.string(k), value)
        .bytes()
    )


def _decode_attrs(raw: bytes) -> dict:
    from ceph_tpu.osd.ecutil import HashInfo

    def value(d):
        tag = d.u8()
        if tag == 1:
            return d.s64()
        if tag == 2:
            return d.blob()
        if tag == 3:
            return d.string()
        if tag == 4:
            return d.boolean()
        if tag == 5:
            return HashInfo(d.u64(), d.list(lambda dd: dd.u64()))
        raise ValueError(f"unknown attr tag {tag}")

    return Decoder(raw).mapping(lambda d: d.string(), value)


class KStore:
    """ObjectStore over a KeyValueDB; see module docstring."""

    KIND = "kstore"
    #: optional distributed tracer (set by the owning daemon): traced
    #: ops get a journal_commit span per transaction; untraced cost is
    #: one attribute check
    tracer = None

    def __init__(self, db: KeyValueDB | None = None):
        self.db = db if db is not None else MemDB()

    def used_bytes(self) -> int:
        """Current store footprint (ObjectStore::statfs 'used' role):
        live keys + values, so deletes genuinely free space — unlike the
        WAL's cumulative bytes_logged. O(rows); fine at test scale, a
        maintained counter when stores grow."""
        return sum(
            len(k[1]) + len(v) for k, v in self.db.table.items()
        )

    # -- transactions ---------------------------------------------------------

    def queue_transaction(self, txn: Transaction) -> None:
        """Compile to one KV batch and commit atomically."""
        sp = None if self.tracer is None else self.tracer.child(
            "journal_commit",
            tags={"store": self.KIND, "ops": len(txn.ops)},
        )
        try:
            kv = KVTransaction()
            self._begin_batch()
            try:
                for op in txn.ops:
                    self._compile_op(kv, op)
            except BaseException:
                self._abort_batch()
                raise
            self._commit_batch(kv)
        finally:
            if sp is not None:
                sp.finish()

    def _begin_batch(self) -> None:
        """Per-transaction compile state reset (backend hook)."""

    def _abort_batch(self) -> None:
        """Undo compile-time side effects after a failed compile (backend
        hook; the KV batch itself was never submitted)."""

    def _commit_batch(self, kv: KVTransaction) -> None:
        """Make the compiled batch durable — THE commit point."""
        self.db.submit_transaction(kv)

    def _compile_op(self, kv: KVTransaction, op: tuple) -> None:
        kind = op[0]
        if kind == "mkcoll":
            kv.set(_COLL, op[1].encode(), b"")
        elif kind == "rmcoll":
            coll = op[1]
            kv.rm(_COLL, coll.encode())
            for table, row_key in self._rows_of(coll):
                kv.rm(table, row_key)
        elif kind == "touch":
            _, coll, name = op
            if self.db.get(_DATA, _okey(coll, name)) is None:
                kv.set(_DATA, _okey(coll, name), b"")
        elif kind == "write":
            _, coll, name, data, attrs = op
            kv.set(_DATA, _okey(coll, name), data)
            if attrs is not None:
                kv.set(_ATTR, _okey(coll, name), _encode_attrs(attrs))
        elif kind == "write_at":
            _, coll, name, off, data = op
            kv.set_range(_DATA, _okey(coll, name), off, data)
        elif kind == "remove":
            _, coll, name = op
            kv.rm(_DATA, _okey(coll, name))
            kv.rm(_ATTR, _okey(coll, name))
            for k, _v in list(self.db.iterate(_OMAP)):
                if k[1].startswith(_okey(coll, name)):
                    kv.rm(_OMAP, k[1])
        elif kind == "setattrs":
            _, coll, name, attrs = op
            merged = dict(self.getattrs(coll, name))
            merged.update(attrs)
            kv.set(_ATTR, _okey(coll, name), _encode_attrs(merged))
        elif kind == "omap_set":
            _, coll, name, pairs = op
            for k, v in pairs.items():
                kv.set(_OMAP, _okey(coll, name, k), v)
        elif kind == "omap_rm":
            _, coll, name, keys = op
            for k in keys:
                kv.rm(_OMAP, _okey(coll, name, k))
        else:
            raise ValueError(f"unknown transaction op {kind!r}")

    def _rows_of(self, coll: str):
        prefix = Encoder().string(coll).bytes()
        for table in (_DATA, _ATTR, _OMAP):
            for k, _v in list(self.db.iterate(table)):
                if k[1].startswith(prefix):
                    yield table, k[1]

    # -- reads ----------------------------------------------------------------

    def collection_exists(self, coll: str) -> bool:
        return self.db.get(_COLL, coll.encode()) is not None

    def list_collections(self) -> list[str]:
        return [k[1].decode() for k, _ in self.db.iterate(_COLL)]

    def exists(self, coll: str, name: str) -> bool:
        return self.db.get(_DATA, _okey(coll, name)) is not None

    def read(self, coll: str, name: str) -> bytes:
        data = self.db.get(_DATA, _okey(coll, name))
        if data is None:
            raise StoreError("ENOENT", f"{coll}/{name} does not exist")
        return data

    def getattrs(self, coll: str, name: str) -> dict:
        raw = self.db.get(_ATTR, _okey(coll, name))
        return {} if raw is None else _decode_attrs(raw)

    def omap_get(self, coll: str, name: str) -> dict[bytes, bytes]:
        prefix = _okey(coll, name)
        out = {}
        for k, v in self.db.iterate(_OMAP):
            if k[1].startswith(prefix):
                out[k[1][len(prefix):]] = v
        return out

    def list_objects(self, coll: str) -> list[str]:
        prefix = Encoder().string(coll).bytes()
        out = []
        for k, _v in self.db.iterate(_DATA):
            if k[1].startswith(prefix):
                out.append(_okey_decode(k[1])[1])
        return out

    # -- fsck -----------------------------------------------------------------

    def fsck(self, deep: bool = False) -> list[dict]:
        """Consistency check (ceph-objectstore-tool --op fsck surface).

        KStore keeps everything in KV rows the WAL already crc-frames, so
        there is no allocator or at-rest checksum to cross-check — fsck
        verifies the rows themselves decode: object keys, attr blobs, and
        (deep) that every data row is readable. BlockStore overrides this
        with the real extent/free-list/checksum cross-checks."""
        errors: list[dict] = []
        for k, _v in list(self.db.iterate(_DATA)):
            try:
                _okey_decode(k[1])
            except Exception as e:  # noqa: BLE001 - each row reported
                errors.append(
                    {"key": k[1].hex(), "error": f"undecodable key: {e}"}
                )
        for k, v in list(self.db.iterate(_ATTR)):
            try:
                coll, name = _okey_decode(k[1])
                _decode_attrs(v)
            except Exception as e:  # noqa: BLE001
                errors.append(
                    {"key": k[1].hex(), "error": f"undecodable attrs: {e}"}
                )
        if deep:
            for k, _v in list(self.db.iterate(_DATA)):
                try:
                    coll, name = _okey_decode(k[1])
                    self.read(coll, name)
                except Exception as e:  # noqa: BLE001
                    errors.append(
                        {"key": k[1].hex(), "error": f"unreadable: {e}"}
                    )
        return errors


def create_store(db: KeyValueDB | None = None, config=None):
    """Build the ObjectStore the `osd_objectstore` option names.

    `kstore-file`/`memstore` differ only in the KeyValueDB the caller
    passes (FileDB vs MemDB) — both get a KStore. `blockstore` gets the
    allocator/at-rest-checksum store; its block file defaults to
    `<db.path>/block` beside a FileDB's WAL, or an in-memory device over
    MemDB (the MemStore-tier equivalent for tests)."""
    kind = config.get("osd_objectstore") if config is not None else None
    if kind == "blockstore":
        from ceph_tpu.osd.blockstore import BlockStore

        return BlockStore(db, config=config)
    return KStore(db)
