"""cls: in-OSD object classes — server-side methods on objects.

The reference's object-class mechanism (src/objclass/class_api.cc +
src/cls/*) lets clients invoke named methods that run INSIDE the primary
OSD against the object (rados `exec`/cls_cxx_*): reads and read-modify-write
cycles happen server-side, atomically, without shipping the object to the
client. rbd locking, rgw indexes, and watch bookkeeping all live there.

Mini equivalent: a `ClassHandler` registry of (class, method) -> python
callable with RD/WR flags (objclass method flags); the OSD daemon executes
a "call" op by building a `MethodContext` over the object's current content
+ user xattrs, running the method, and — for WR methods that dirtied the
context — writing the result back through the normal backend path, so the
mutation replicates/EC-encodes like any client write.

Built-in classes (reference parity targets):

  * `lock` — advisory exclusive/shared locks held in user xattrs
    (src/cls/lock/cls_lock.cc: lock_op/unlock_op semantics incl. EBUSY on
    conflicting holders and idempotent re-lock by the same owner+cookie).
  * `version` — object-version read/check gates
    (src/cls/version/cls_version.cc), backed by the PG log's obj_ver.

Custom classes register at runtime (`DEFAULT_HANDLER.register`), the
load-your-own-.so story without dlopen.
"""

from __future__ import annotations

from dataclasses import dataclass, field

RD = 1  #: method reads object state (CLS_METHOD_RD)
WR = 2  #: method may mutate object state (CLS_METHOD_WR)


class ClsError(Exception):
    def __init__(self, code: str, message: str = ""):
        super().__init__(message or code)
        self.code = code  # "EBUSY" | "ENOENT" | "ECANCELED" | ...


@dataclass
class MethodContext:
    """What a method sees of its object (cls_cxx_read/write/get/setxattr)."""

    #: None when the object does not exist yet
    data: bytes | None
    #: free-form user xattrs (json-serializable values)
    user_attrs: dict = field(default_factory=dict)
    #: the PG log's object version (0 when absent)
    version: int = 0
    #: the primary's clock at call time (ceph_clock_now as seen by cls
    #: methods); lease arithmetic uses this, never the client's clock.
    #: The OSD stamps it from time.time() + `cls_clock_offset` so tests
    #: can advance "time" deterministically without sleeping.
    now: float = 0.0
    _writable: bool = False
    dirty: bool = False

    def exists(self) -> bool:
        return self.data is not None

    def read(self) -> bytes:
        if self.data is None:
            raise ClsError("ENOENT", "object does not exist")
        return self.data

    def write(self, data: bytes) -> None:
        if not self._writable:
            raise ClsError("EPERM", "RD method attempted a write")
        self.data = bytes(data)
        self.dirty = True

    def getxattr(self, key: str):
        return self.user_attrs.get(key)

    def setxattr(self, key: str, value) -> None:
        if not self._writable:
            raise ClsError("EPERM", "RD method attempted a write")
        self.user_attrs[key] = value
        self.dirty = True

    def rmxattr(self, key: str) -> None:
        if not self._writable:
            raise ClsError("EPERM", "RD method attempted a write")
        if self.user_attrs.pop(key, None) is not None:
            self.dirty = True

    # -- omap (cls_cxx_map_get_vals / set_vals / remove_key) ------------------
    #
    # Methods see the object's real omap rows; mutations are tracked as an
    # exact delta the OSD replicates (EC pools pass omap_supported=False
    # and methods get EOPNOTSUPP, matching ECBackend's no-omap rule).

    omap: dict = None  # set by the OSD before the call; bytes -> bytes
    omap_supported: bool = True

    def _require_omap(self) -> dict:
        if not self.omap_supported:
            raise ClsError("EOPNOTSUPP", "no omap on this pool")
        if self.omap is None:
            self.omap = {}
        if not hasattr(self, "omap_sets"):
            self.omap_sets: dict = {}
            self.omap_rms: list = []
            self.omap_cleared = False
        return self.omap

    def omap_get_vals(
        self, after: bytes | None = None, max_return: int | None = None,
        prefix: bytes = b"",
    ) -> dict:
        omap = self._require_omap()
        keys = sorted(k for k in omap if k.startswith(prefix))
        if after is not None:
            keys = [k for k in keys if k > after]
        if max_return is not None:
            keys = keys[:max_return]
        return {k: omap[k] for k in keys}

    def omap_get_val(self, key: bytes):
        return self._require_omap().get(key)

    def omap_set(self, kv: dict) -> None:
        if not self._writable:
            raise ClsError("EPERM", "RD method attempted a write")
        omap = self._require_omap()
        omap.update(kv)
        self.omap_sets.update(kv)
        for k in kv:
            if k in self.omap_rms:
                self.omap_rms.remove(k)
        self.dirty = True

    def omap_rm(self, keys) -> None:
        if not self._writable:
            raise ClsError("EPERM", "RD method attempted a write")
        omap = self._require_omap()
        for k in keys:
            omap.pop(k, None)
            self.omap_sets.pop(k, None)
            if k not in self.omap_rms:
                self.omap_rms.append(k)
        self.dirty = True

    def omap_delta(self) -> dict | None:
        """The replication payload (hex kv), or None when untouched."""
        if not hasattr(self, "omap_sets"):
            return None
        if not (self.omap_sets or self.omap_rms or self.omap_cleared):
            return None
        return {
            "sets": {k.hex(): v.hex() for k, v in self.omap_sets.items()},
            "rms": [k.hex() for k in self.omap_rms],
            "clear": self.omap_cleared,
        }


class ClassHandler:
    """(class, method) registry (ClassHandler in src/osd/ClassHandler.h)."""

    def __init__(self) -> None:
        self._methods: dict[tuple[str, str], tuple[int, object]] = {}

    def register(self, cls: str, method: str, flags: int, fn) -> None:
        self._methods[(cls, method)] = (flags, fn)

    def call(self, cls: str, method: str, ctx: MethodContext, inp: dict):
        entry = self._methods.get((cls, method))
        if entry is None:
            raise ClsError("EOPNOTSUPP", f"no method {cls}.{method}")
        flags, fn = entry
        ctx._writable = bool(flags & WR)
        return fn(ctx, inp or {})


# -- cls_lock (src/cls/lock/cls_lock.cc behaviors) ----------------------------
#
# Advisory exclusive/shared locks with cookie+owner identity and lease
# TTLs, held in a user xattr (EC-pool-safe — no omap). A holder with
# `duration > 0` carries `expiration` (primary clock, ctx.now); expired
# holders are invisible to conflict checks and breakable by anyone, while
# a re-lock by the same owner+cookie renews the lease (bumps expiration).
# `duration == 0` means the lock never expires (reference cls_lock's
# LOCK_FLAG_MAY_RENEW / utime_t duration semantics).

def _lock_key(name: str) -> str:
    return f"lock.{name}"


def _lock_live(h: dict, now: float) -> bool:
    exp = h.get("expiration", 0)
    return not exp or exp > now


def _lock_op(ctx: MethodContext, inp: dict):
    name = inp["name"]
    ltype = inp.get("type", "exclusive")
    if ltype not in ("exclusive", "shared"):
        raise ClsError("EINVAL", f"bad lock type {ltype!r}")
    owner = inp["owner"]
    cookie = inp.get("cookie", "")
    duration = float(inp.get("duration", 0) or 0)
    state = ctx.getxattr(_lock_key(name)) or {"type": ltype, "holders": []}
    expiration = ctx.now + duration if duration > 0 else 0
    for h in state["holders"]:
        if h["owner"] == owner and h["cookie"] == cookie:
            # idempotent re-lock by the holder renews the lease — even
            # past expiry, as long as nobody broke or took the lock
            h["expiration"] = expiration
            h["description"] = inp.get("description", h.get("description", ""))
            ctx.setxattr(_lock_key(name), state)
            return {"ok": True, "renewed": True, "expiration": expiration}
    live = [h for h in state["holders"] if _lock_live(h, ctx.now)]
    if live and (ltype == "exclusive" or state["type"] == "exclusive"):
        raise ClsError("EBUSY", f"lock {name!r} held")
    # expired holders are pruned the first time a new locker gets in
    # (reference cls_lock expiration semantics); the reply names them
    # so the client can log/count the implicit break
    pruned = [{"owner": h["owner"], "cookie": h["cookie"]}
              for h in state["holders"] if not _lock_live(h, ctx.now)]
    state["type"] = ltype
    state["holders"] = live + [{
        "owner": owner, "cookie": cookie, "expiration": expiration,
        "since": ctx.now, "description": inp.get("description", ""),
    }]
    ctx.setxattr(_lock_key(name), state)
    return {"ok": True, "expiration": expiration, "pruned": pruned}


def _unlock_op(ctx: MethodContext, inp: dict):
    name = inp["name"]
    state = ctx.getxattr(_lock_key(name))
    owner, cookie = inp["owner"], inp.get("cookie", "")
    # exact owner+cookie match; an expired-but-unbroken holder may still
    # unlock (its entry is present until pruned)
    keep = [] if not state else [
        h for h in state["holders"]
        if not (h["owner"] == owner and h["cookie"] == cookie)
    ]
    if not state or len(keep) == len(state["holders"]):
        raise ClsError("ENOENT", f"not the holder of {name!r}")
    if keep:
        state["holders"] = keep
        ctx.setxattr(_lock_key(name), state)
    else:
        ctx.rmxattr(_lock_key(name))
    return {"ok": True}


def _lock_info(ctx: MethodContext, inp: dict):
    state = ctx.getxattr(_lock_key(inp["name"]))
    holders = []
    for h in ([] if not state else state["holders"]):
        exp = h.get("expiration", 0)
        holders.append(dict(
            h,
            expired=bool(exp) and exp <= ctx.now,
            ttl=max(0.0, exp - ctx.now) if exp else None,
        ))
    return {"holders": holders,
            "type": None if not state else state["type"],
            "now": ctx.now}


def _break_lock(ctx: MethodContext, inp: dict):
    """cls_lock break_lock: remove a NAMED holder without being it —
    the recovery path after the holder died (the caller blocklists the
    holder first so its in-flight ops can't outlive the break). With
    `if_expired`, the break only lands if the holder's lease has lapsed
    — evaluated against the primary's clock inside the primary, so it
    is atomic with respect to a racing renewal."""
    name = inp["name"]
    state = ctx.getxattr(_lock_key(name))
    owner = inp["owner"]
    cookie = inp.get("cookie")  # None = any cookie of that owner
    if not state:
        raise ClsError("ENOENT", f"lock {name!r} not held")

    def match(h):
        return h["owner"] == owner and (cookie is None
                                        or h["cookie"] == cookie)

    matched = [h for h in state["holders"] if match(h)]
    if not matched:
        raise ClsError("ENOENT", f"{owner!r} does not hold {name!r}")
    if inp.get("if_expired"):
        live = [h for h in matched if _lock_live(h, ctx.now)]
        if live:
            raise ClsError("EBUSY", f"{owner!r} lease on {name!r} "
                                    "is still live")
    keep = [h for h in state["holders"] if not match(h)]
    if keep:
        state["holders"] = keep
        ctx.setxattr(_lock_key(name), state)
    else:
        ctx.rmxattr(_lock_key(name))
    return {"ok": True, "broken": len(matched)}


# -- cls_ckpt (ceph_tpu.ckpt HEAD pointer guard) ------------------------------
#
# Compare-and-swap of a checkpoint HEAD pointer, the commit point of the
# ckpt writer's chunks -> manifest -> HEAD protocol. State lives in a user
# xattr (plus the object data for plain-read visibility), NOT omap, so the
# same guard works on EC pools where omap is EOPNOTSUPP. Runs inside the
# primary, so two racing savers serialize on the object: the loser's stale
# `expect` fails with ECANCELED and its chunks stay orphaned (gc's job).

#: committed-save history entries the HEAD object retains (gc retention
#: windows are far smaller; entries whose saves were reclaimed are pruned
#: by ckpt.prune_history on the next gc pass)
CKPT_HISTORY_MAX = 512


def _ckpt_mirror(ctx: MethodContext, head: dict, history: list) -> None:
    """Mirror HEAD + commit history into the object data so a plain
    `ioctx.read(HEAD)` needs no exec."""
    import json as _json

    ctx.write(_json.dumps(
        dict(head, history=history), sort_keys=True
    ).encode())


def _ckpt_cas_head(ctx: MethodContext, inp: dict):
    cur = ctx.getxattr("ckpt.head")
    cur_id = None if cur is None else cur.get("save_id")
    expect = inp.get("expect")
    if cur_id != expect:
        raise ClsError(
            "ECANCELED",
            f"HEAD is {cur_id!r}, caller expected {expect!r}",
        )
    head = dict(inp["head"])
    # commit order for gc retention (keep-last-N / keep-every-Nth):
    # appended atomically with the swap, inside the primary
    history = list(ctx.getxattr("ckpt.history") or ())
    history.append(head["save_id"])
    history = history[-CKPT_HISTORY_MAX:]
    ctx.setxattr("ckpt.head", head)
    ctx.setxattr("ckpt.history", history)
    _ckpt_mirror(ctx, head, history)
    return {"ok": True, "prev": cur_id}


def _ckpt_prune_history(ctx: MethodContext, inp: dict):
    """Drop reclaimed save_ids from the commit history (gc's epilogue;
    idempotent — pruning an absent id is a no-op). HEAD itself is never
    prunable."""
    head = ctx.getxattr("ckpt.head")
    if head is None:
        raise ClsError("ENOENT", "no checkpoint HEAD")
    drop = set(inp.get("remove", ())) - {head.get("save_id")}
    history = [
        sid for sid in (ctx.getxattr("ckpt.history") or [])
        if sid not in drop
    ]
    ctx.setxattr("ckpt.history", history)
    _ckpt_mirror(ctx, head, history)
    return {"ok": True, "history": history}


def _ckpt_read_head(ctx: MethodContext, inp: dict):
    head = ctx.getxattr("ckpt.head")
    if head is None:
        raise ClsError("ENOENT", "no checkpoint HEAD")
    return {"head": head}


# -- cls_version (src/cls/version/cls_version.cc) -----------------------------

def _version_read(ctx: MethodContext, inp: dict):
    return {"ver": ctx.version}


def _version_check(ctx: MethodContext, inp: dict):
    """Fail with ECANCELED unless the object version satisfies the
    condition — the optimistic-concurrency gate rgw relies on."""
    want = inp["ver"]
    cond = inp.get("cond", "eq")
    ok = {
        "eq": ctx.version == want,
        "gt": ctx.version > want,
        "ge": ctx.version >= want,
    }.get(cond)
    if ok is None:
        raise ClsError("EINVAL", f"bad cond {cond!r}")
    if not ok:
        raise ClsError(
            "ECANCELED", f"version {ctx.version} fails {cond} {want}"
        )
    return {"ok": True, "ver": ctx.version}


def default_handler() -> ClassHandler:
    h = ClassHandler()
    h.register("lock", "lock", RD | WR, _lock_op)
    h.register("lock", "unlock", RD | WR, _unlock_op)
    h.register("lock", "get_info", RD, _lock_info)
    h.register("lock", "break_lock", RD | WR, _break_lock)
    h.register("version", "read", RD, _version_read)
    h.register("version", "check", RD, _version_check)
    h.register("ckpt", "cas_head", RD | WR, _ckpt_cas_head)
    h.register("ckpt", "read_head", RD, _ckpt_read_head)
    h.register("ckpt", "prune_history", RD | WR, _ckpt_prune_history)
    return h
