"""EncodeService: coalesce concurrent per-object EC work into planar
TPU launches.

SURVEY §7 calls packing "stripes from many concurrent objects" into one
launch the real performance design problem: a 4 KiB object write encodes
512 B chunks — far too small to feed the MXU — but N concurrent writes
stacked end-to-end along the chunk axis are one wide (k, N·chunk/4) planar
`encode_words` call on the fused Pallas kernel (ceph_tpu.ops.gf_pallas).
The reference's analogue is ECBackend's op pipelining (start_rmw batches
in-flight ops, ECBackend.cc:1830) feeding ISA-L's wide SIMD units.

Mechanics: the first enqueue arms a latency-bound flush (the batch
window); everything that arrives while the window is open — concurrent
client ops on the 4 op shards, recovery decodes, scrub rebuilds — rides
the same launch. `launches`/`objects` counters let tests assert the
coalescing actually happened (objects >> launches under concurrency).

Codecs without the planar API (clay/lrc/shec compositions) fall back to
their per-object paths transparently.
"""

from __future__ import annotations

import asyncio

import numpy as np

from ceph_tpu.ops import gf_pallas as gp
from ceph_tpu.ops.gf import gf_region_matmul


def _bucket_pad(words: np.ndarray) -> tuple[np.ndarray, int]:
    """Pad the planar width up to a power-of-2 bucket so batches of
    varying composition reuse a handful of compiled kernels instead of
    jitting per width (zero columns encode to zero parity; sliced off)."""
    w = words.shape[-1]
    bucket = max(256, 1 << (w - 1).bit_length())
    if bucket == w:
        return words, w
    padded = np.zeros((*words.shape[:-1], bucket), dtype=words.dtype)
    padded[..., :w] = words
    return padded, w


_UNSET = object()


class EncodeService:
    def __init__(
        self, window: float = 0.002, max_batch: int = 128,
        mesh_min_bytes: int = 8192, tracer=None,
    ):
        #: optional distributed tracer: traced ops get an encode_wait
        #: span (enqueue -> result) and each device launch an
        #: encode_batch span tagged with batch size and whether this
        #: planar shape compiled fresh or reused a cached executable
        self.tracer = tracer
        #: (kind, k, m, bucket width) planar shapes already launched —
        #: a first launch at a shape pays the jit compile
        self._seen_shapes: set[tuple] = set()
        #: seconds the first op of a batch waits for company
        self.window = window
        self.max_batch = max_batch
        #: planar widths >= this dispatch through the device MESH
        #: (parallel.sharding): the coalesced batch's byte axis folds
        #: onto the (stripe, byte) mesh with no communication, so every
        #: visible chip shares the launch; below it the single-device
        #: kernel wins (dispatch overhead beats the parallelism)
        self.mesh_min_bytes = mesh_min_bytes
        self._mesh_cache = _UNSET
        #: launches that went through the sharded mesh path
        self.mesh_launches = 0
        self._enc_q: dict[int, list] = {}
        self._dec_q: dict[tuple, list] = {}
        self._codecs: dict[int, object] = {}
        #: armed window timers, cancelled on flush (a stale timer from a
        #: max_batch-flushed batch would otherwise cut the NEXT window
        #: short and erode coalescing under sustained load). Decode
        #: timers are keyed per CODEC: one shared window drains every
        #: erasure signature queued for it (mass-failure recovery waves
        #: mix signatures; a window per signature would serialize them)
        self._enc_timers: dict[int, object] = {}
        self._dec_timers: dict[int, object] = {}
        #: device launches / objects served — the coalescing evidence
        self.launches = 0
        self.objects = 0

    def _mesh(self, width_bytes: int):
        """The device mesh for a planar launch of `width_bytes`, or None
        (single device / width too small to amortize dispatch)."""
        if width_bytes < self.mesh_min_bytes:
            return None
        if self._mesh_cache is _UNSET:
            import jax

            n = len(jax.devices())
            if n > 1:
                from ceph_tpu.parallel import sharding

                # largest power-of-2 subset: bucket-padded planar widths
                # then always fold evenly onto the (stripe, byte) axes
                self._mesh_cache = sharding.ec_mesh(
                    1 << (n.bit_length() - 1)
                )
            else:
                self._mesh_cache = None
        return self._mesh_cache

    # -- encode ---------------------------------------------------------------

    async def encode(self, codec, data: bytes) -> dict[int, bytes]:
        """All k+m chunks for one object, batched across callers."""
        blocksize = codec.get_chunk_size(len(data))
        if not hasattr(codec, "encode_words") or blocksize % 4:
            self.launches += 1
            self.objects += 1
            return codec.encode(range(codec.get_chunk_count()), data)
        key = id(codec)
        self._codecs[key] = codec
        fut = asyncio.get_event_loop().create_future()
        q = self._enc_q.setdefault(key, [])
        q.append((data, blocksize, fut))
        sp = None if self.tracer is None else self.tracer.child(
            "encode_wait", tags={"bytes": len(data)}
        )
        if len(q) >= self.max_batch:
            self._flush_encode(key)
        elif len(q) == 1:
            # call_later captures the current context, so the flush
            # callback's encode_batch span parents to THIS op's trace
            self._enc_timers[key] = asyncio.get_event_loop().call_later(
                self.window, self._flush_encode, key
            )
        if sp is None:
            return await fut
        try:
            return await fut
        finally:
            sp.finish()

    def _flush_encode(self, key: int) -> None:
        timer = self._enc_timers.pop(key, None)
        if timer is not None:
            timer.cancel()
        q = self._enc_q.pop(key, [])
        if not q:
            return
        codec = self._codecs[key]
        k, n = codec.k, codec.get_chunk_count()
        sp = None if self.tracer is None else self.tracer.child(
            "encode_batch", tags={"batch": len(q)}
        )
        try:
            self._flush_encode_inner(key, q, codec, k, n, sp)
        finally:
            if sp is not None:
                sp.finish()

    def _flush_encode_inner(self, key, q, codec, k, n, sp) -> None:
        try:
            # pack every object's chunk j end-to-end into planar row j
            rows: list[list[np.ndarray]] = [[] for _ in range(k)]
            for data, bs, _fut in q:
                padded = np.zeros(k * bs, dtype=np.uint8)
                padded[: len(data)] = np.frombuffer(data, dtype=np.uint8)
                for i in range(k):
                    rows[i].append(padded[i * bs: (i + 1) * bs])
            planes = np.stack([np.concatenate(r) for r in rows])
            mesh = self._mesh(planes.shape[1])
            path, bucket = "numpy", planes.shape[1]
            if mesh is not None:
                from ceph_tpu.parallel import sharding

                padded, width = _bucket_pad(planes)
                path, bucket = "mesh", padded.shape[-1]
                self._note_launch(sp, path, k, n, bucket, len(q))
                parity = sharding.mesh_encode_planar(
                    codec, padded, mesh
                )[:, :width]
                self.mesh_launches += 1
            elif gp.available():
                words = np.stack(
                    [np.concatenate(r).view(np.int32) for r in rows]
                )
                words, width = _bucket_pad(words)
                path, bucket = "pallas", words.shape[-1]
                self._note_launch(sp, path, k, n, bucket, len(q))
                parity = np.asarray(
                    codec.encode_words(words)
                )[:, :width].view(np.uint8)
                parity = parity.reshape(codec.m, -1)
            else:
                # off-device: exact table-driven numpy planar path — no
                # jit-per-width (tiny batches would otherwise recompile
                # for every composition)
                parity_mat = codec._gen[codec.k:]
                self._note_launch(sp, path, k, n, bucket, len(q))
                if getattr(codec, "_xor_ok", False):
                    parity = np.bitwise_xor.reduce(
                        planes, axis=0
                    )[None]
                else:
                    parity = gf_region_matmul(parity_mat, planes)
            self.launches += 1
            self.objects += len(q)
            off = 0
            for j, (data, bs, fut) in enumerate(q):
                chunks: dict[int, bytes] = {}
                for logical in range(k):
                    chunks[codec.chunk_index(logical)] = (
                        rows[logical][j].tobytes()
                    )
                for logical in range(k, n):
                    chunks[codec.chunk_index(logical)] = parity[
                        logical - k, off: off + bs
                    ].tobytes()
                off += bs
                if not fut.done():
                    fut.set_result(chunks)
        except Exception as e:
            for _data, _bs, fut in q:
                if not fut.done():
                    fut.set_exception(e)

    def _note_launch(self, sp, path: str, k: int, n: int,
                     bucket: int, batch: int) -> None:
        """Tag the batch span with the compile-vs-execute split: a
        planar shape's FIRST launch pays the jit compile, later ones
        reuse the cached executable — the difference dominates tail
        latency and must be attributable in a trace."""
        shape = (path, k, n, bucket)
        fresh = shape not in self._seen_shapes
        self._seen_shapes.add(shape)
        if sp is not None:
            sp.set_tag("path", path)
            sp.set_tag("width", bucket)
            sp.set_tag("compile", fresh)
            sp.set_tag("batch", batch)

    # -- decode ---------------------------------------------------------------

    async def decode(
        self, codec, want_to_read, chunks: dict[int, bytes]
    ) -> dict[int, bytes]:
        """Batched degraded-read decode: objects sharing an erasure
        signature (same survivors/targets) decode in one launch."""
        want = set(want_to_read)
        have = set(chunks)
        if want <= have:
            return {i: bytes(chunks[i]) for i in want}
        blocksize = len(next(iter(chunks.values())))
        if not hasattr(codec, "decode_words") or blocksize % 4:
            self.launches += 1
            self.objects += 1
            return codec.decode(want, chunks)
        present = tuple(
            sorted(codec.logical_index(p) for p in have)
        )[: codec.k]
        targets = tuple(
            sorted(codec.logical_index(p) for p in want - have)
        )
        key = (id(codec), present, targets)
        self._codecs[id(codec)] = codec
        fut = asyncio.get_event_loop().create_future()
        q = self._dec_q.setdefault(key, [])
        q.append((chunks, blocksize, want, fut))
        if len(q) >= self.max_batch:
            self._flush_decode(key)
            if not any(k[0] == id(codec) for k in self._dec_q):
                timer = self._dec_timers.pop(id(codec), None)
                if timer is not None:
                    timer.cancel()
        elif id(codec) not in self._dec_timers:
            # ONE window per codec, not per signature: a mass-failure
            # recovery wave decodes with many erasure signatures at
            # once, and paying a fresh window per signature would
            # serialize exactly when throughput matters most — window
            # expiry drains EVERY signature queued for this codec
            # (one launch each, shared window)
            self._dec_timers[id(codec)] = (
                asyncio.get_event_loop().call_later(
                    self.window, self._flush_decode_all, id(codec)
                )
            )
        return await fut

    def _flush_decode_all(self, codec_id: int) -> None:
        """Window expiry: drain every signature queued for this codec."""
        self._dec_timers.pop(codec_id, None)
        for key in [k for k in self._dec_q if k[0] == codec_id]:
            self._flush_decode(key)

    def _flush_decode(self, key: tuple) -> None:
        q = self._dec_q.pop(key, None)
        if not q:
            return
        codec_id, present, targets = key
        codec = self._codecs[codec_id]
        sp = None if self.tracer is None else self.tracer.child(
            "decode_batch",
            tags={"batch": len(q), "targets": len(targets)},
        )
        try:
            self._flush_decode_inner(key, q, codec, sp)
        finally:
            if sp is not None:
                sp.finish()

    def _flush_decode_inner(self, key, q, codec, sp) -> None:
        codec_id, present, targets = key
        try:
            rows: list[list[np.ndarray]] = [[] for _ in present]
            for chunks, bs, _want, _fut in q:
                for i, logical in enumerate(present):
                    phys = codec.chunk_index(logical)
                    rows[i].append(
                        np.frombuffer(chunks[phys], dtype=np.uint8)
                    )
            planes = np.stack([np.concatenate(r) for r in rows])
            mesh = self._mesh(planes.shape[1])
            if mesh is not None:
                from ceph_tpu.parallel import sharding

                padded, width = _bucket_pad(planes)
                rebuilt = sharding.mesh_decode_planar(
                    codec, list(present), list(targets), padded, mesh
                )[:, :width]
                self.mesh_launches += 1
            elif gp.available():
                words = np.stack(
                    [np.concatenate(r).view(np.int32) for r in rows]
                )
                words, width = _bucket_pad(words)
                rebuilt = np.asarray(
                    codec.decode_words(
                        list(present), list(targets), words
                    )
                )[:, :width].view(np.uint8).reshape(len(targets), -1)
            else:
                from ceph_tpu.ec import matrices

                dm = matrices.decode_matrix(
                    codec._gen, codec.k, list(present), list(targets)
                )
                rebuilt = gf_region_matmul(dm, planes)
            self.launches += 1
            self.objects += len(q)
            off = 0
            for chunks, bs, want, fut in q:
                out = {
                    i: bytes(chunks[i]) for i in want if i in chunks
                }
                for t, logical in enumerate(targets):
                    phys = codec.chunk_index(logical)
                    if phys in want:
                        out[phys] = rebuilt[t, off: off + bs].tobytes()
                off += bs
                if not fut.done():
                    fut.set_result(out)
        except Exception as e:
            for _c, _b, _w, fut in q:
                if not fut.done():
                    fut.set_exception(e)
