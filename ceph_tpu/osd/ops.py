"""Object op execution: the do_osd_ops analogue.

The reference executes a client op vector against an ObjectContext inside
the primary (PrimaryLogPG::do_osd_ops, src/osd/PrimaryLogPG.cc:5577 — the
giant per-op switch; execute_ctx 3709 builds the transaction that then
replicates). Here the same idea is a pure function over an `ObjectState`:
the primary AND every replica run `execute_ops` on the identical op vector
(sub-ops ship the ops, the reference ships the compiled transaction — same
contract: deterministic application), so partial writes, omap, and xattr
mutations replicate without shipping whole objects.

Op descriptors are JSON dicts; bulk write payloads ride the message's raw
segment, split by `data_lens` (one slice per data-consuming op, in op
order). Read results are returned the same way.

Ops (reference opcode in parens, src/include/rados.h):

  data    write(off) (WRITE), write_full (WRITEFULL), append (APPEND),
          truncate(size) (TRUNCATE), zero(off,len) (ZERO),
          create (CREATE: EEXIST when exclusive), delete (DELETE),
          read(off,len) (READ), stat (STAT)
  omap    omap_set(kv) (OMAPSETVALS), omap_get(after,max) (OMAPGETVALS),
          omap_rm(keys) (OMAPRMKEYS), omap_clear (OMAPCLEAR)
  xattr   setxattr(name) (SETXATTR), getxattr(name) (GETXATTR),
          rmxattr(name) (RMXATTR), getxattrs (GETXATTRS)

EC pools construct ObjectState with omap_supported=False: omap ops raise
EOPNOTSUPP, the errno ECBackend returns (EC pools have no omap in the
reference either).
"""

from __future__ import annotations

from dataclasses import dataclass, field


class OpError(Exception):
    """Typed, client-visible errno (ENOENT/EEXIST/EOPNOTSUPP/...)."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


@dataclass
class ObjectState:
    """The mutable object context one op vector executes against."""

    exists: bool = False
    data: bytearray = field(default_factory=bytearray)
    #: user xattrs, name -> bytes (object_info_t attrs role)
    xattrs: dict[str, bytes] = field(default_factory=dict)
    #: user omap, bytes -> bytes; None means "not loaded" (lazy)
    omap: dict[bytes, bytes] | None = None
    omap_supported: bool = True
    # dirty tracking: what persistence must flush
    data_dirty: bool = False
    xattr_dirty: bool = False
    #: exact omap delta (replicas replay these against their local omap)
    omap_sets: dict[bytes, bytes] = field(default_factory=dict)
    omap_rms: list[bytes] = field(default_factory=list)
    omap_cleared: bool = False
    deleted: bool = False

    @property
    def dirty(self) -> bool:
        return (
            self.data_dirty
            or self.xattr_dirty
            or self.omap_dirty
            or self.deleted
        )

    @property
    def omap_dirty(self) -> bool:
        return bool(self.omap_sets or self.omap_rms or self.omap_cleared)

    def _require_omap(self) -> dict[bytes, bytes]:
        if not self.omap_supported:
            raise OpError(
                "EOPNOTSUPP", "omap operations not supported on this pool"
            )
        if self.omap is None:
            self.omap = {}
        return self.omap

    def _touch(self) -> None:
        if not self.exists:
            self.exists = True
            self.data_dirty = True


def execute_ops(
    state: ObjectState, ops: list[dict], datas: list[bytes]
) -> tuple[list[dict], list[bytes]]:
    """Run the vector in order. Returns (per-op results, read payloads);
    read payloads concatenate into the reply's raw segment in op order.
    Raises OpError leaving `state` possibly part-mutated — callers discard
    the state on error (the reference aborts the whole ctx the same way).
    """
    results: list[dict] = []
    reads: list[bytes] = []
    di = 0

    def next_data() -> bytes:
        nonlocal di
        if di >= len(datas):
            raise OpError("EINVAL", "op vector short of data segments")
        d = datas[di]
        di += 1
        return d

    for op in ops:
        kind = op["op"]
        res: dict = {}
        if kind == "create":
            if op.get("exclusive") and state.exists:
                raise OpError("EEXIST", "object exists")
            state._touch()
        elif kind == "write_full":
            buf = next_data()
            state.data = bytearray(buf)
            state._touch()
            state.data_dirty = True
        elif kind == "write":
            buf = next_data()
            off = int(op.get("off", 0))
            if off + len(buf) > len(state.data):
                state.data.extend(
                    b"\x00" * (off + len(buf) - len(state.data))
                )
            state.data[off: off + len(buf)] = buf
            state._touch()
            state.data_dirty = True
        elif kind == "append":
            buf = next_data()
            state.data.extend(buf)
            state._touch()
            state.data_dirty = True
        elif kind == "truncate":
            size = int(op["size"])
            if size <= len(state.data):
                del state.data[size:]
            else:
                state.data.extend(b"\x00" * (size - len(state.data)))
            state._touch()
            state.data_dirty = True
        elif kind == "zero":
            if not state.exists:
                raise OpError("ENOENT", "no such object")
            off, length = int(op["off"]), int(op["len"])
            end = min(off + length, len(state.data))
            if off < len(state.data):
                state.data[off:end] = b"\x00" * (end - off)
            state.data_dirty = True
        elif kind == "delete":
            if not state.exists:
                raise OpError("ENOENT", "no such object")
            state.exists = False
            state.deleted = True
            state.data = bytearray()
            state.xattrs = {}
            if state.omap_supported:
                state.omap = {}
                state.omap_cleared = True
                state.omap_sets = {}
                state.omap_rms = []
        elif kind == "read":
            if not state.exists:
                raise OpError("ENOENT", "no such object")
            off = int(op.get("off", 0))
            length = op.get("length")
            end = len(state.data) if length is None else off + int(length)
            chunk = bytes(state.data[off:end])
            res["data_len"] = len(chunk)
            reads.append(chunk)
        elif kind == "stat":
            if not state.exists:
                raise OpError("ENOENT", "no such object")
            res["size"] = len(state.data)
        elif kind == "omap_set":
            omap = state._require_omap()
            kv = {
                bytes.fromhex(k): bytes.fromhex(v)
                for k, v in op["kv"].items()
            }
            omap.update(kv)
            state.omap_sets.update(kv)
            for k in kv:
                if k in state.omap_rms:
                    state.omap_rms.remove(k)
            state._touch()
        elif kind == "omap_get":
            omap = state._require_omap()
            after = bytes.fromhex(op["after"]) if op.get("after") else None
            max_return = op.get("max_return")
            keys = sorted(omap)
            if after is not None:
                keys = [k for k in keys if k > after]
            if max_return is not None:
                keys = keys[: int(max_return)]
            res["kv"] = {k.hex(): omap[k].hex() for k in keys}
        elif kind == "omap_rm":
            if not state.exists:
                raise OpError("ENOENT", "no such object")
            omap = state._require_omap()
            for khex in op["keys"]:
                k = bytes.fromhex(khex)
                omap.pop(k, None)
                state.omap_sets.pop(k, None)
                if k not in state.omap_rms:
                    state.omap_rms.append(k)
        elif kind == "omap_clear":
            if not state.exists:
                raise OpError("ENOENT", "no such object")
            state._require_omap()
            state.omap = {}
            state.omap_sets = {}
            state.omap_rms = []
            state.omap_cleared = True
        elif kind == "setxattr":
            state.xattrs[op["name"]] = bytes.fromhex(op["value"])
            state.xattr_dirty = True
            state._touch()
        elif kind == "getxattr":
            if op["name"] not in state.xattrs:
                raise OpError("ENOENT", f"no xattr {op['name']!r}")
            res["value"] = state.xattrs[op["name"]].hex()
        elif kind == "rmxattr":
            if state.xattrs.pop(op["name"], None) is not None:
                state.xattr_dirty = True
        elif kind == "getxattrs":
            # reserved names (SnapSet etc.) are internal bookkeeping,
            # invisible to clients like object_info_t attrs are
            res["xattrs"] = {
                k: v.hex() for k, v in state.xattrs.items()
                if not k.startswith("\x01")
            }
        else:
            raise OpError("EINVAL", f"unknown op {kind!r}")
        results.append(res)
    return results, reads


def is_mutating(ops: list[dict]) -> bool:
    read_only = {
        "read", "stat", "omap_get", "getxattr", "getxattrs",
    }
    return any(op["op"] not in read_only for op in ops)
