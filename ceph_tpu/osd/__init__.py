"""OSD-side cluster-map layer: pools, placement pipeline, balancer.

Re-expresses the reference's `src/osd/osd_types.{h,cc}` pool/PG types and
`src/osd/OSDMap.{h,cc}` placement pipeline in TPU-first form: the per-PG
scalar pipeline for parity with the C code, and a batched whole-pool mapping
(ParallelPGMapper's job, OSDMapMapping.h:18) on the vectorized CRUSH mapper.
"""

from ceph_tpu.osd.types import PgPool, ceph_stable_mod, pg_num_mask
from ceph_tpu.osd.osdmap import OSDMap

__all__ = ["PgPool", "OSDMap", "ceph_stable_mod", "pg_num_mask"]
