"""OSDMap: the epoch-versioned cluster map and its placement pipeline.

Re-expresses the reference's `OSDMap` placement path (src/osd/OSDMap.cc):

  pg -> pps (stable-mod + pool hash)          osd_types.cc:1640
     -> raw osds (crush->do_rule)             OSDMap.cc:2359 _pg_to_raw_osds
     -> upmap overrides                       OSDMap.cc:2389 _apply_upmap
     -> up osds (drop/NONE down+dne)          OSDMap.cc:2436 _raw_to_up_osds
     -> primary (affinity-aware)              OSDMap.cc:2460 _apply_primary_affinity
     -> acting (pg_temp/primary_temp)         OSDMap.cc:2515 _get_temp_osds
                                              OSDMap.cc:2591 _pg_to_up_acting_osds

Two drivers share the exact same semantics:

  * `pg_to_up_acting_osds(pool_id, ps)` — scalar, mirrors the C control flow,
    used by tests and one-off lookups;
  * `pool_mappings(pool_id)` — the whole pool in one batched TPU mapper
    launch (ceph_tpu.crush.jax_mapper.map_rule) plus vectorized numpy
    post-processing: the TPU-native replacement for the reference's
    thread-pool ParallelPGMapper (OSDMapMapping.h:18).

`calc_pg_upmaps` is the balancer step (OSDMap.cc:4512): it computes per-OSD
PG load from the batched mapping, then greedily moves PGs from the most
overfull OSD to underfull peers via pg_upmap_items entries until the
deviation target or the change budget is hit. Unlike the reference it does
not re-run crush->try_remap_rule per candidate; it restricts replacement
targets to OSDs absent from the PG's up set and re-validates by remapping
the touched PG, which keeps sets duplicate-free (failure-domain validation
beyond that is the caller's concern, as noted in the method doc).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.crush import mapper as scalar_mapper
from ceph_tpu.crush.types import CrushMap
from ceph_tpu.osd.types import PgPool
from ceph_tpu.crush.hash import crush_hash32_2

CRUSH_ITEM_NONE = 0x7FFFFFFF
DEFAULT_PRIMARY_AFFINITY = 0x10000
MAX_PRIMARY_AFFINITY = 0x10000


@dataclass
class OSDMap:
    crush: CrushMap
    pools: dict[int, PgPool] = field(default_factory=dict)
    max_osd: int = 0
    epoch: int = 1
    # per-osd state; weights are 16.16 fixed point like the crush map's
    osd_exists: np.ndarray | None = None  # bool (max_osd,)
    osd_up: np.ndarray | None = None  # bool (max_osd,)
    osd_weight: np.ndarray | None = None  # int64 16.16 in/out weight
    osd_primary_affinity: np.ndarray | None = None  # int64 16.16
    #: per-osd up_thru epoch (osd_info_t::up_thru): the highest epoch
    #: the mon has confirmed this OSD was alive-and-primary in. The
    #: load-bearing bit of interval math: a past interval whose primary
    #: never got up_thru confirmed inside it CANNOT have served writes
    #: (maybe_went_rw=false), so peering may skip its members
    osd_up_thru: np.ndarray | None = None  # int64 (max_osd,)
    pg_upmap: dict[tuple[int, int], list[int]] = field(default_factory=dict)
    pg_upmap_items: dict[tuple[int, int], list[tuple[int, int]]] = field(
        default_factory=dict
    )
    pg_temp: dict[tuple[int, int], list[int]] = field(default_factory=dict)
    primary_temp: dict[tuple[int, int], int] = field(default_factory=dict)
    #: profile name -> k/v profile, stored in the map like the reference
    #: (OSDMap::erasure_code_profiles; the mon validates + commits them)
    erasure_code_profiles: dict[str, dict] = field(default_factory=dict)
    #: osd -> (host, port) public address (OSDMap::osd_addrs) — how clients
    #: and peers reach a daemon; registered at boot via the mon
    osd_addrs: dict[int, tuple[str, int]] = field(default_factory=dict)
    #: osd -> scheme-tagged local endpoint (uds://...) announced at boot;
    #: co-located clients dial this first and fall back to osd_addrs
    osd_local_addrs: dict[int, str] = field(default_factory=dict)
    #: fencing (OSDMap.h:579 blacklist map): entity identity -> unix expiry.
    #: Identities are "client.name" (every instance of the entity) or
    #: "client.name/nonce" (one messenger instance). OSDs refuse ops from
    #: blocklisted identities; the MDS blocklists before re-granting an
    #: evicted client's caps (mds_session_blacklist_on_evict) so stale
    #: direct-RADOS writes can never race the new cap holder.
    blocklist: dict[str, float] = field(default_factory=dict)

    def __post_init__(self):
        n = self.max_osd
        if self.osd_exists is None:
            self.osd_exists = np.ones(n, dtype=bool)
        if self.osd_up is None:
            self.osd_up = np.ones(n, dtype=bool)
        if self.osd_weight is None:
            self.osd_weight = np.full(n, 0x10000, dtype=np.int64)
        if self.osd_up_thru is None:
            self.osd_up_thru = np.zeros(n, dtype=np.int64)
        self._compiled = None
        #: BalanceResult of the most recent calc_pg_upmaps pass (not
        #: encoded; diagnostics for the balancer module / bench)
        self.last_balance = None

    # -- state transitions (the failure-detection consumer) -------------------

    # note: up/out/weight changes do NOT invalidate the compiled mapper —
    # compile_map depends only on the crush hierarchy; weights are a per-call
    # input and up/exists are applied in post-processing. Only crush edits
    # need invalidate_compiled().

    def invalidate_compiled(self) -> None:
        """Call after mutating self.crush (buckets/rules/tunables)."""
        self._compiled = None

    def mark_down(self, osd: int) -> None:
        self.osd_up[osd] = False
        self.epoch += 1

    def mark_up(self, osd: int) -> None:
        self.osd_up[osd] = True
        self.epoch += 1

    def mark_out(self, osd: int) -> None:
        self.osd_weight[osd] = 0
        self.epoch += 1

    def reweight(self, osd: int, weight_16_16: int) -> None:
        self.osd_weight[osd] = weight_16_16
        self.epoch += 1

    def is_down(self, osd: int) -> bool:
        return not (0 <= osd < self.max_osd and self.osd_up[osd])

    def exists(self, osd: int) -> bool:
        return 0 <= osd < self.max_osd and bool(self.osd_exists[osd])

    def is_blocklisted(
        self, name: str, nonce: int = 0, now: float | None = None
    ) -> bool:
        """OSDMap::is_blacklisted: entity-wide entry fences every
        instance; an entity/nonce entry fences one messenger instance.
        Entries expire by wall clock (utime expiry in the reference)."""
        if not self.blocklist:
            return False
        if now is None:
            import time as _time

            now = _time.time()
        for key in (name, f"{name}/{nonce}"):
            exp = self.blocklist.get(key)
            if exp is not None and exp > now:
                return True
        return False

    # -- rule lookup (CrushWrapper::find_rule) ---------------------------------

    def find_rule(self, ruleset: int, pool_type: int, size: int) -> int:
        for rid, rule in sorted(self.crush.rules.items()):
            if (
                rule.ruleset == ruleset
                and rule.type == pool_type
                and rule.min_size <= size <= rule.max_size
            ):
                return rid
        return -1

    # -- scalar pipeline -------------------------------------------------------

    def pg_to_raw_osds(self, pool_id: int, ps: int) -> tuple[list[int], int]:
        """_pg_to_raw_osds (OSDMap.cc:2359): CRUSH + drop nonexistent."""
        pool = self.pools[pool_id]
        pps = pool.raw_pg_to_pps(pool_id, ps)
        ruleno = self.find_rule(pool.crush_rule, pool.type, pool.size)
        if ruleno < 0:
            return [], pps
        raw = scalar_mapper.do_rule(
            self.crush, ruleno, pps, list(self.osd_weight), pool.size
        )
        raw = self._remove_nonexistent(pool, raw)
        return raw, pps

    def _remove_nonexistent(self, pool: PgPool, raw: list[int]) -> list[int]:
        if pool.can_shift_osds():
            return [o for o in raw if o == CRUSH_ITEM_NONE or self.exists(o)]
        return [
            o if o == CRUSH_ITEM_NONE or self.exists(o) else CRUSH_ITEM_NONE
            for o in raw
        ]

    def apply_upmap(self, pool_id: int, ps: int, raw: list[int]) -> list[int]:
        """_apply_upmap (OSDMap.cc:2389): explicit full-set override, then
        per-item from->to replacements; targets marked out are ignored."""
        pool = self.pools[pool_id]
        pg = (pool_id, pool.raw_pg_to_pg(ps))
        full = self.pg_upmap.get(pg)
        if full is not None:
            ok = all(
                not (
                    o != CRUSH_ITEM_NONE
                    and 0 <= o < self.max_osd
                    and self.osd_weight[o] == 0
                )
                for o in full
            )
            if not ok:
                # an out target invalidates the whole explicit mapping AND
                # short-circuits pg_upmap_items (OSDMap.cc:2395-2400 returns)
                return raw
            raw = list(full)
        items = self.pg_upmap_items.get(pg)
        if items is not None:
            raw = list(raw)
            for frm, to in items:
                pos = -1
                exists = False
                for i, o in enumerate(raw):
                    if o == to:
                        exists = True
                        break
                    if (
                        o == frm
                        and pos < 0
                        and not (
                            to != CRUSH_ITEM_NONE
                            and 0 <= to < self.max_osd
                            and self.osd_weight[to] == 0
                        )
                    ):
                        pos = i
                if not exists and pos >= 0:
                    raw[pos] = to
        return raw

    def raw_to_up_osds(self, pool: PgPool, raw: list[int]) -> list[int]:
        """_raw_to_up_osds (OSDMap.cc:2436): drop (replicated) or NONE-out
        (erasure) the down/nonexistent devices."""
        if pool.can_shift_osds():
            return [o for o in raw if self.exists(o) and not self.is_down(o)]
        return [
            o if self.exists(o) and not self.is_down(o) else CRUSH_ITEM_NONE
            for o in raw
        ]

    @staticmethod
    def pick_primary(osds: list[int]) -> int:
        for o in osds:
            if o != CRUSH_ITEM_NONE:
                return o
        return -1

    def apply_primary_affinity(
        self, pps: int, pool: PgPool, up: list[int], primary: int
    ) -> tuple[list[int], int]:
        """_apply_primary_affinity (OSDMap.cc:2460)."""
        aff = self.osd_primary_affinity
        if aff is None:
            return up, primary
        if not any(
            o != CRUSH_ITEM_NONE and aff[o] != DEFAULT_PRIMARY_AFFINITY
            for o in up
        ):
            return up, primary
        pos = -1
        for i, o in enumerate(up):
            if o == CRUSH_ITEM_NONE:
                continue
            a = int(aff[o])
            if a < MAX_PRIMARY_AFFINITY and (
                crush_hash32_2(pps, o) >> 16
            ) >= a:
                if pos < 0:
                    pos = i
            else:
                pos = i
                break
        if pos < 0:
            return up, primary
        primary = up[pos]
        if pool.can_shift_osds() and pos > 0:
            up = [up[pos]] + up[:pos] + up[pos + 1 :]
        return up, primary

    def get_temp_osds(
        self, pool_id: int, ps: int
    ) -> tuple[list[int], int]:
        """_get_temp_osds (OSDMap.cc:2515): pg_temp/primary_temp overrides."""
        pool = self.pools[pool_id]
        pg = (pool_id, pool.raw_pg_to_pg(ps))
        raw_temp = self.pg_temp.get(pg, [])
        if pool.can_shift_osds():
            temp = [
                o for o in raw_temp
                if self.exists(o) and not self.is_down(o)
            ]
        else:
            # positional semantics: dead members become NONE holes so the
            # surviving shards keep their offsets (OSDMap.cc:2524-2529)
            temp = [
                o if self.exists(o) and not self.is_down(o)
                else CRUSH_ITEM_NONE
                for o in raw_temp
            ]
        temp_primary = self.primary_temp.get(pg, -1)
        if temp_primary == -1 and temp:
            temp_primary = self.pick_primary(temp)
        return temp, temp_primary

    def pg_to_up_acting_osds(
        self, pool_id: int, ps: int
    ) -> tuple[list[int], int, list[int], int]:
        """_pg_to_up_acting_osds (OSDMap.cc:2591):
        returns (up, up_primary, acting, acting_primary)."""
        pool = self.pools.get(pool_id)
        if pool is None or ps >= pool.pg_num:
            return [], -1, [], -1
        acting, acting_primary = self.get_temp_osds(pool_id, ps)
        raw, pps = self.pg_to_raw_osds(pool_id, ps)
        raw = self.apply_upmap(pool_id, ps, raw)
        up = self.raw_to_up_osds(pool, raw)
        up_primary = self.pick_primary(up)
        up, up_primary = self.apply_primary_affinity(
            pps, pool, up, up_primary
        )
        if not acting:
            acting = list(up)
            if acting_primary == -1:
                acting_primary = up_primary
        return up, up_primary, acting, acting_primary

    def read_candidates(self, acting: list[int]) -> list[int]:
        """Clean-acting balanced-read targets: the live members of the
        acting set (the client side of CEPH_OSD_FLAG_BALANCE_READS
        target selection). Positional EC holes and down members are
        never candidates; backfill targets and peering state are only
        knowable OSD-side, so the serving OSD re-validates and
        redirects when it cannot prove its copy current."""
        return [
            o for o in acting
            if o != CRUSH_ITEM_NONE and not self.is_down(o)
        ]

    def whole_acting(self, acting: list[int]) -> bool:
        """True when every positional slot of the acting set holds a
        live OSD — the precondition for EC direct-shard reads (any hole
        means some shard would need a decode, i.e. the primary path)."""
        return bool(acting) and all(
            o != CRUSH_ITEM_NONE and not self.is_down(o) for o in acting
        )

    # -- batched pipeline (the ParallelPGMapper analogue) ----------------------

    def _compile(self):
        from ceph_tpu.crush import jax_mapper

        if self._compiled is None:
            self._compiled = jax_mapper.compile_map_cached(self.crush)
        return self._compiled

    def pool_mappings(
        self, pool_id: int, runtime_weights=None, return_raw=False
    ) -> np.ndarray:
        """Up sets for EVERY PG of a pool in one batched mapper run.

        Returns (pg_num, size) int32, CRUSH_ITEM_NONE-padded, after the full
        raw -> upmap -> up pipeline (erasure pools keep positional NONE
        holes; replicated pools are left-compacted). One device launch maps
        the whole pool — the batch axis is the PG id.

        runtime_weights: optional jax_mapper.runtime_weight_arrays overlay —
        candidate choose_args weight-sets evaluated as traced inputs with no
        recompile (the crush-compat balancer's per-iteration path). Callers
        must keep self.crush.choose_args in sync with the overlay: the
        sparse overrides below (upmap entries, primary-affinity rows) re-run
        through the scalar pipeline, which reads choose_args from the map.

        return_raw=True additionally returns the pre-upmap CRUSH rows
        ((pg_num, size) int32, the _pg_to_raw_osds stage before
        _remove_nonexistent) as a second array: the balancer revalidates
        candidate moves by replaying apply_upmap/raw_to_up_osds over these
        cached rows — bit-identical to a full scalar remap without paying
        the per-PG CRUSH walk per move.
        """
        from ceph_tpu.crush import jax_mapper

        pool = self.pools[pool_id]
        ps = np.arange(pool.pg_num, dtype=np.int64)
        pps = pool.raw_pg_to_pps_np(pool_id, ps)
        ruleno = self.find_rule(pool.crush_rule, pool.type, pool.size)
        if ruleno < 0:
            empty = np.full(
                (pool.pg_num, pool.size), CRUSH_ITEM_NONE, np.int32
            )
            return (empty, empty.copy()) if return_raw else empty
        if not jax_mapper.supports(self.crush, ruleno):
            # PER-RULE scope gate: only rules that reach legacy buckets
            # pay the scalar path — straw2 rules keep the batched 10x
            # even on a map that has a legacy bucket somewhere else
            out = np.full(
                (pool.pg_num, pool.size), CRUSH_ITEM_NONE, np.int32
            )
            raw_out = np.full_like(out, CRUSH_ITEM_NONE)
            for pg_ord in range(pool.pg_num):
                up, *_ = self.pg_to_up_acting_osds(pool_id, pg_ord)
                out[pg_ord, : len(up)] = up
                if return_raw:
                    rr = scalar_mapper.do_rule(
                        self.crush, ruleno,
                        int(pps[pg_ord]), list(self.osd_weight), pool.size,
                    )
                    raw_out[pg_ord, : len(rr)] = rr
            return (out, raw_out) if return_raw else out
        raw = jax_mapper.map_rule(
            self._compile(), ruleno, pps.astype(np.int32), self.osd_weight,
            pool.size, runtime_weights=runtime_weights,
        )  # (pg_num, size)

        # vectorized _remove_nonexistent + _raw_to_up_osds: valid = exists & up
        osd_ok = self.osd_exists & self.osd_up
        in_range = (raw >= 0) & (raw < self.max_osd)
        valid = np.where(in_range, osd_ok[np.clip(raw, 0, self.max_osd - 1)], False)
        none = raw == CRUSH_ITEM_NONE

        # sparse overrides (upmap entries, and rows touched by non-default
        # primary affinity, which reorders replicated up-sets): few by
        # construction, re-run through the exact scalar pipeline
        out = np.where(valid | none, raw, CRUSH_ITEM_NONE).astype(np.int32)
        # ps < pg_num guards against stale entries after a pool shrink
        overridden = {
            pg[1]
            for pg in list(self.pg_upmap) + list(self.pg_upmap_items)
            if pg[0] == pool_id and pg[1] < pool.pg_num
        }
        aff = self.osd_primary_affinity
        if aff is not None:
            special = np.zeros(self.max_osd + 1, dtype=bool)
            special[:-1] = np.asarray(aff) != DEFAULT_PRIMARY_AFFINITY
            hit = special[
                np.clip(np.where(out == CRUSH_ITEM_NONE, self.max_osd, out),
                        0, self.max_osd)
            ].any(axis=1)
            overridden |= set(np.nonzero(hit)[0].tolist())
        for pg_ord in overridden:
            up, *_ = self.pg_to_up_acting_osds(pool_id, int(pg_ord))
            row = np.full(pool.size, CRUSH_ITEM_NONE, np.int32)
            row[: len(up)] = up
            out[pg_ord] = row

        if pool.can_shift_osds():
            # left-compact each row (replicated semantics): a stable argsort
            # on the NONE mask pulls placed entries left in order — one
            # vectorized pass instead of a per-row python loop (which
            # dominated whole-pool mapping at simulator scale)
            order = np.argsort(out == CRUSH_ITEM_NONE, axis=1, kind="stable")
            out = np.take_along_axis(out, order, axis=1)
        if return_raw:
            return out, np.asarray(raw, dtype=np.int32)
        return out

    # -- balancer (calc_pg_upmaps, OSDMap.cc:4512) ------------------------------

    def calc_pg_upmaps(
        self,
        max_deviation: float = 1.0,
        max_changes: int = 10,
        pools: set[int] | None = None,
    ) -> int:
        """Batched greedy upmap balancing (crush/balance.py).

        Per-OSD PG loads come from one batched mapper launch per pool, every
        candidate (pg, from, to) move is scored in one vectorized call per
        PG-table chunk, and moves are committed greedily with pg_upmap_items
        entries until every OSD's deviation from its weight-proportional
        target is within `max_deviation` PGs or `max_changes` entries were
        made. Returns the number of changes; the full BalanceResult (spread
        before/after, launches, score latency) lands in `self.last_balance`.

        This is the balancer-module usage of the reference's calc_pg_upmaps
        (pybind/mgr/balancer/module.py:902 -> OSDMap.cc:4512).
        """
        from ceph_tpu.crush import balance

        result = balance.calc_pg_upmaps(
            self,
            max_deviation=max_deviation,
            max_changes=max_changes,
            pools=pools,
        )
        self.last_balance = result
        return result.changes


# -- incremental maps + encoding (OSDMap::Incremental, OSDMap.cc:encode) ------
#
# The reference distributes maps as versioned deltas: the mon commits an
# OSDMap::Incremental per epoch (OSDMap.h:class Incremental) and every daemon
# applies them in sequence; full maps are only sent to newcomers. The same
# protocol here, encoded with denc-lite (ceph_tpu.common.encoding). The crush
# map travels as its canonical crushtool text (compiled back on decode) —
# byte-for-byte deterministic and human-auditable, the role the reference's
# binary crush bufferlist plays.

from dataclasses import dataclass as _dataclass, field as _field

from ceph_tpu.common.encoding import Decoder as _Decoder, Encoder as _Encoder


def _enc_pg(e, pg: tuple) -> None:
    e.u64(pg[0]).u64(pg[1])


def _dec_pg(d) -> tuple:
    return (d.u64(), d.u64())


def _enc_pool(e, p: PgPool) -> None:
    e.struct(
        3,
        1,
        lambda b: b.u32(p.pg_num)
        .u32(p.pgp_num)
        .u32(p.size)
        .u32(p.min_size)
        .u8(p.type)
        .u32(p.crush_rule)
        .u64(p.flags)
        .string(p.erasure_code_profile)
        .u64(p.snap_seq)
        .list(sorted(p.removed_snaps), lambda ee, s: ee.u64(s))
        .s32(p.tier_of)
        .s32(p.read_tier)
        .s32(p.write_tier)
        .string(p.cache_mode)
        .u32(p.cache_target_dirty_max),
    )


def _dec_pool(d) -> PgPool:
    def body(b, version):
        p = PgPool(
            pg_num=b.u32(),
            pgp_num=b.u32(),
            size=b.u32(),
            min_size=b.u32(),
            type=b.u8(),
            crush_rule=b.u32(),
            flags=b.u64(),
            erasure_code_profile=b.string(),
        )
        if version >= 2:
            p.snap_seq = b.u64()
            p.removed_snaps = b.list(lambda dd: dd.u64())
        if version >= 3:
            p.tier_of = b.s32()
            p.read_tier = b.s32()
            p.write_tier = b.s32()
            p.cache_mode = b.string()
            p.cache_target_dirty_max = b.u32()
        return p

    return d.struct(3, body)


def _enc_profile(e, prof: dict) -> None:
    e.mapping(
        {str(k): str(v) for k, v in prof.items()},
        lambda enc, k: enc.string(k),
        lambda enc, v: enc.string(v),
    )


def _dec_profile(d) -> dict:
    return d.mapping(lambda dd: dd.string(), lambda dd: dd.string())


@_dataclass
class Incremental:
    """One epoch's delta (OSDMap::Incremental, src/osd/OSDMap.h).

    `epoch` is the epoch the delta PRODUCES: apply_incremental refuses it
    unless the map is currently at epoch-1, which is what makes the mon's
    commit sequence gap-free."""

    epoch: int
    new_max_osd: int | None = None
    #: full crush replacement as canonical crushtool text (None = unchanged)
    new_crush_text: str | None = None
    new_up: list = _field(default_factory=list)
    new_down: list = _field(default_factory=list)
    #: osd -> 16.16 weight (0 = out); CEPH_OSD_IN = 0x10000
    new_weight: dict = _field(default_factory=dict)
    #: osd -> 16.16 primary affinity
    new_primary_affinity: dict = _field(default_factory=dict)
    new_pools: dict = _field(default_factory=dict)
    old_pools: list = _field(default_factory=list)
    new_erasure_code_profiles: dict = _field(default_factory=dict)
    old_erasure_code_profiles: list = _field(default_factory=list)
    new_pg_upmap: dict = _field(default_factory=dict)
    old_pg_upmap: list = _field(default_factory=list)
    new_pg_upmap_items: dict = _field(default_factory=dict)
    old_pg_upmap_items: list = _field(default_factory=list)
    #: pg -> acting override; empty list clears (OSDMap.cc new_pg_temp)
    new_pg_temp: dict = _field(default_factory=dict)
    #: pg -> primary; -1 clears
    new_primary_temp: dict = _field(default_factory=dict)
    #: osd -> (host, port) announced at boot
    new_osd_addrs: dict = _field(default_factory=dict)
    #: osd -> uds:// local endpoint announced at boot ("" clears)
    new_osd_local_addrs: dict = _field(default_factory=dict)
    #: pool -> new snap_seq (selfmanaged_snap_create commits)
    new_pool_snap_seq: dict = _field(default_factory=dict)
    #: pool -> snap ids to append to removed_snaps (snap deletion)
    new_removed_snaps: dict = _field(default_factory=dict)
    #: entity identity -> unix expiry (blocklist add)
    new_blocklist: dict = _field(default_factory=dict)
    #: entity identities to un-blocklist
    old_blocklist: list = _field(default_factory=list)
    #: osd -> confirmed up_thru epoch (OSDMonitor prepare_alive)
    new_up_thru: dict = _field(default_factory=dict)

    def encode(self) -> bytes:
        def body(b):
            b.u64(self.epoch)
            b.s32(-1 if self.new_max_osd is None else self.new_max_osd)
            b.boolean(self.new_crush_text is not None)
            if self.new_crush_text is not None:
                b.string(self.new_crush_text)
            b.list(sorted(self.new_up), lambda e, v: e.u32(v))
            b.list(sorted(self.new_down), lambda e, v: e.u32(v))
            b.mapping(self.new_weight, lambda e, k: e.u32(k),
                      lambda e, v: e.u64(v))
            b.mapping(self.new_primary_affinity, lambda e, k: e.u32(k),
                      lambda e, v: e.u64(v))
            b.mapping(self.new_pools, lambda e, k: e.u64(k), _enc_pool)
            b.list(sorted(self.old_pools), lambda e, v: e.u64(v))
            b.mapping(self.new_erasure_code_profiles,
                      lambda e, k: e.string(k), _enc_profile)
            b.list(sorted(self.old_erasure_code_profiles),
                   lambda e, v: e.string(v))
            b.mapping(self.new_pg_upmap, _enc_pg,
                      lambda e, v: e.list(v, lambda ee, o: ee.s32(o)))
            b.list(sorted(self.old_pg_upmap), _enc_pg)
            b.mapping(
                self.new_pg_upmap_items, _enc_pg,
                lambda e, v: e.list(
                    v, lambda ee, p: ee.s32(p[0]).s32(p[1])
                ),
            )
            b.list(sorted(self.old_pg_upmap_items), _enc_pg)
            b.mapping(self.new_pg_temp, _enc_pg,
                      lambda e, v: e.list(v, lambda ee, o: ee.s32(o)))
            b.mapping(self.new_primary_temp, _enc_pg,
                      lambda e, v: e.s32(v))
            b.mapping(self.new_osd_addrs, lambda e, k: e.u32(k),
                      lambda e, v: e.string(v[0]).u32(v[1]))
            b.mapping(self.new_pool_snap_seq, lambda e, k: e.u64(k),
                      lambda e, v: e.u64(v))
            b.mapping(
                self.new_removed_snaps, lambda e, k: e.u64(k),
                lambda e, v: e.list(sorted(v), lambda ee, s: ee.u64(s)),
            )
            b.mapping(self.new_blocklist, lambda e, k: e.string(k),
                      lambda e, v: e.f64(v))
            b.list(sorted(self.old_blocklist), lambda e, v: e.string(v))
            b.mapping(self.new_up_thru, lambda e, k: e.u32(k),
                      lambda e, v: e.u64(v))
            b.mapping(self.new_osd_local_addrs, lambda e, k: e.u32(k),
                      lambda e, v: e.string(v))

        return _Encoder().struct(5, 1, body).bytes()

    @staticmethod
    def decode(raw: bytes) -> "Incremental":
        def body(b, version):
            inc = Incremental(epoch=b.u64())
            nmo = b.s32()
            inc.new_max_osd = None if nmo < 0 else nmo
            if b.boolean():
                inc.new_crush_text = b.string()
            inc.new_up = b.list(lambda d: d.u32())
            inc.new_down = b.list(lambda d: d.u32())
            inc.new_weight = b.mapping(lambda d: d.u32(), lambda d: d.u64())
            inc.new_primary_affinity = b.mapping(
                lambda d: d.u32(), lambda d: d.u64()
            )
            inc.new_pools = b.mapping(lambda d: d.u64(), _dec_pool)
            inc.old_pools = b.list(lambda d: d.u64())
            inc.new_erasure_code_profiles = b.mapping(
                lambda d: d.string(), _dec_profile
            )
            inc.old_erasure_code_profiles = b.list(lambda d: d.string())
            inc.new_pg_upmap = b.mapping(
                _dec_pg, lambda d: d.list(lambda dd: dd.s32())
            )
            inc.old_pg_upmap = b.list(_dec_pg)
            inc.new_pg_upmap_items = b.mapping(
                _dec_pg,
                lambda d: d.list(lambda dd: (dd.s32(), dd.s32())),
            )
            inc.old_pg_upmap_items = b.list(_dec_pg)
            inc.new_pg_temp = b.mapping(
                _dec_pg, lambda d: d.list(lambda dd: dd.s32())
            )
            inc.new_primary_temp = b.mapping(_dec_pg, lambda d: d.s32())
            inc.new_osd_addrs = b.mapping(
                lambda d: d.u32(), lambda d: (d.string(), d.u32())
            )
            if version >= 2:
                inc.new_pool_snap_seq = b.mapping(
                    lambda d: d.u64(), lambda d: d.u64()
                )
                inc.new_removed_snaps = b.mapping(
                    lambda d: d.u64(),
                    lambda d: d.list(lambda dd: dd.u64()),
                )
            if version >= 3:
                inc.new_blocklist = b.mapping(
                    lambda d: d.string(), lambda d: d.f64()
                )
                inc.old_blocklist = b.list(lambda d: d.string())
            if version >= 4:
                inc.new_up_thru = b.mapping(
                    lambda d: d.u32(), lambda d: d.u64()
                )
            if version >= 5:
                inc.new_osd_local_addrs = b.mapping(
                    lambda d: d.u32(), lambda d: d.string()
                )
            return inc

        return _Decoder(raw).struct(5, body)


def apply_incremental(self, inc: Incremental) -> None:
    """OSDMap::apply_incremental (OSDMap.cc): strict epoch+1 sequencing."""
    if inc.epoch != self.epoch + 1:
        raise ValueError(
            f"incremental for epoch {inc.epoch} cannot apply to map at "
            f"epoch {self.epoch}"
        )
    if inc.new_max_osd is not None and inc.new_max_osd != self.max_osd:
        n = inc.new_max_osd

        def grow(arr, fill, dtype):
            out = np.full(n, fill, dtype=dtype)
            out[: min(len(arr), n)] = arr[: min(len(arr), n)]
            return out

        self.osd_exists = grow(self.osd_exists, True, bool)
        self.osd_up = grow(self.osd_up, True, bool)
        self.osd_weight = grow(self.osd_weight, 0x10000, np.int64)
        self.osd_up_thru = grow(self.osd_up_thru, 0, np.int64)
        if self.osd_primary_affinity is not None:
            self.osd_primary_affinity = grow(
                self.osd_primary_affinity, DEFAULT_PRIMARY_AFFINITY, np.int64
            )
        self.max_osd = n
    if inc.new_crush_text is not None:
        from ceph_tpu.crush.compiler import compile_crushmap

        self.crush = compile_crushmap(inc.new_crush_text)
        self.invalidate_compiled()
    for osd in inc.new_up:
        self.osd_up[osd] = True
    for osd in inc.new_down:
        self.osd_up[osd] = False
    for osd, w in inc.new_weight.items():
        self.osd_weight[osd] = w
    if inc.new_primary_affinity:
        if self.osd_primary_affinity is None:
            self.osd_primary_affinity = np.full(
                self.max_osd, DEFAULT_PRIMARY_AFFINITY, dtype=np.int64
            )
        for osd, a in inc.new_primary_affinity.items():
            self.osd_primary_affinity[osd] = a
    for pid, pool in inc.new_pools.items():
        self.pools[pid] = pool
    for pid in inc.old_pools:
        self.pools.pop(pid, None)
    for name, prof in inc.new_erasure_code_profiles.items():
        self.erasure_code_profiles[name] = dict(prof)
    for name in inc.old_erasure_code_profiles:
        self.erasure_code_profiles.pop(name, None)
    self.pg_upmap.update(inc.new_pg_upmap)
    for pg in inc.old_pg_upmap:
        self.pg_upmap.pop(pg, None)
    self.pg_upmap_items.update(inc.new_pg_upmap_items)
    for pg in inc.old_pg_upmap_items:
        self.pg_upmap_items.pop(pg, None)
    for pg, acting in inc.new_pg_temp.items():
        if acting:
            self.pg_temp[pg] = list(acting)
        else:
            self.pg_temp.pop(pg, None)
    for pg, primary in inc.new_primary_temp.items():
        if primary >= 0:
            self.primary_temp[pg] = primary
        else:
            self.primary_temp.pop(pg, None)
    for osd, addr in inc.new_osd_addrs.items():
        self.osd_addrs[osd] = tuple(addr)
    for osd, la in inc.new_osd_local_addrs.items():
        if la:
            self.osd_local_addrs[osd] = la
        else:
            self.osd_local_addrs.pop(osd, None)
    for pid, seq in inc.new_pool_snap_seq.items():
        if pid in self.pools:
            self.pools[pid].snap_seq = max(self.pools[pid].snap_seq, seq)
    for pid, snaps in inc.new_removed_snaps.items():
        if pid in self.pools:
            cur = set(self.pools[pid].removed_snaps)
            cur.update(snaps)
            self.pools[pid].removed_snaps = sorted(cur)
    self.blocklist.update(inc.new_blocklist)
    for entity in inc.old_blocklist:
        self.blocklist.pop(entity, None)
    for osd, e in inc.new_up_thru.items():
        if 0 <= osd < self.max_osd:
            self.osd_up_thru[osd] = max(
                int(self.osd_up_thru[osd]), int(e)
            )
    self.epoch = inc.epoch


def encode_osdmap(self) -> bytes:
    """Full map for newcomers (OSDMap::encode)."""
    from ceph_tpu.crush.compiler import decompile_crushmap

    crush_text = decompile_crushmap(self.crush)

    def body(b):
        b.u64(self.epoch)
        b.u32(self.max_osd)
        b.string(crush_text)
        b.blob(np.asarray(self.osd_exists, np.uint8).tobytes())
        b.blob(np.asarray(self.osd_up, np.uint8).tobytes())
        b.list(
            [int(w) for w in self.osd_weight], lambda e, v: e.u64(v)
        )
        b.boolean(self.osd_primary_affinity is not None)
        if self.osd_primary_affinity is not None:
            b.list(
                [int(a) for a in self.osd_primary_affinity],
                lambda e, v: e.u64(v),
            )
        b.mapping(self.pools, lambda e, k: e.u64(k), _enc_pool)
        b.mapping(self.erasure_code_profiles, lambda e, k: e.string(k),
                  _enc_profile)
        b.mapping(self.pg_upmap, _enc_pg,
                  lambda e, v: e.list(v, lambda ee, o: ee.s32(o)))
        b.mapping(
            self.pg_upmap_items, _enc_pg,
            lambda e, v: e.list(v, lambda ee, p: ee.s32(p[0]).s32(p[1])),
        )
        b.mapping(self.pg_temp, _enc_pg,
                  lambda e, v: e.list(v, lambda ee, o: ee.s32(o)))
        b.mapping(self.primary_temp, _enc_pg, lambda e, v: e.s32(v))
        b.mapping(self.osd_addrs, lambda e, k: e.u32(k),
                  lambda e, v: e.string(v[0]).u32(v[1]))
        b.mapping(self.blocklist, lambda e, k: e.string(k),
                  lambda e, v: e.f64(v))
        b.list(
            [int(v) for v in self.osd_up_thru], lambda e, v: e.u64(v)
        )
        b.mapping(self.osd_local_addrs, lambda e, k: e.u32(k),
                  lambda e, v: e.string(v))

    return _Encoder().struct(4, 1, body).bytes()


def decode_osdmap(raw: bytes) -> "OSDMap":
    from ceph_tpu.crush.compiler import compile_crushmap

    def body(b, version):
        epoch = b.u64()
        max_osd = b.u32()
        crush = compile_crushmap(b.string())
        exists = np.frombuffer(b.blob(), np.uint8).astype(bool)
        up = np.frombuffer(b.blob(), np.uint8).astype(bool)
        weight = np.array(b.list(lambda d: d.u64()), dtype=np.int64)
        paff = None
        if b.boolean():
            paff = np.array(b.list(lambda d: d.u64()), dtype=np.int64)
        m = OSDMap(
            crush=crush,
            max_osd=max_osd,
            epoch=epoch,
            osd_exists=exists,
            osd_up=up,
            osd_weight=weight,
            osd_primary_affinity=paff,
        )
        m.pools = b.mapping(lambda d: d.u64(), _dec_pool)
        m.erasure_code_profiles = b.mapping(
            lambda d: d.string(), _dec_profile
        )
        m.pg_upmap = b.mapping(
            _dec_pg, lambda d: d.list(lambda dd: dd.s32())
        )
        m.pg_upmap_items = b.mapping(
            _dec_pg, lambda d: d.list(lambda dd: (dd.s32(), dd.s32()))
        )
        m.pg_temp = b.mapping(
            _dec_pg, lambda d: d.list(lambda dd: dd.s32())
        )
        m.primary_temp = b.mapping(_dec_pg, lambda d: d.s32())
        m.osd_addrs = b.mapping(
            lambda d: d.u32(), lambda d: (d.string(), d.u32())
        )
        if version >= 2:
            m.blocklist = b.mapping(
                lambda d: d.string(), lambda d: d.f64()
            )
        if version >= 3:
            m.osd_up_thru = np.array(
                b.list(lambda d: d.u64()), dtype=np.int64
            )
            if len(m.osd_up_thru) != m.max_osd:
                m.osd_up_thru = np.zeros(m.max_osd, dtype=np.int64)
        if version >= 4:
            m.osd_local_addrs = b.mapping(
                lambda d: d.u32(), lambda d: d.string()
            )
        return m

    return _Decoder(raw).struct(4, body)


# bound here so the dataclass body above stays focused on placement; these
# names are the public API (map.apply_incremental(inc), map.encode(),
# OSDMap.decode(raw))
OSDMap.apply_incremental = apply_incremental
OSDMap.encode = encode_osdmap
OSDMap.decode = staticmethod(decode_osdmap)
