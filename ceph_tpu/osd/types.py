"""Pool/PG types and the stable-mod placement-seed math.

Mirrors the reference's `pg_pool_t` (osd_types.h:1155-1603) and the
`ceph_stable_mod` bin-split hash (include/rados.h:86-92): a PG id is
(pool, ps); `raw_pg_to_pps` folds ps and pool into the CRUSH input seed
(osd_types.cc:1640-1654) so different pools don't collide.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ceph_tpu.crush.hash import crush_hash32_2, crush_hash32_2_np

# pg_pool_t::TYPE_* (osd_types.h:1156-1160)
TYPE_REPLICATED = 1
TYPE_ERASURE = 3

# pg_pool_t::FLAG_* (osd_types.h:1166+)
FLAG_HASHPSPOOL = 1 << 0


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """Stable modulo: bins can grow without reshuffling everything
    (include/rados.h:86-92)."""
    return x & bmask if (x & bmask) < b else x & (bmask >> 1)


def ceph_stable_mod_np(x, b: int, bmask: int):
    x = np.asarray(x, dtype=np.int64)
    low = x & bmask
    return np.where(low < b, low, x & (bmask >> 1))


def pg_num_mask(pg_num: int) -> int:
    """Containing power-of-two minus one (pg_pool_t::calc_pg_masks)."""
    return (1 << max(pg_num - 1, 0).bit_length()) - 1


@dataclass
class PgPool:
    """The placement-relevant subset of pg_pool_t."""

    pg_num: int = 8
    pgp_num: int = 0  # defaults to pg_num
    size: int = 3
    min_size: int = 2
    type: int = TYPE_REPLICATED
    crush_rule: int = 0
    flags: int = FLAG_HASHPSPOOL
    # erasure profile name, carried for the data path (pg_pool_t stores the
    # profile name; the mon holds the name -> profile map)
    erasure_code_profile: str = ""
    #: self-managed snapshot allocator high-water (pg_pool_t::snap_seq);
    #: selfmanaged_snap_create returns snap_seq+1 committed via the mon
    snap_seq: int = 0
    #: deleted snap ids (pg_pool_t::removed_snaps interval_set, as a flat
    #: list at mini scale); OSDs trim clones covered only by removed snaps
    removed_snaps: list = field(default_factory=list)
    #: cache tiering (pg_pool_t::tier_of / read_tier / write_tier /
    #: cache_mode, osd_types.h): `tier_of` on the CACHE pool names its
    #: base; `read_tier`/`write_tier` on the BASE pool name the overlay
    #: the Objecter redirects to; cache_mode "" | "writeback"
    tier_of: int = -1
    read_tier: int = -1
    write_tier: int = -1
    cache_mode: str = ""
    #: dirty objects a cache PG primary tolerates before the tier agent
    #: flushes to the base pool (cache_target_dirty_ratio's object-count
    #: role at mini scale)
    cache_target_dirty_max: int = 8

    def __post_init__(self):
        if not self.pgp_num:
            self.pgp_num = self.pg_num

    @property
    def pg_mask(self) -> int:
        return pg_num_mask(self.pg_num)

    @property
    def pgp_mask(self) -> int:
        return pg_num_mask(self.pgp_num)

    def is_erasure(self) -> bool:
        return self.type == TYPE_ERASURE

    def can_shift_osds(self) -> bool:
        """Replicated sets compact over gaps; EC sets are positional
        (pg_pool_t::can_shift_osds, osd_types.h)."""
        return self.type == TYPE_REPLICATED

    def raw_pg_to_pg(self, ps: int) -> int:
        """Full-precision ps -> actual pg ordinal (osd_types.cc:1628-1632)."""
        return ceph_stable_mod(ps, self.pg_num, self.pg_mask)

    def raw_pg_to_pps(self, pool_id: int, ps: int) -> int:
        """Placement seed for CRUSH (osd_types.cc:1640-1654)."""
        if self.flags & FLAG_HASHPSPOOL:
            return crush_hash32_2(
                ceph_stable_mod(ps, self.pgp_num, self.pgp_mask), pool_id
            )
        return ceph_stable_mod(ps, self.pgp_num, self.pgp_mask) + pool_id

    def raw_pg_to_pps_np(self, pool_id: int, ps) -> np.ndarray:
        """Vectorized raw_pg_to_pps over an array of ps values."""
        stable = ceph_stable_mod_np(ps, self.pgp_num, self.pgp_mask)
        if self.flags & FLAG_HASHPSPOOL:
            return crush_hash32_2_np(
                stable.astype(np.uint32), np.uint32(pool_id)
            ).astype(np.int64)
        return stable + pool_id
