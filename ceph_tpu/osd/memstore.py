"""MemStore: the in-memory ObjectStore used by the mini data path.

Plays the role of the reference's MemStore (/root/reference/src/os/memstore) —
the disk-free ObjectStore every OSD-logic test runs against — with the fault
hooks the qa suites drive through config injection
(`ms_inject_socket_failures`, options.cc:1044-1066; EIO corruption via
test-erasure-eio.sh): a store can be killed (OSD death), individual objects
can be poisoned with EIO, and a transient-failure rate makes ops fail
intermittently so callers exercise their retry paths.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field


class ObjectStoreError(Exception):
    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code  # "EIO" | "ENOENT" | "ECONN" | "EDOWN"


@dataclass
class MemStore:
    """One OSD's object store: key -> bytes, plus fault state."""

    osd_id: int
    objects: dict[tuple, bytes] = field(default_factory=dict)
    #: key -> xattr map (HashInfo etc.), like ObjectStore::getattrs
    attrs: dict[tuple, dict] = field(default_factory=dict)
    alive: bool = True
    eio_keys: set = field(default_factory=set)
    #: 1-in-N transient op failure (0 = off), ms_inject_socket_failures-style
    inject_transient_every: int = 0
    _rng: random.Random = field(default_factory=lambda: random.Random(0))
    reads: int = 0
    bytes_read: int = 0
    writes: int = 0

    def _gate(self, key=None) -> None:
        if not self.alive:
            raise ObjectStoreError("EDOWN", f"osd.{self.osd_id} is down")
        if self.inject_transient_every and (
            self._rng.randrange(self.inject_transient_every) == 0
        ):
            raise ObjectStoreError(
                "ECONN", f"osd.{self.osd_id} injected transient failure"
            )
        if key is not None and key in self.eio_keys:
            raise ObjectStoreError("EIO", f"osd.{self.osd_id} EIO on {key}")

    def write(self, key: tuple, data: bytes, attrs: dict | None = None) -> None:
        self._gate()
        self.objects[key] = bytes(data)
        if attrs is not None:
            self.attrs[key] = dict(attrs)
        self.writes += 1

    def getattrs(self, key: tuple) -> dict:
        """Object attributes (the xattr map real stores keep per object)."""
        self._gate(key)
        return self.attrs.get(key, {})

    def read(self, key: tuple, offset: int = 0, length: int | None = None) -> bytes:
        self._gate(key)
        if key not in self.objects:
            raise ObjectStoreError("ENOENT", f"osd.{self.osd_id}: no {key}")
        self.reads += 1
        data = self.objects[key]
        out = data[offset:] if length is None else data[offset : offset + length]
        self.bytes_read += len(out)
        return out

    def read_runs(self, key: tuple, runs, unit: int) -> bytes:
        """Gather (offset, count) sub-chunk runs of `unit` bytes each —
        the partial-read shape minimum_to_decode hands back for array codes."""
        return b"".join(
            self.read(key, off * unit, count * unit) for off, count in runs
        )

    def remove(self, key: tuple) -> None:
        self._gate()
        self.objects.pop(key, None)
        self.attrs.pop(key, None)

    def keys(self):
        return list(self.objects)
