"""Extent allocator for BlockStore — the Allocator + FreelistManager roles.

The reference splits block-space management in two (src/os/bluestore):
an in-memory `Allocator` (Allocator.h; bitmap/avl/stupid variants) that
answers "give me N bytes of free extents", and a `FreelistManager`
(FreelistManager.h) that persists which extents are free as KV rows in the
same RocksDB transaction as the metadata they pay for — which is exactly
what makes allocation crash-consistent: an extent changes state only when
the batch that references it commits.

`ExtentAllocator` collapses both roles at our scale: a coalesced
offset->length map served first-fit in address order (the stupid/avl
discipline; address order keeps reuse dense so the block file stays
compact), min_alloc_size rounding (bluestore_min_alloc_size), and
`flush()` which emits only the CHANGED free-list rows into the caller's
KV batch — the delta discipline FreelistManager's merge ops give the
reference, sized for a Python dict instead of a bitmap.

The device is a grow-on-demand file by default: allocation beyond the
current high-water mark extends `size` (persisted alongside the rows).
An optional `capacity` cap (`blockstore_block_size`) plays the
fixed-disk role: an allocation that cannot be met from free space plus
growth headroom raises `StoreError("ENOSPC")` *before* mutating any
state — clean, un-fenced, and retryable once frees land. `check()` is
the fsck cross-check: given every
extent the onodes reference, verify allocated ∪ free tiles [0, size)
exactly — overlaps and leaks are each reported, never repaired silently.
"""

from __future__ import annotations

from ceph_tpu.common.encoding import Encoder
from ceph_tpu.osd.objectstore import StoreError


def _row_key(off: int) -> bytes:
    # big-endian so ordered KV iteration walks the device address order
    return off.to_bytes(8, "big")


class ExtentAllocator:
    """First-fit extent allocator with persistent free-list deltas."""

    def __init__(self, min_alloc_size: int = 4096, capacity: int = 0):
        if min_alloc_size <= 0 or min_alloc_size & (min_alloc_size - 1):
            raise ValueError(
                f"min_alloc_size must be a power of two, got {min_alloc_size}"
            )
        self.min_alloc_size = min_alloc_size
        #: hard device-size cap (bytes; the fixed-disk role): allocation
        #: that would grow past it raises ENOSPC; 0 = grow-on-demand
        self.capacity = capacity
        #: disjoint, coalesced free extents: offset -> length
        self.free: dict[int, int] = {}
        #: device high-water mark (the grow-on-demand "disk size")
        self.size = 0
        self._persisted: dict[int, int] = {}
        self._persisted_size = 0

    # -- state ----------------------------------------------------------------

    def init(self, free: dict[int, int], size: int) -> None:
        """Adopt the persisted state a (re)opening store loaded."""
        self.free = dict(free)
        self.size = size
        self._persisted = dict(free)
        self._persisted_size = size

    def round_up(self, n: int) -> int:
        m = self.min_alloc_size
        return (n + m - 1) // m * m

    def free_bytes(self) -> int:
        return sum(self.free.values())

    def allocated_bytes(self) -> int:
        return self.size - self.free_bytes()

    # -- allocate / release ----------------------------------------------------

    def allocate(self, length: int) -> list[tuple[int, int]]:
        """Return disjoint extents totalling round_up(length) bytes —
        free extents first (address order), then an end-of-device
        extension. May span multiple extents (BlueStore PExtentVector).

        Prefers the first free extent that fits the whole ask (so the
        common allocation is one contiguous run the vectored device IO
        path serves with a single pwrite/pread) before falling back to
        first-fit spanning across fragments; spanning still beats
        growing the device, which keeps the block file compact."""
        need = self.round_up(length)
        # capacity gate BEFORE any mutation, so a failed ask leaves the
        # free map untouched: ENOSPC must be clean and retryable after
        # frees — never a half-allocated state
        if self.capacity and need > self.free_bytes() + max(
            0, self.capacity - self.size
        ):
            raise StoreError(
                "ENOSPC",
                f"allocating {need} bytes: {self.free_bytes()} free + "
                f"{max(0, self.capacity - self.size)} growable of a "
                f"{self.capacity}-byte device",
            )
        if need:
            for off in sorted(self.free):
                ln = self.free[off]
                if ln >= need:
                    self.free.pop(off)
                    if need < ln:
                        self.free[off + need] = ln - need
                    return [(off, need)]
        got: list[tuple[int, int]] = []
        for off in sorted(self.free):
            if not need:
                break
            ln = self.free.pop(off)
            take = min(ln, need)
            got.append((off, take))
            if take < ln:
                self.free[off + take] = ln - take
            need -= take
        if need:
            got.append((self.size, need))
            self.size += need
        return got

    def allocate_many(
        self, lengths: list[int]
    ) -> list[list[tuple[int, int]]]:
        """One allocator pass for a whole batch (the deferred-flush
        shape): allocate round_up(sum) bytes once, then carve the
        returned extents into per-length runs at min_alloc boundaries.
        Cheaper than N allocate() calls and it lands the batch in one
        (usually contiguous) device region, so the flush coalesces into
        very few writes."""
        pool = self.allocate(sum(self.round_up(n) for n in lengths))
        out: list[list[tuple[int, int]]] = []
        for n in lengths:
            need = self.round_up(n)
            got: list[tuple[int, int]] = []
            while need:
                off, ln = pool[0]
                take = min(ln, need)
                got.append((off, take))
                if take < ln:
                    pool[0] = (off + take, ln - take)
                else:
                    pool.pop(0)
                need -= take
            out.append(got)
        return out

    def release(self, extents) -> None:
        """Return extents to the free map, coalescing neighbors."""
        if not extents:
            return
        for off, ln in extents:
            self.free[off] = ln
        merged: dict[int, int] = {}
        last = None
        for off in sorted(self.free):
            ln = self.free[off]
            if last is not None and last + merged[last] == off:
                merged[last] += ln
            else:
                merged[off] = ln
                last = off
        self.free = merged

    # -- persistence -----------------------------------------------------------

    def flush(self, kv, table: bytes, meta_table: bytes,
              size_key: bytes = b"size") -> None:
        """Emit the free-list rows that changed since the last flush into
        `kv` (the caller's batch), so free-space state commits atomically
        with the onodes that allocated/released it."""
        for off in self._persisted.keys() - self.free.keys():
            kv.rm(table, _row_key(off))
        for off, ln in self.free.items():
            if self._persisted.get(off) != ln:
                kv.set(table, _row_key(off), Encoder().u64(ln).bytes())
        if self.size != self._persisted_size:
            kv.set(meta_table, size_key, Encoder().u64(self.size).bytes())
        self._persisted = dict(self.free)
        self._persisted_size = self.size

    # -- fsck ------------------------------------------------------------------

    def check(self, allocated) -> list[str]:
        """Cross-check onode extents vs the free list: allocated ∪ free
        must tile [0, size) with no overlap. Returns error strings."""
        errors: list[str] = []
        marks = [(off, ln, "allocated") for off, ln in allocated]
        marks += [(off, ln, "free") for off, ln in self.free.items()]
        marks.sort()
        pos = 0
        for off, ln, kind in marks:
            if ln <= 0 or off % self.min_alloc_size or ln % self.min_alloc_size:
                errors.append(f"misaligned {kind} extent ({off}, {ln})")
            if off + ln > self.size:
                errors.append(
                    f"{kind} extent ({off}, {ln}) beyond device size {self.size}"
                )
            if off < pos:
                errors.append(
                    f"{kind} extent ({off}, {ln}) overlaps the previous extent"
                )
            elif off > pos:
                errors.append(f"leaked space [{pos}, {off})")
            pos = max(pos, off + ln)
        if pos < self.size:
            errors.append(f"leaked space [{pos}, {self.size})")
        return errors
