"""``python -m ceph_tpu.osd --id N --spec cluster_spec.json``

The OSD daemon main (the reference's ``src/ceph_osd.cc:106``): one
OSDService in its own OS process, FileDB-backed, SIGTERM for clean
shutdown; SIGKILL is the crash path the multi-process thrasher exercises.
"""

import argparse

from ceph_tpu.vstart import daemon_main


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--id", type=int, required=True, help="osd id")
    ap.add_argument("--spec", required=True, help="cluster spec path")
    args = ap.parse_args()
    daemon_main("osd", args.id, args.spec)


main()
