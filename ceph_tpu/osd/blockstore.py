"""BlockStore: the BlueStore-analogue local object store.

Re-expresses the reference's src/os/bluestore design at our scale: object
**data** lives as allocator-managed extents in one raw block file; all
**metadata** — onodes (extent map + per-block checksums), xattrs, omap,
collections, and the allocator free list — lives in the `KeyValueDB`
(RocksDB's role). A `Transaction` still commits as exactly one KV batch,
and the ordering discipline is BlueStore's:

  * **big writes** (>= min_alloc_size) go to freshly-allocated extents —
    never to space a live onode references — and the device is fsynced
    *before* the KV batch commits, so a crash at any point leaves the old
    onode pointing at intact old bytes (copy-on-write, no torn data);
  * **small writes** (< min_alloc_size) are *deferred*: the payload rides
    the KV WAL batch itself (the commit point) and `flush_deferred` later
    moves it onto the device, repointing the onode in a second batch —
    BlueStore's deferred-write path, crash-safe because the WAL row stays
    authoritative until that second batch commits;
  * **frees** are quarantined until the batch that drops them commits —
    reusing a freed extent earlier could clobber bytes the previous onode
    still references across a crash.

Every checksum block (bluestore_csum_block_size) of the stored payload is
crc32c-summed on write and verified on every device read; a mismatch
raises `StoreError("EIO", ...)`, which the OSD's deep scrub surfaces as a
`read_error` inconsistency and repairs from healthy peers. Optional
compression-on-write runs the payload through the compressor registry
(BlueStore's compression_mode/required_ratio policy) with the compressed
length tracked per blob. `fsck(deep=...)` cross-checks onode extents vs
the free list (allocated ∪ free must tile the device exactly) and — deep —
re-reads every blob against its stored checksums.

The fast path (BlueStore's cache trio + deferred aging):

  * an **onode LRU** (`blockstore_onode_cache_size`) keeps decoded
    onodes so hot objects skip the KV fetch + decode; entries fold in
    only after the KV batch that changes them commits, so the cache is
    always committed truth (aborted compiles never pollute it);
  * a **buffer cache** (`blockstore_buffer_cache_bytes`, LRU by bytes,
    write-through) keeps recently read/written logical object data so
    re-reads skip the device and the checksum re-verify entirely.
    `read_verify` bypasses it (and refreshes it) — deep scrub and fsck
    always see device truth, so cached data can never mask at-rest
    corruption; `drop_caches` is the restart-equivalent hook tests use;
  * a **background flusher** drains the deferred backlog once its oldest
    entry exceeds `blockstore_deferred_max_age_ms` (BlueStore's
    deferred_try_submit aging), instead of only on byte pressure. It
    starts lazily on the first commit that leaves a backlog — a store
    opened for inspection (fsck / objectstore_tool) never spawns one —
    and is joined before the device closes. Crash-safety is unchanged:
    the flush is the same WAL-row-authoritative two-phase move;
  * **vectored device IO**: adjacent extents coalesce into single
    pwrite/pread calls (writev/readv discipline), and the deferred flush
    batches the whole backlog into ONE allocator pass + one coalesced
    write plan + one fsync + one KV batch.

Per-store `PerfCounters` (cache hits/misses, deferred queue depth/age,
flush latency, device call/segment counts) make the wins observable via
`perf dump` when a daemon adopts the block.

The device fault layer (the one fault domain `ms_inject_*` can't reach):

  * **injection** — config-driven hooks at the device IO sites, the
    `_InjectingStream` idiom with its disabled-cost rule (one cached
    flag check per site): `blockstore_inject_read_eio` /
    `blockstore_inject_write_eio` / `blockstore_inject_fsync_fail` as
    1-in-N rates, plus `inject_data_error()` — the `injectdataerr`
    admin-command analogue — arming a deterministic per-object read EIO
    that clears when the object is rewritten (so a write-back repair
    genuinely heals it). Injected counts ride the perf block;
  * **error taxonomy** (see `StoreError`): a READ error is `EIO` and
    recoverable above the store — the OSD heals the object from
    replicas/EC survivors; a WRITE or FSYNC error is `StoreFatalError`
    and **fences** the store: it flips read-only (`fenced`), refuses
    every further transaction with `EROFS` so no ack can lie about
    durability, and fires `on_fatal` so the owning OSD can fail-stop
    (report itself to the mon and shut down). ENOSPC from a
    capacity-capped allocator (`blockstore_block_size`) is transient:
    nothing fences, and frees make the store writable again.
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

from ceph_tpu.common.crc import ceph_crc32c
from ceph_tpu.common.encoding import Decoder, Encoder
from ceph_tpu.common.kv import KeyValueDB, KVTransaction
from ceph_tpu.common.perf_counters import PerfCounters
from ceph_tpu.osd.allocator import ExtentAllocator
from ceph_tpu.osd.objectstore import (
    _ATTR,
    _OMAP,
    KStore,
    StoreError,
    StoreFatalError,
    _encode_attrs,
    _okey,
    _okey_decode,
)

_ONODE = b"ond"  # onode rows: size, flags, extent map, csums (O prefix)
_DEFER = b"dfw"  # deferred sub-min_alloc payloads riding the KV WAL
_FREE = b"fre"   # allocator free-list rows (FreelistManager's B prefix)
_BMETA = b"bmt"  # store meta: device size + pinned geometry

_CSUM_SEED = 0xFFFFFFFF

FLAG_INLINE = 1      # payload lives in the _DEFER row, not on the device
FLAG_COMPRESSED = 2  # stored payload is comp_alg-compressed


@dataclass
class Onode:
    """Per-object metadata row (bluestore_onode_t + its blob/extent maps,
    flattened: one blob per object at our scale)."""

    size: int = 0         # logical object size
    flags: int = 0
    comp_alg: str = ""    # compressor name when FLAG_COMPRESSED
    stored_len: int = 0   # physical payload length (== size when raw)
    csum_block: int = 4096
    extents: list = field(default_factory=list)  # [(offset, length)]
    csums: list = field(default_factory=list)    # u32 per csum block

    def encode(self) -> bytes:
        def body(b):
            b.u8(self.flags).u64(self.size).string(self.comp_alg)
            b.u64(self.stored_len).u32(self.csum_block)
            b.list(self.extents, lambda e, x: e.u64(x[0]).u64(x[1]))
            b.list(self.csums, lambda e, c: e.u32(c))

        return Encoder().struct(1, 1, body).bytes()

    @staticmethod
    def decode(raw: bytes) -> "Onode":
        def body(b, _version):
            on = Onode(flags=b.u8(), size=b.u64(), comp_alg=b.string())
            on.stored_len = b.u64()
            on.csum_block = b.u32()
            on.extents = b.list(lambda d: (d.u64(), d.u64()))
            on.csums = b.list(lambda d: d.u32())
            return on

        on = Decoder(raw).struct(1, body)
        on.size, on.stored_len = int(on.size), int(on.stored_len)
        return on


def _coalesce(extents) -> list[tuple[int, int]]:
    """Merge device-adjacent extents into runs: [(0,4096),(4096,4096)]
    -> [(0,8192)]. Inputs are in payload order; only extents adjacent in
    BOTH payload and device order merge, so a run is always one
    contiguous pread/pwrite of in-order payload bytes."""
    runs: list[list[int]] = []
    for off, ln in extents:
        if runs and runs[-1][0] + runs[-1][1] == off:
            runs[-1][1] += ln
        else:
            runs.append([off, ln])
    return [(off, ln) for off, ln in runs]


# ---------------------------------------------------------------------------
# Block devices (KernelDevice's role, reduced to pread/pwrite/flush)


class MemBlockDevice:
    """bytearray-backed device — the MemStore-tier BlockStore for tests
    (and the bit-rot injection surface: flip bytes in `buf`)."""

    path = None

    def __init__(self) -> None:
        self.buf = bytearray()

    def pwrite(self, off: int, data: bytes) -> None:
        end = off + len(data)
        if len(self.buf) < end:
            self.buf.extend(b"\x00" * (end - len(self.buf)))
        self.buf[off:end] = data

    def pwritev(self, off: int, buffers) -> None:
        """One contiguous vectored write (writev at a device offset)."""
        self.pwrite(off, b"".join(buffers))

    def pread(self, off: int, length: int) -> bytes:
        out = bytes(self.buf[off:off + length])
        return out + b"\x00" * (length - len(out))  # sparse tail is zeros

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class FileBlockDevice:
    """One raw block file, grow-on-demand; flush() is a real fsync — the
    write-before-commit ordering the crash story depends on.

    All IO is raw positional fd syscalls (os.pread/os.pwrite(v)) —
    KernelDevice's shape — deliberately avoiding Python's buffered file
    objects: mixing a BufferedRandom's seek-within-buffer fast path with
    raw vectored writes on the same fd can serve stale bytes."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        self._closed = False

    def pwrite(self, off: int, data: bytes) -> None:
        view = memoryview(data)
        while view:
            n = os.pwrite(self._fd, view, off)
            off += n
            view = view[n:]

    def pwritev(self, off: int, buffers) -> None:
        """One contiguous vectored write: os.pwritev when the platform
        has it (one syscall for the whole coalesced run, the io_uring-ish
        shape), else a joined pwrite."""
        buffers = [b for b in buffers if b]
        if not buffers:
            return
        if len(buffers) == 1 or not hasattr(os, "pwritev"):
            self.pwrite(off, buffers[0] if len(buffers) == 1
                        else b"".join(buffers))
            return
        queue = [memoryview(b) for b in buffers]
        while queue:
            n = os.pwritev(self._fd, queue, off)
            off += n
            while queue and n >= len(queue[0]):
                n -= len(queue[0])
                queue.pop(0)
            if queue and n:
                queue[0] = queue[0][n:]

    def pread(self, off: int, length: int) -> bytes:
        out = os.pread(self._fd, length, off)
        while len(out) < length:  # short reads only happen at EOF...
            more = os.pread(self._fd, length - len(out), off + len(out))
            if not more:
                break
            out += more
        return out + b"\x00" * (length - len(out))  # ...sparse tail: zeros

    def flush(self) -> None:
        os.fsync(self._fd)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            os.close(self._fd)


# ---------------------------------------------------------------------------


class BlockStore(KStore):
    """ObjectStore with data on a block device; see module docstring.

    Inherits the collection/attr/omap row handling from KStore and
    overrides only the data-bearing ops — the BlueStore/KStore contract
    difference is *where bytes live*, not what a Transaction means.

    Thread model: the data path is the owning (event-loop) thread plus
    the one background flusher; every entry point that touches the KV
    table, device, allocator, or caches serializes on `_lock` (an RLock
    so transaction compilation may re-enter `read`).
    """

    KIND = "blockstore"

    def __init__(self, db: KeyValueDB | None = None, config=None,
                 block_path: str | None = None):
        super().__init__(db)
        if config is None:
            from ceph_tpu.common.config import Config

            config = Config()
        min_alloc = int(config.get("blockstore_min_alloc_size"))
        self.csum_block = int(config.get("blockstore_csum_block_size"))
        # geometry is pinned at mkfs: a later config change must not skew
        # how an existing store's checksums were laid out
        geom = self.db.get(_BMETA, b"geometry")
        if geom is not None:
            d = Decoder(geom)
            min_alloc, self.csum_block = int(d.u64()), int(d.u64())
        self.alloc = ExtentAllocator(
            min_alloc, capacity=int(config.get("blockstore_block_size"))
        )
        self.comp_mode = config.get("blockstore_compression_mode")
        self.comp_min = int(
            config.get("blockstore_compression_min_blob_size")
        )
        self._compressor = None
        if self.comp_mode != "none":
            from ceph_tpu.common.compressor import factory

            self._compressor = factory(
                config.get("blockstore_compression_algorithm")
            )
        self.deferred_batch_bytes = int(
            config.get("blockstore_deferred_batch_bytes")
        )
        self.deferred_max_age = (
            int(config.get("blockstore_deferred_max_age_ms")) / 1000.0
        )
        self.onode_cache_size = int(
            config.get("blockstore_onode_cache_size")
        )
        self.buffer_cache_bytes = int(
            config.get("blockstore_buffer_cache_bytes")
        )
        if block_path is None:
            block_path = config.get("blockstore_block_path") or None
        if block_path is None and isinstance(
            getattr(self.db, "path", None), str
        ):
            block_path = os.path.join(self.db.path, "block")
        self.device = (
            FileBlockDevice(block_path) if block_path else MemBlockDevice()
        )
        # caches: committed truth only (folded in at commit points)
        self._onode_cache: OrderedDict[bytes, Onode] = OrderedDict()
        self._buffer_cache: OrderedDict[bytes, bytes] = OrderedDict()
        self._buffer_bytes = 0
        # one lock serializes the data path against the flusher thread
        self._lock = threading.RLock()
        self._flusher: threading.Thread | None = None
        self._flusher_stop = threading.Event()
        # set whenever the deferred backlog is empty — an event-driven
        # "WAL drained" signal (tests and external drivers wait on it
        # instead of polling the _DEFER prefix)
        self._deferred_drained = threading.Event()
        self._deferred_drained.set()
        self._closed = False
        # device fault layer: cached 1-in-N rates (config-observed so
        # injectargs flips them live) + the deterministic per-object set;
        # `_inj_read_armed` folds both into the ONE flag the read hot
        # path checks (the _InjectingStream disabled-cost rule)
        self._inj_read_rate = int(config.get("blockstore_inject_read_eio"))
        self._inj_write_rate = int(
            config.get("blockstore_inject_write_eio")
        )
        self._inj_fsync_rate = int(
            config.get("blockstore_inject_fsync_fail")
        )
        self._inject_read_keys: set[bytes] = set()
        self._inj_read_armed = bool(self._inj_read_rate)
        self._inj_rng = random.Random()
        config.observe("blockstore_inject_read_eio", self._on_inj_read)
        config.observe("blockstore_inject_write_eio", self._on_inj_write)
        config.observe("blockstore_inject_fsync_fail", self._on_inj_fsync)
        #: fail-stop state: a write/fsync device error flips this and the
        #: store refuses every further transaction with EROFS
        self._fenced = False
        #: callback(reason) fired exactly once when the store fences —
        #: the owning OSD hooks its fail-stop here; may be invoked from
        #: the flusher thread, so implementations must only schedule
        self.on_fatal = None
        self.perf = self._make_perf()
        # per-transaction compile state
        self._staged: dict[bytes, tuple[Onode, bytes]] = {}
        self._pending_release: list[tuple[int, int]] = []
        self._batch_allocs: list[tuple[int, int]] = []
        self._batch_drops: set[bytes] = set()
        self._batch_deferred_n = 0
        self._batch_big_n = 0
        self._last_deferred_n = 0
        self._last_big_n = 0
        self._mount(geom is None)

    def _make_perf(self) -> PerfCounters:
        perf = PerfCounters("blockstore")
        for key, desc in (
            ("onode_hit", "onode served from the LRU (no KV decode)"),
            ("onode_miss", "onode fetched from KV and decoded"),
            ("buffer_hit", "read served from the buffer cache "
                           "(no device IO, no checksum verify)"),
            ("buffer_miss", "read that went to the WAL row / device"),
            ("buffer_evict_bytes", "bytes LRU-evicted from the buffer "
                                   "cache"),
            ("write_big", "writes that took the COW big-write path"),
            ("write_deferred", "sub-min_alloc writes deferred onto the "
                               "KV WAL"),
            ("deferred_flush", "deferred-backlog flushes"),
            ("deferred_flush_aged", "flushes triggered by max-age, not "
                                    "byte pressure"),
            ("deferred_flush_ops", "payloads moved WAL -> device by "
                                   "flushes"),
            ("deferred_flush_errors", "background flush attempts that "
                                      "raised (retried next tick)"),
            ("dev_read_calls", "device pread calls issued"),
            ("dev_read_segments", "extents those preads covered "
                                  "(segments - calls = coalescing win)"),
            ("dev_write_calls", "device pwrite(v) calls issued"),
            ("dev_write_segments", "extents those pwrites covered"),
            ("inject_read_eio", "reads failed by the fault-injection "
                                "layer (rate or per-object)"),
            ("inject_write_eio", "device writes failed by injection "
                                 "(each one fences the store)"),
            ("inject_fsync_fail", "device fsyncs failed by injection "
                                  "(each one fences the store)"),
        ):
            perf.add_u64_counter(key, desc)
        for key, desc in (
            ("deferred_bytes", "deferred backlog riding the KV WAL"),
            ("deferred_peak_bytes", "high-watermark of the deferred "
                                    "backlog since mount"),
            ("deferred_ops", "deferred payload rows queued"),
            ("deferred_age_ms", "age of the oldest queued deferred "
                                "write at the last queue/flush event"),
            ("buffer_bytes", "bytes held by the buffer cache"),
            ("onode_entries", "onodes held by the LRU"),
            ("fenced", "1 when a write/fsync error has fenced the store "
                       "(read-only fail-stop state)"),
        ):
            perf.add_u64(key, desc)
        perf.add_time_avg(
            "l_flush", "deferred flush wall time (alloc+write+fsync+KV)"
        )
        return perf

    def _mount(self, mkfs: bool) -> None:
        raw = self.db.get(_BMETA, b"size")
        size = Decoder(raw).u64() if raw is not None else 0
        free = {
            int.from_bytes(k[1], "big"): Decoder(v).u64()
            for k, v in self.db.iterate(_FREE)
        }
        self.alloc.init(free, size)
        rows = list(self.db.iterate(_DEFER))
        self._deferred_bytes = sum(len(v) for _k, v in rows)
        self._deferred_ops = len(rows)
        # a backlog inherited across a crash starts its age clock at
        # mount; the flusher itself stays lazy (first write commit) so a
        # store opened only for inspection never mutates itself
        self._deferred_since = time.monotonic() if rows else None
        if rows:
            self._deferred_drained.clear()
        self._sync_gauges()
        if mkfs:
            kv = KVTransaction()
            kv.set(
                _BMETA, b"geometry",
                Encoder().u64(self.alloc.min_alloc_size)
                .u64(self.csum_block).bytes(),
            )
            self.db.submit_transaction(kv)

    # -- device fault layer (injection + fail-stop fencing) --------------------

    def _on_inj_read(self, _n, v) -> None:
        self._inj_read_rate = int(v)
        self._inj_read_armed = bool(
            self._inj_read_rate or self._inject_read_keys
        )

    def _on_inj_write(self, _n, v) -> None:
        self._inj_write_rate = int(v)

    def _on_inj_fsync(self, _n, v) -> None:
        self._inj_fsync_rate = int(v)

    @property
    def fenced(self) -> bool:
        return self._fenced

    def inject_data_error(self, coll: str, name: str) -> None:
        """Arm a deterministic read EIO for one object (the reference's
        `injectdataerr` admin command): every read of it fails until a
        write rewrites the object — which is exactly what a write-back
        repair does, so a healed object reads clean again. Drops the
        object's cached data so the next read really hits the fault."""
        with self._lock:
            key = _okey(coll, name)
            self._inject_read_keys.add(key)
            self._inj_read_armed = True
            self._buffer_drop(key)

    def _maybe_inject_read(self, key: bytes, label: str) -> None:
        """Slow path behind the single `_inj_read_armed` check."""
        if key in self._inject_read_keys:
            self.perf.inc("inject_read_eio")
            raise StoreError(
                "EIO", f"{label}: injected per-object read error"
            )
        rate = self._inj_read_rate
        if rate and self._inj_rng.randrange(rate) == 0:
            self.perf.inc("inject_read_eio")
            raise StoreError(
                "EIO",
                f"{label}: injected device read error (1-in-{rate})",
            )

    def _fatal(self, reason: str) -> None:
        """Fail-stop: fence the store (no further acks can lie about
        durability), fire on_fatal ONCE, and raise the fatal error. The
        callback only schedules (it may run on the flusher thread)."""
        if not self._fenced:
            self._fenced = True
            self.perf.set("fenced", 1)
            cb = self.on_fatal
            if cb is not None:
                try:
                    cb(reason)
                # cephlint: disable=error-taxonomy (fencing must proceed even if a death-callback misbehaves)
                except Exception:  # noqa: BLE001 - fencing must proceed
                    pass
        raise StoreFatalError("EIO", f"store fenced: {reason}")

    def _device_flush(self) -> None:
        """The one fsync site: injection check, then the real flush; a
        failure of either fences the store — an fsync error can never be
        retried-and-forgotten."""
        if (
            self._inj_fsync_rate
            and self._inj_rng.randrange(self._inj_fsync_rate) == 0
        ):
            self.perf.inc("inject_fsync_fail")
            self._fatal("injected fsync failure")
        try:
            self.device.flush()
        except OSError as e:
            self._fatal(f"device fsync failed: {e}")

    # -- caches ---------------------------------------------------------------

    def _sync_gauges(self) -> None:
        self.perf.set("deferred_bytes", self._deferred_bytes)
        self.perf.set_max("deferred_peak_bytes", self._deferred_bytes)
        self.perf.set("deferred_ops", self._deferred_ops)
        self.perf.set(
            "deferred_age_ms", int(self.deferred_age_s() * 1000)
        )
        self.perf.set("buffer_bytes", self._buffer_bytes)
        self.perf.set("onode_entries", len(self._onode_cache))

    def _onode_put(self, key: bytes, on: Onode) -> None:
        if self.onode_cache_size <= 0:
            return
        oc = self._onode_cache
        oc[key] = on
        oc.move_to_end(key)
        while len(oc) > self.onode_cache_size:
            oc.popitem(last=False)

    def _get_onode(self, key: bytes) -> Onode | None:
        """Committed onode for `key`, LRU first. None when absent."""
        on = self._onode_cache.get(key)
        if on is not None:
            self._onode_cache.move_to_end(key)
            self.perf.inc("onode_hit")
            return on
        raw = self.db.get(_ONODE, key)
        if raw is None:
            return None
        self.perf.inc("onode_miss")
        on = Onode.decode(raw)
        self._onode_put(key, on)
        return on

    def _buffer_drop(self, key: bytes) -> None:
        old = self._buffer_cache.pop(key, None)
        if old is not None:
            self._buffer_bytes -= len(old)

    def _buffer_put(self, key: bytes, data: bytes) -> None:
        if self.buffer_cache_bytes <= 0:
            return
        self._buffer_drop(key)
        if len(data) > self.buffer_cache_bytes:
            return
        self._buffer_cache[key] = data
        self._buffer_bytes += len(data)
        while self._buffer_bytes > self.buffer_cache_bytes:
            _k, v = self._buffer_cache.popitem(last=False)
            self._buffer_bytes -= len(v)
            self.perf.inc("buffer_evict_bytes", len(v))

    def drop_caches(self) -> None:
        """Forget every cached onode and data buffer — the cache state an
        OSD restart implies. The next reads hit the KV layer and the
        device, which is what makes injected at-rest bit-rot visible to a
        plain `read` again (deep scrub never needs this: `read_verify`
        bypasses the buffer cache by construction)."""
        with self._lock:
            self._onode_cache.clear()
            self._buffer_cache.clear()
            self._buffer_bytes = 0
            self._sync_gauges()

    # -- transaction compilation ----------------------------------------------

    def queue_transaction(self, txn) -> None:
        if self._fenced:
            # fail-stop contract: a fenced store refuses every write up
            # front — acking from a store that failed a write/fsync
            # would lie about durability
            raise StoreError(
                "EROFS",
                "store is fenced after a device write/fsync error; "
                "refusing writes",
            )
        sp = None if self.tracer is None else self.tracer.child(
            "blockstore_txn", tags={"ops": len(txn.ops)}
        )
        try:
            with self._lock:
                super().queue_transaction(txn)
                if sp is not None:
                    # write-path classification of the batch just
                    # committed (deferred = rode the KV WAL)
                    sp.set_tag("deferred", self._last_deferred_n)
                    sp.set_tag("big", self._last_big_n)
        finally:
            if sp is not None:
                sp.finish()

    def _begin_batch(self) -> None:
        self._staged = {}
        self._pending_release = []
        self._batch_allocs = []
        self._batch_drops = set()
        self._batch_deferred_n = 0
        self._batch_big_n = 0

    def _abort_batch(self) -> None:
        # compile failed before the commit point: hand batch allocations
        # back (their device bytes are garbage in free space — harmless),
        # re-derive the deferred backlog from committed rows, and drop
        # every touched cache entry (committed truth is re-readable)
        self.alloc.release(self._batch_allocs)
        rows = list(self.db.iterate(_DEFER))
        self._deferred_bytes = sum(len(v) for _k, v in rows)
        self._deferred_ops = len(rows)
        if not rows:
            self._deferred_since = None
            self._deferred_drained.set()
        for key in set(self._staged) | self._batch_drops:
            self._onode_cache.pop(key, None)
            self._buffer_drop(key)
        self._sync_gauges()
        self._begin_batch()

    def _commit_batch(self, kv: KVTransaction) -> None:
        # frees quarantined during compile join the allocator only now —
        # nothing between here and the KV submit allocates, so a freed
        # extent can never be rewritten before the free itself commits
        self.alloc.release(self._pending_release)
        self.alloc.flush(kv, _FREE, _BMETA)
        self._device_flush()  # data durable BEFORE metadata references it
        self.db.submit_transaction(kv)
        # the batch is durable: fold its effects into the caches (drops
        # first — a remove-then-write of one key re-stages it)
        for key in self._batch_drops:
            self._onode_cache.pop(key, None)
            self._buffer_drop(key)
        for key, (on, data) in self._staged.items():
            self._onode_put(key, on)
            self._buffer_put(key, data)
        if self._deferred_bytes > 0:
            if self._deferred_since is None:
                self._deferred_since = time.monotonic()
            self._deferred_drained.clear()
            self._maybe_start_flusher()
        else:
            self._deferred_since = None
            self._deferred_drained.set()
        self._sync_gauges()
        self._last_deferred_n = self._batch_deferred_n
        self._last_big_n = self._batch_big_n
        self._begin_batch()
        if self._deferred_bytes > self.deferred_batch_bytes:
            try:
                self.flush_deferred()
            except StoreError as e:
                # the batch ITSELF committed; a full device just leaves
                # the backlog on the (still authoritative) KV WAL until
                # frees open headroom — fatal errors still propagate
                if e.code != "ENOSPC":
                    raise

    def _compile_op(self, kv: KVTransaction, op: tuple) -> None:
        kind = op[0]
        if kind == "touch":
            _, coll, name = op
            key = _okey(coll, name)
            if key not in self._staged and self._get_onode(key) is None:
                on = Onode(csum_block=self.csum_block)
                kv.set(_ONODE, key, on.encode())
                self._staged[key] = (on, b"")
        elif kind == "write":
            _, coll, name, data, attrs = op
            key = _okey(coll, name)
            self._stage_write(kv, key, data)
            if attrs is not None:
                kv.set(_ATTR, key, _encode_attrs(attrs))
        elif kind == "write_at":
            _, coll, name, off, data = op
            key = _okey(coll, name)
            cur = self._compile_read(coll, name, key)
            if len(cur) < off:
                cur = cur + b"\x00" * (off - len(cur))
            self._stage_write(
                kv, key, cur[:off] + data + cur[off + len(data):]
            )
        elif kind == "remove":
            _, coll, name = op
            key = _okey(coll, name)
            self._forget(kv, key)
            self._batch_drops.add(key)
            kv.rm(_ONODE, key)
            kv.rm(_ATTR, key)
            for k, _v in list(self.db.iterate(_OMAP)):
                if k[1].startswith(key):
                    kv.rm(_OMAP, k[1])
        elif kind == "rmcoll":
            prefix = Encoder().string(op[1]).bytes()
            for k, _v in list(self.db.iterate(_ONODE)):
                if k[1].startswith(prefix):
                    self._forget(kv, k[1])
                    self._batch_drops.add(k[1])
            super()._compile_op(kv, op)  # coll row + rows via _rows_of
        else:
            super()._compile_op(kv, op)

    def _forget(self, kv: KVTransaction, key: bytes) -> None:
        """Release whatever payload the current onode (staged by an
        earlier op in this batch, else committed) holds for `key`."""
        staged = self._staged.pop(key, None)
        if staged is not None:
            on = staged[0]
        else:
            on = self._get_onode(key)
            if on is None:
                return
        if on.flags & FLAG_INLINE:
            kv.rm(_DEFER, key)
            self._deferred_bytes -= on.stored_len
            self._deferred_ops -= 1
        else:
            self._pending_release.extend(on.extents)

    def _stage_write(self, kv: KVTransaction, key: bytes,
                     data: bytes) -> None:
        self._forget(kv, key)
        data = bytes(data)
        payload, alg = data, ""
        if self._compressor is not None and len(data) >= self.comp_min:
            compressed, out = self._compressor.maybe_compress(
                data, mode=self.comp_mode
            )
            if compressed and len(out) < len(data):
                payload, alg = out, self._compressor.name
        on = Onode(
            size=len(data),
            flags=FLAG_COMPRESSED if alg else 0,
            comp_alg=alg,
            stored_len=len(payload),
            csum_block=self.csum_block,
        )
        on.csums = [
            ceph_crc32c(_CSUM_SEED, payload[i:i + self.csum_block])
            for i in range(0, len(payload), self.csum_block)
        ]
        if payload and len(payload) < self.alloc.min_alloc_size:
            on.flags |= FLAG_INLINE
            kv.set(_DEFER, key, payload)
            self._deferred_bytes += len(payload)
            self._deferred_ops += 1
            self._batch_deferred_n += 1
            self.perf.inc("write_deferred")
        elif payload:
            on.extents = self.alloc.allocate(len(payload))
            self._batch_allocs.extend(on.extents)
            self._write_extents(on.extents, payload)
            self._batch_big_n += 1
            self.perf.inc("write_big")
        kv.set(_ONODE, key, on.encode())
        self._staged[key] = (on, data)
        if self._inject_read_keys:
            # a rewrite replaces the faulty extents, so the armed
            # per-object error heals with the data (injectdataerr
            # semantics: repair write-backs read clean afterwards)
            self._inject_read_keys.discard(key)
            self._inj_read_armed = bool(
                self._inj_read_rate or self._inject_read_keys
            )

    def _compile_read(self, coll: str, name: str, key: bytes) -> bytes:
        """Object content as visible to the op being compiled: what an
        earlier op in this batch staged, else committed state."""
        staged = self._staged.get(key)
        if staged is not None:
            return staged[1]
        try:
            return self.read(coll, name)
        except StoreError as e:
            if e.code == "ENOENT":
                return b""
            raise

    # -- vectored device IO ----------------------------------------------------

    def _write_extents(self, extents, payload: bytes) -> None:
        self._write_plan(self._extent_chunks(extents, payload))

    @staticmethod
    def _extent_chunks(extents, payload: bytes):
        """[(device offset, chunk)] for a payload across its extents.
        Chunks are zero-padded to the extent length — the extents are
        freshly-allocated COW space, reads stop at stored_len, and full
        min_alloc-granular chunks let device-adjacent extents (even of
        different objects in a deferred batch) coalesce into one
        pwrite."""
        pos = 0
        plan = []
        for off, ln in extents:
            chunk = payload[pos:pos + ln]
            pos += len(chunk)
            if len(chunk) < ln:
                chunk = chunk + b"\x00" * (ln - len(chunk))
            if chunk:
                plan.append((off, chunk))
        return plan

    def _write_plan(self, plan) -> None:
        """Issue [(device offset, bytes)] writes, coalescing runs that
        are adjacent on the device into single vectored pwrites. A device
        write failure — injected or real — fences the store (fail-stop):
        the bytes under an extent the metadata is about to reference can
        no longer be trusted."""
        if not plan:
            return
        if (
            self._inj_write_rate
            and self._inj_rng.randrange(self._inj_write_rate) == 0
        ):
            self.perf.inc("inject_write_eio")
            self._fatal("injected device write error")
        plan = sorted(plan)
        run_off, run = plan[0][0], [plan[0][1]]
        run_end = run_off + len(plan[0][1])
        calls = 0
        try:
            for off, data in plan[1:]:
                if off == run_end:
                    run.append(data)
                else:
                    self.device.pwritev(run_off, run)
                    calls += 1
                    run_off, run = off, [data]
                run_end = off + len(data)
            self.device.pwritev(run_off, run)
        except OSError as e:
            self._fatal(f"device write failed: {e}")
        self.perf.inc("dev_write_calls", calls + 1)
        self.perf.inc("dev_write_segments", len(plan))

    # -- deferred writes -------------------------------------------------------

    def deferred_age_s(self) -> float:
        """Seconds the oldest queued deferred write has been waiting."""
        since = self._deferred_since
        return 0.0 if since is None else time.monotonic() - since

    def wait_deferred_drained(self, timeout: float | None = None) -> bool:
        """Block until the deferred backlog is empty — event-driven: the
        aging flusher, byte pressure, or an explicit flush sets the
        event the moment the last WAL row commits to the device. Returns
        False on timeout."""
        return self._deferred_drained.wait(timeout)

    def tick(self) -> int:
        """Age-based deferred flush: drain the backlog iff its oldest
        entry exceeds blockstore_deferred_max_age_ms. Called by the
        background flusher; also callable from an external driver loop
        (an OSD tick) when the flusher is disabled. Returns payloads
        moved."""
        with self._lock:
            self._sync_gauges()
            if self._closed or self._fenced or self._deferred_bytes <= 0:
                return 0
            if self.deferred_max_age <= 0:
                return 0
            if self.deferred_age_s() < self.deferred_max_age:
                return 0
            self.perf.inc("deferred_flush_aged")
            return self.flush_deferred()

    def _maybe_start_flusher(self) -> None:
        """Lazily spawn the aging flusher — only ever from a write
        commit, so read-only opens (fsck, objectstore_tool) never start
        one and never mutate the store under examination."""
        if (
            self._flusher is None
            and not self._closed
            and self.deferred_max_age > 0
        ):
            self._flusher = threading.Thread(
                target=self._flusher_main,
                name="blockstore-flusher",
                daemon=True,
            )
            self._flusher.start()

    def _flusher_main(self) -> None:
        interval = max(0.01, self.deferred_max_age / 4)
        while not self._flusher_stop.wait(interval):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - keep aging; retry next tick
                self.perf.inc("deferred_flush_errors")

    def _stop_flusher(self) -> None:
        """Join the flusher (outside the lock — it may hold it mid-flush)
        so no thread can touch the device after close."""
        t = self._flusher
        if t is None:
            return
        self._flusher_stop.set()
        t.join()
        self._flusher = None

    def flush_deferred(self) -> int:
        """Move every deferred payload onto the device (BlueStore's
        deferred_try_submit / _deferred_replay): ONE allocator pass for
        the whole backlog, one coalesced vectored write plan, one fsync,
        then ONE KV batch repoints the onodes and drops the WAL rows.
        Crash-safe at any point — until that batch commits, the _DEFER
        rows remain authoritative. Returns the number of payloads moved."""
        # flushes are their own (root) traces: they run from the aging
        # thread or byte pressure, not inside any one client op
        sp = None if self.tracer is None else self.tracer.start(
            "blockstore_flush", tags={"deferred": True}
        )
        try:
            return self._flush_deferred_inner(sp)
        finally:
            if sp is not None:
                sp.finish()

    def _flush_deferred_inner(self, sp) -> int:
        with self._lock:
            if self._fenced:
                return 0  # rows stay authoritative on the WAL; no acks lie
            t0 = time.perf_counter()
            rows = [(k[1], v) for k, v in self.db.iterate(_DEFER)]
            if not rows:
                self._deferred_bytes = 0
                self._deferred_ops = 0
                self._deferred_since = None
                self._deferred_drained.set()
                self._sync_gauges()
                return 0
            kv = KVTransaction()
            moved: list[tuple[bytes, Onode, bytes]] = []
            for key, payload in rows:
                raw = self.db.get(_ONODE, key)
                on = Onode.decode(raw) if raw is not None else None
                if on is None or not on.flags & FLAG_INLINE:
                    kv.rm(_DEFER, key)  # orphan WAL row: drop
                    continue
                moved.append((key, on, payload))
            if moved:
                extent_lists = self.alloc.allocate_many(
                    [len(p) for _k, _on, p in moved]
                )
                plan = []
                for (key, on, payload), extents in zip(
                    moved, extent_lists
                ):
                    on.extents = extents
                    on.flags &= ~FLAG_INLINE
                    kv.set(_ONODE, key, on.encode())
                    kv.rm(_DEFER, key)
                    plan.extend(self._extent_chunks(extents, payload))
                self._write_plan(plan)
            self.alloc.flush(kv, _FREE, _BMETA)
            self._device_flush()
            self.db.submit_transaction(kv)
            for key, on, _payload in moved:
                if key in self._onode_cache:
                    self._onode_cache[key] = on
            self._deferred_bytes = 0
            self._deferred_ops = 0
            self._deferred_since = None
            self._deferred_drained.set()
            self.perf.inc("deferred_flush")
            self.perf.inc("deferred_flush_ops", len(moved))
            self.perf.tinc("l_flush", time.perf_counter() - t0)
            if sp is not None:
                sp.set_tag("ops", len(moved))
            self._sync_gauges()
            return len(moved)

    def compact(self) -> None:
        """Flush the deferred backlog, then fold the KV WAL."""
        with self._lock:
            self.flush_deferred()
            if hasattr(self.db, "compact"):
                self.db.compact()

    def umount(self) -> None:
        """Clean shutdown: join the flusher BEFORE the device closes,
        drain deferred writes, close device + DB."""
        self._stop_flusher()
        with self._lock:
            if not self._closed:
                try:
                    self.flush_deferred()
                except StoreError:
                    # full (ENOSPC) or failing device at shutdown: the
                    # WAL rows stay authoritative and replay on the next
                    # mount — close must still proceed
                    pass
            self._closed = True
            self.device.close()
            if hasattr(self.db, "close"):
                self.db.close()

    def close(self) -> None:
        """Read-only close (fsck/tool path): no deferred flush, so an
        inspection never mutates the store under examination. A flusher
        is never *started* by this path (it spawns only from write
        commits), but one left over from earlier writes is still joined
        before the device goes away."""
        self._stop_flusher()
        with self._lock:
            self._closed = True
            self.device.close()
            if hasattr(self.db, "close"):
                self.db.close()

    # -- reads ----------------------------------------------------------------

    def exists(self, coll: str, name: str) -> bool:
        with self._lock:
            key = _okey(coll, name)
            if key in self._onode_cache:
                return True
            return self.db.get(_ONODE, key) is not None

    def read(self, coll: str, name: str) -> bytes:
        sp = None if self.tracer is None else self.tracer.child(
            "blockstore_read"
        )
        try:
            with self._lock:
                key = _okey(coll, name)
                data = self._buffer_cache.get(key)
                if data is not None:
                    self._buffer_cache.move_to_end(key)
                    self.perf.inc("buffer_hit")
                    if sp is not None:
                        sp.set_tag("cache", "hit")
                    return data
                self.perf.inc("buffer_miss")
                if sp is not None:
                    sp.set_tag("cache", "miss")
                return self._read_cold(coll, name, key)
        finally:
            if sp is not None:
                sp.finish()

    def read_verify(self, coll: str, name: str) -> bytes:
        """Read device truth: bypass the buffer cache, re-run the stored
        checksum verification, and refresh the cache with the verified
        bytes. Deep scrub reads through this so cached data can never
        mask at-rest corruption."""
        sp = None if self.tracer is None else self.tracer.child(
            "blockstore_read", tags={"verify": True, "cache": "bypass"}
        )
        try:
            with self._lock:
                return self._read_cold(coll, name, _okey(coll, name))
        finally:
            if sp is not None:
                sp.finish()

    def _read_cold(self, coll: str, name: str, key: bytes) -> bytes:
        on = self._get_onode(key)
        if on is None:
            raise StoreError("ENOENT", f"{coll}/{name} does not exist")
        payload = self._read_payload(key, on, f"{coll}/{name}")
        if on.flags & FLAG_COMPRESSED:
            from ceph_tpu.common.compressor import factory

            try:
                data = factory(on.comp_alg).decompress(payload)
            except Exception as e:  # noqa: BLE001 - surfaced as EIO
                raise StoreError(
                    "EIO", f"{coll}/{name}: decompression failed: {e}"
                ) from e
            if len(data) != on.size:
                raise StoreError(
                    "EIO",
                    f"{coll}/{name}: decompressed to {len(data)} bytes, "
                    f"onode says {on.size}",
                )
        else:
            data = payload
        self._buffer_put(key, data)
        return data

    def _read_payload(self, key: bytes, on: Onode, label: str) -> bytes:
        if self._inj_read_armed:
            self._maybe_inject_read(key, label)
        if on.flags & FLAG_INLINE:
            payload = self.db.get(_DEFER, key)
            if payload is None:
                raise StoreError(
                    "EIO", f"{label}: deferred payload row missing"
                )
        else:
            takes = []
            remaining = on.stored_len
            for off, ln in on.extents:
                take = min(ln, remaining)
                if take:
                    takes.append((off, take))
                remaining -= take
            runs = _coalesce(takes)
            try:
                parts = [self.device.pread(off, ln) for off, ln in runs]
            except OSError as e:
                # a read error is recoverable ABOVE the store (the OSD
                # heals from replicas/EC survivors): surface, don't fence
                raise StoreError(
                    "EIO", f"{label}: device read failed: {e}"
                ) from e
            self.perf.inc("dev_read_calls", len(runs))
            self.perf.inc("dev_read_segments", len(takes))
            payload = b"".join(parts)
            if len(payload) != on.stored_len:
                raise StoreError(
                    "EIO",
                    f"{label}: extent map covers {len(payload)} of "
                    f"{on.stored_len} stored bytes",
                )
        bs = on.csum_block or self.csum_block
        want = (len(payload) + bs - 1) // bs
        if len(on.csums) != want:
            raise StoreError(
                "EIO",
                f"{label}: {len(on.csums)} checksums for {want} blocks",
            )
        for i, c in enumerate(on.csums):
            if ceph_crc32c(_CSUM_SEED, payload[i * bs:(i + 1) * bs]) != c:
                raise StoreError(
                    "EIO",
                    f"{label}: checksum mismatch in block {i} "
                    f"(at-rest corruption)",
                )
        return payload

    def list_objects(self, coll: str) -> list[str]:
        with self._lock:
            prefix = Encoder().string(coll).bytes()
            return [
                _okey_decode(k[1])[1]
                for k, _v in self.db.iterate(_ONODE)
                if k[1].startswith(prefix)
            ]

    def _rows_of(self, coll: str):
        prefix = Encoder().string(coll).bytes()
        for table in (_ONODE, _DEFER, _ATTR, _OMAP):
            for k, _v in list(self.db.iterate(table)):
                if k[1].startswith(prefix):
                    yield table, k[1]

    # the flusher thread mutates the (single) KV table dict mid-batch;
    # every reader that iterates it must hold the lock too
    def getattrs(self, coll: str, name: str) -> dict:
        with self._lock:
            return super().getattrs(coll, name)

    def omap_get(self, coll: str, name: str) -> dict[bytes, bytes]:
        with self._lock:
            return super().omap_get(coll, name)

    def collection_exists(self, coll: str) -> bool:
        with self._lock:
            return super().collection_exists(coll)

    def list_collections(self) -> list[str]:
        with self._lock:
            return super().list_collections()

    def used_bytes(self) -> int:
        """KV footprint (metadata + deferred WAL rows) plus the bytes the
        allocator has handed to live blobs."""
        with self._lock:
            return super().used_bytes() + self.alloc.allocated_bytes()

    def compression_stats(self) -> dict:
        """Per-blob compressed-length bookkeeping rolled up for `ceph
        df` (the bluestore_compressed/_original stat pair): logical vs
        stored bytes of every compressed onode. Scans the onode rows —
        the statfs caller caches, so the scan is off the hot path."""
        original = stored = blobs = 0
        with self._lock:
            for _k, raw in list(self.db.iterate(_ONODE)):
                try:
                    on = Onode.decode(raw)
                # cephlint: disable=error-taxonomy (undecodable onode is fsck's department, not stats')
                except Exception:  # fsck's department, not stats'
                    continue
                if on.flags & FLAG_COMPRESSED:
                    blobs += 1
                    original += on.size
                    stored += on.stored_len
        return {
            "compressed_blobs": blobs,
            "data_compressed_original": original,
            "data_compressed": stored,
        }

    # -- fsck -----------------------------------------------------------------

    def fsck(self, deep: bool = False) -> list[dict]:
        """Cross-check the whole store; returns one dict per error.

        Shallow: every onode decodes; inline onodes have their WAL row and
        no extents; no orphan WAL rows; onode extents vs the free list
        tile [0, device size) exactly (no overlap, no leak). Deep: also
        re-read every blob and verify its stored checksums (and that
        compressed blobs still decompress to the logical size). Reads go
        straight to the KV rows and the device — never the caches."""
        with self._lock:
            return self._fsck_locked(deep)

    def _fsck_locked(self, deep: bool) -> list[dict]:
        errors: list[dict] = []
        onodes: list[tuple[str, str, bytes, Onode]] = []
        allocated: list[tuple[int, int]] = []
        for k, raw in list(self.db.iterate(_ONODE)):
            key = k[1]
            try:
                coll, name = _okey_decode(key)
                on = Onode.decode(raw)
            except Exception as e:  # noqa: BLE001 - each row reported
                errors.append(
                    {"key": key.hex(), "error": f"undecodable onode: {e}"}
                )
                continue
            onodes.append((coll, name, key, on))
            allocated.extend(on.extents)
            if on.flags & FLAG_INLINE:
                if on.extents:
                    errors.append({
                        "object": f"{coll}/{name}",
                        "error": "inline onode with extents",
                    })
                if self.db.get(_DEFER, key) is None:
                    errors.append({
                        "object": f"{coll}/{name}",
                        "error": "deferred payload row missing",
                    })
        inline_keys = {
            key for _c, _n, key, on in onodes if on.flags & FLAG_INLINE
        }
        for k, _v in list(self.db.iterate(_DEFER)):
            if k[1] not in inline_keys:
                errors.append({
                    "key": k[1].hex(),
                    "error": "orphan deferred row (no inline onode)",
                })
        for msg in self.alloc.check(allocated):
            errors.append({"error": msg})
        if deep:
            for coll, name, key, on in onodes:
                try:
                    payload = self._read_payload(key, on, f"{coll}/{name}")
                    if on.flags & FLAG_COMPRESSED:
                        from ceph_tpu.common.compressor import factory

                        out = factory(on.comp_alg).decompress(payload)
                        if len(out) != on.size:
                            raise StoreError(
                                "EIO", "decompressed size mismatch"
                            )
                except Exception as e:  # noqa: BLE001
                    errors.append(
                        {"object": f"{coll}/{name}", "error": str(e)}
                    )
        return errors
