"""BlockStore: the BlueStore-analogue local object store.

Re-expresses the reference's src/os/bluestore design at our scale: object
**data** lives as allocator-managed extents in one raw block file; all
**metadata** — onodes (extent map + per-block checksums), xattrs, omap,
collections, and the allocator free list — lives in the `KeyValueDB`
(RocksDB's role). A `Transaction` still commits as exactly one KV batch,
and the ordering discipline is BlueStore's:

  * **big writes** (>= min_alloc_size) go to freshly-allocated extents —
    never to space a live onode references — and the device is fsynced
    *before* the KV batch commits, so a crash at any point leaves the old
    onode pointing at intact old bytes (copy-on-write, no torn data);
  * **small writes** (< min_alloc_size) are *deferred*: the payload rides
    the KV WAL batch itself (the commit point) and `flush_deferred` later
    moves it onto the device, repointing the onode in a second batch —
    BlueStore's deferred-write path, crash-safe because the WAL row stays
    authoritative until that second batch commits;
  * **frees** are quarantined until the batch that drops them commits —
    reusing a freed extent earlier could clobber bytes the previous onode
    still references across a crash.

Every checksum block (bluestore_csum_block_size) of the stored payload is
crc32c-summed on write and verified on every read; a mismatch raises
`StoreError("EIO", ...)`, which the OSD's deep scrub surfaces as a
`read_error` inconsistency and repairs from healthy peers. Optional
compression-on-write runs the payload through the compressor registry
(BlueStore's compression_mode/required_ratio policy) with the compressed
length tracked per blob. `fsck(deep=...)` cross-checks onode extents vs
the free list (allocated ∪ free must tile the device exactly) and — deep —
re-reads every blob against its stored checksums.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from ceph_tpu.common.crc import ceph_crc32c
from ceph_tpu.common.encoding import Decoder, Encoder
from ceph_tpu.common.kv import KeyValueDB, KVTransaction
from ceph_tpu.osd.allocator import ExtentAllocator
from ceph_tpu.osd.objectstore import (
    _ATTR,
    _OMAP,
    KStore,
    StoreError,
    _encode_attrs,
    _okey,
    _okey_decode,
)

_ONODE = b"ond"  # onode rows: size, flags, extent map, csums (O prefix)
_DEFER = b"dfw"  # deferred sub-min_alloc payloads riding the KV WAL
_FREE = b"fre"   # allocator free-list rows (FreelistManager's B prefix)
_BMETA = b"bmt"  # store meta: device size + pinned geometry

_CSUM_SEED = 0xFFFFFFFF

FLAG_INLINE = 1      # payload lives in the _DEFER row, not on the device
FLAG_COMPRESSED = 2  # stored payload is comp_alg-compressed


@dataclass
class Onode:
    """Per-object metadata row (bluestore_onode_t + its blob/extent maps,
    flattened: one blob per object at our scale)."""

    size: int = 0         # logical object size
    flags: int = 0
    comp_alg: str = ""    # compressor name when FLAG_COMPRESSED
    stored_len: int = 0   # physical payload length (== size when raw)
    csum_block: int = 4096
    extents: list = field(default_factory=list)  # [(offset, length)]
    csums: list = field(default_factory=list)    # u32 per csum block

    def encode(self) -> bytes:
        def body(b):
            b.u8(self.flags).u64(self.size).string(self.comp_alg)
            b.u64(self.stored_len).u32(self.csum_block)
            b.list(self.extents, lambda e, x: e.u64(x[0]).u64(x[1]))
            b.list(self.csums, lambda e, c: e.u32(c))

        return Encoder().struct(1, 1, body).bytes()

    @staticmethod
    def decode(raw: bytes) -> "Onode":
        def body(b, _version):
            on = Onode(flags=b.u8(), size=b.u64(), comp_alg=b.string())
            on.stored_len = b.u64()
            on.csum_block = b.u32()
            on.extents = b.list(lambda d: (d.u64(), d.u64()))
            on.csums = b.list(lambda d: d.u32())
            return on

        on = Decoder(raw).struct(1, body)
        on.size, on.stored_len = int(on.size), int(on.stored_len)
        return on


# ---------------------------------------------------------------------------
# Block devices (KernelDevice's role, reduced to pread/pwrite/flush)


class MemBlockDevice:
    """bytearray-backed device — the MemStore-tier BlockStore for tests
    (and the bit-rot injection surface: flip bytes in `buf`)."""

    path = None

    def __init__(self) -> None:
        self.buf = bytearray()

    def pwrite(self, off: int, data: bytes) -> None:
        end = off + len(data)
        if len(self.buf) < end:
            self.buf.extend(b"\x00" * (end - len(self.buf)))
        self.buf[off:end] = data

    def pread(self, off: int, length: int) -> bytes:
        out = bytes(self.buf[off:off + length])
        return out + b"\x00" * (length - len(out))  # sparse tail is zeros

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class FileBlockDevice:
    """One raw block file, grow-on-demand; flush() is a real fsync — the
    write-before-commit ordering the crash story depends on."""

    def __init__(self, path: str):
        self.path = path
        if not os.path.exists(path):
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "wb"):
                pass
        self._f = open(path, "r+b")

    def pwrite(self, off: int, data: bytes) -> None:
        self._f.seek(off)
        self._f.write(data)

    def pread(self, off: int, length: int) -> bytes:
        self._f.seek(off)
        out = self._f.read(length)
        return out + b"\x00" * (length - len(out))  # sparse tail is zeros

    def flush(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


# ---------------------------------------------------------------------------


class BlockStore(KStore):
    """ObjectStore with data on a block device; see module docstring.

    Inherits the collection/attr/omap row handling from KStore and
    overrides only the data-bearing ops — the BlueStore/KStore contract
    difference is *where bytes live*, not what a Transaction means.
    """

    def __init__(self, db: KeyValueDB | None = None, config=None,
                 block_path: str | None = None):
        super().__init__(db)
        if config is None:
            from ceph_tpu.common.config import Config

            config = Config()
        min_alloc = int(config.get("blockstore_min_alloc_size"))
        self.csum_block = int(config.get("blockstore_csum_block_size"))
        # geometry is pinned at mkfs: a later config change must not skew
        # how an existing store's checksums were laid out
        geom = self.db.get(_BMETA, b"geometry")
        if geom is not None:
            d = Decoder(geom)
            min_alloc, self.csum_block = int(d.u64()), int(d.u64())
        self.alloc = ExtentAllocator(min_alloc)
        self.comp_mode = config.get("blockstore_compression_mode")
        self.comp_min = int(
            config.get("blockstore_compression_min_blob_size")
        )
        self._compressor = None
        if self.comp_mode != "none":
            from ceph_tpu.common.compressor import factory

            self._compressor = factory(
                config.get("blockstore_compression_algorithm")
            )
        self.deferred_batch_bytes = int(
            config.get("blockstore_deferred_batch_bytes")
        )
        if block_path is None:
            block_path = config.get("blockstore_block_path") or None
        if block_path is None and isinstance(
            getattr(self.db, "path", None), str
        ):
            block_path = os.path.join(self.db.path, "block")
        self.device = (
            FileBlockDevice(block_path) if block_path else MemBlockDevice()
        )
        # per-transaction compile state
        self._staged: dict[bytes, tuple[Onode, bytes]] = {}
        self._pending_release: list[tuple[int, int]] = []
        self._batch_allocs: list[tuple[int, int]] = []
        self._mount(geom is None)

    def _mount(self, mkfs: bool) -> None:
        raw = self.db.get(_BMETA, b"size")
        size = Decoder(raw).u64() if raw is not None else 0
        free = {
            int.from_bytes(k[1], "big"): Decoder(v).u64()
            for k, v in self.db.iterate(_FREE)
        }
        self.alloc.init(free, size)
        self._deferred_bytes = sum(
            len(v) for _k, v in self.db.iterate(_DEFER)
        )
        if mkfs:
            kv = KVTransaction()
            kv.set(
                _BMETA, b"geometry",
                Encoder().u64(self.alloc.min_alloc_size)
                .u64(self.csum_block).bytes(),
            )
            self.db.submit_transaction(kv)

    # -- transaction compilation ----------------------------------------------

    def _begin_batch(self) -> None:
        self._staged = {}
        self._pending_release = []
        self._batch_allocs = []

    def _abort_batch(self) -> None:
        # compile failed before the commit point: hand batch allocations
        # back (their device bytes are garbage in free space — harmless)
        # and re-derive the deferred backlog from committed rows
        self.alloc.release(self._batch_allocs)
        self._deferred_bytes = sum(
            len(v) for _k, v in self.db.iterate(_DEFER)
        )
        self._begin_batch()

    def _commit_batch(self, kv: KVTransaction) -> None:
        # frees quarantined during compile join the allocator only now —
        # nothing between here and the KV submit allocates, so a freed
        # extent can never be rewritten before the free itself commits
        self.alloc.release(self._pending_release)
        self.alloc.flush(kv, _FREE, _BMETA)
        self.device.flush()  # data durable BEFORE metadata references it
        self.db.submit_transaction(kv)
        self._begin_batch()
        if self._deferred_bytes > self.deferred_batch_bytes:
            self.flush_deferred()

    def _compile_op(self, kv: KVTransaction, op: tuple) -> None:
        kind = op[0]
        if kind == "touch":
            _, coll, name = op
            key = _okey(coll, name)
            if key not in self._staged and self.db.get(_ONODE, key) is None:
                on = Onode(csum_block=self.csum_block)
                kv.set(_ONODE, key, on.encode())
                self._staged[key] = (on, b"")
        elif kind == "write":
            _, coll, name, data, attrs = op
            key = _okey(coll, name)
            self._stage_write(kv, key, data)
            if attrs is not None:
                kv.set(_ATTR, key, _encode_attrs(attrs))
        elif kind == "write_at":
            _, coll, name, off, data = op
            key = _okey(coll, name)
            cur = self._compile_read(coll, name, key)
            if len(cur) < off:
                cur = cur + b"\x00" * (off - len(cur))
            self._stage_write(
                kv, key, cur[:off] + data + cur[off + len(data):]
            )
        elif kind == "remove":
            _, coll, name = op
            key = _okey(coll, name)
            self._forget(kv, key)
            kv.rm(_ONODE, key)
            kv.rm(_ATTR, key)
            for k, _v in list(self.db.iterate(_OMAP)):
                if k[1].startswith(key):
                    kv.rm(_OMAP, k[1])
        elif kind == "rmcoll":
            prefix = Encoder().string(op[1]).bytes()
            for k, _v in list(self.db.iterate(_ONODE)):
                if k[1].startswith(prefix):
                    self._forget(kv, k[1])
            super()._compile_op(kv, op)  # coll row + rows via _rows_of
        else:
            super()._compile_op(kv, op)

    def _forget(self, kv: KVTransaction, key: bytes) -> None:
        """Release whatever payload the current onode (staged by an
        earlier op in this batch, else committed) holds for `key`."""
        staged = self._staged.pop(key, None)
        if staged is not None:
            on = staged[0]
        else:
            raw = self.db.get(_ONODE, key)
            if raw is None:
                return
            on = Onode.decode(raw)
        if on.flags & FLAG_INLINE:
            kv.rm(_DEFER, key)
            self._deferred_bytes -= on.stored_len
        else:
            self._pending_release.extend(on.extents)

    def _stage_write(self, kv: KVTransaction, key: bytes,
                     data: bytes) -> None:
        self._forget(kv, key)
        data = bytes(data)
        payload, alg = data, ""
        if self._compressor is not None and len(data) >= self.comp_min:
            compressed, out = self._compressor.maybe_compress(
                data, mode=self.comp_mode
            )
            if compressed and len(out) < len(data):
                payload, alg = out, self._compressor.name
        on = Onode(
            size=len(data),
            flags=FLAG_COMPRESSED if alg else 0,
            comp_alg=alg,
            stored_len=len(payload),
            csum_block=self.csum_block,
        )
        on.csums = [
            ceph_crc32c(_CSUM_SEED, payload[i:i + self.csum_block])
            for i in range(0, len(payload), self.csum_block)
        ]
        if payload and len(payload) < self.alloc.min_alloc_size:
            on.flags |= FLAG_INLINE
            kv.set(_DEFER, key, payload)
            self._deferred_bytes += len(payload)
        elif payload:
            on.extents = self.alloc.allocate(len(payload))
            self._batch_allocs.extend(on.extents)
            self._write_extents(on.extents, payload)
        kv.set(_ONODE, key, on.encode())
        self._staged[key] = (on, data)

    def _compile_read(self, coll: str, name: str, key: bytes) -> bytes:
        """Object content as visible to the op being compiled: what an
        earlier op in this batch staged, else committed state."""
        staged = self._staged.get(key)
        if staged is not None:
            return staged[1]
        try:
            return self.read(coll, name)
        except StoreError as e:
            if e.code == "ENOENT":
                return b""
            raise

    def _write_extents(self, extents, payload: bytes) -> None:
        pos = 0
        for off, ln in extents:
            chunk = payload[pos:pos + ln]
            self.device.pwrite(off, chunk)
            pos += len(chunk)

    # -- deferred writes -------------------------------------------------------

    def flush_deferred(self) -> int:
        """Move every deferred payload onto the device (BlueStore's
        deferred_try_submit / _deferred_replay): allocate, write, fsync,
        then ONE KV batch repoints the onodes and drops the WAL rows.
        Crash-safe at any point — until that batch commits, the _DEFER
        rows remain authoritative. Returns the number of payloads moved."""
        rows = [(k[1], v) for k, v in self.db.iterate(_DEFER)]
        if not rows:
            self._deferred_bytes = 0
            return 0
        kv = KVTransaction()
        moved = 0
        for key, payload in rows:
            raw = self.db.get(_ONODE, key)
            on = Onode.decode(raw) if raw is not None else None
            if on is None or not on.flags & FLAG_INLINE:
                kv.rm(_DEFER, key)  # orphan WAL row: drop
                continue
            on.extents = self.alloc.allocate(len(payload))
            self._write_extents(on.extents, payload)
            on.flags &= ~FLAG_INLINE
            kv.set(_ONODE, key, on.encode())
            kv.rm(_DEFER, key)
            moved += 1
        self.alloc.flush(kv, _FREE, _BMETA)
        self.device.flush()
        self.db.submit_transaction(kv)
        self._deferred_bytes = 0
        return moved

    def compact(self) -> None:
        """Flush the deferred backlog, then fold the KV WAL."""
        self.flush_deferred()
        if hasattr(self.db, "compact"):
            self.db.compact()

    def umount(self) -> None:
        """Clean shutdown: drain deferred writes, close device + DB."""
        self.flush_deferred()
        self.device.close()
        if hasattr(self.db, "close"):
            self.db.close()

    def close(self) -> None:
        """Read-only close (fsck/tool path): no deferred flush, so an
        inspection never mutates the store under examination."""
        self.device.close()
        if hasattr(self.db, "close"):
            self.db.close()

    # -- reads ----------------------------------------------------------------

    def exists(self, coll: str, name: str) -> bool:
        return self.db.get(_ONODE, _okey(coll, name)) is not None

    def read(self, coll: str, name: str) -> bytes:
        key = _okey(coll, name)
        raw = self.db.get(_ONODE, key)
        if raw is None:
            raise StoreError("ENOENT", f"{coll}/{name} does not exist")
        on = Onode.decode(raw)
        payload = self._read_payload(key, on, f"{coll}/{name}")
        if on.flags & FLAG_COMPRESSED:
            from ceph_tpu.common.compressor import factory

            try:
                data = factory(on.comp_alg).decompress(payload)
            except Exception as e:  # noqa: BLE001 - surfaced as EIO
                raise StoreError(
                    "EIO", f"{coll}/{name}: decompression failed: {e}"
                ) from e
            if len(data) != on.size:
                raise StoreError(
                    "EIO",
                    f"{coll}/{name}: decompressed to {len(data)} bytes, "
                    f"onode says {on.size}",
                )
            return data
        return payload

    def _read_payload(self, key: bytes, on: Onode, label: str) -> bytes:
        if on.flags & FLAG_INLINE:
            payload = self.db.get(_DEFER, key)
            if payload is None:
                raise StoreError(
                    "EIO", f"{label}: deferred payload row missing"
                )
        else:
            parts = []
            remaining = on.stored_len
            for off, ln in on.extents:
                take = min(ln, remaining)
                parts.append(self.device.pread(off, take))
                remaining -= take
            payload = b"".join(parts)
            if len(payload) != on.stored_len:
                raise StoreError(
                    "EIO",
                    f"{label}: extent map covers {len(payload)} of "
                    f"{on.stored_len} stored bytes",
                )
        bs = on.csum_block or self.csum_block
        want = (len(payload) + bs - 1) // bs
        if len(on.csums) != want:
            raise StoreError(
                "EIO",
                f"{label}: {len(on.csums)} checksums for {want} blocks",
            )
        for i, c in enumerate(on.csums):
            if ceph_crc32c(_CSUM_SEED, payload[i * bs:(i + 1) * bs]) != c:
                raise StoreError(
                    "EIO",
                    f"{label}: checksum mismatch in block {i} "
                    f"(at-rest corruption)",
                )
        return payload

    def list_objects(self, coll: str) -> list[str]:
        prefix = Encoder().string(coll).bytes()
        return [
            _okey_decode(k[1])[1]
            for k, _v in self.db.iterate(_ONODE)
            if k[1].startswith(prefix)
        ]

    def _rows_of(self, coll: str):
        prefix = Encoder().string(coll).bytes()
        for table in (_ONODE, _DEFER, _ATTR, _OMAP):
            for k, _v in list(self.db.iterate(table)):
                if k[1].startswith(prefix):
                    yield table, k[1]

    def used_bytes(self) -> int:
        """KV footprint (metadata + deferred WAL rows) plus the bytes the
        allocator has handed to live blobs."""
        return super().used_bytes() + self.alloc.allocated_bytes()

    # -- fsck -----------------------------------------------------------------

    def fsck(self, deep: bool = False) -> list[dict]:
        """Cross-check the whole store; returns one dict per error.

        Shallow: every onode decodes; inline onodes have their WAL row and
        no extents; no orphan WAL rows; onode extents vs the free list
        tile [0, device size) exactly (no overlap, no leak). Deep: also
        re-read every blob and verify its stored checksums (and that
        compressed blobs still decompress to the logical size)."""
        errors: list[dict] = []
        onodes: list[tuple[str, str, bytes, Onode]] = []
        allocated: list[tuple[int, int]] = []
        for k, raw in list(self.db.iterate(_ONODE)):
            key = k[1]
            try:
                coll, name = _okey_decode(key)
                on = Onode.decode(raw)
            except Exception as e:  # noqa: BLE001 - each row reported
                errors.append(
                    {"key": key.hex(), "error": f"undecodable onode: {e}"}
                )
                continue
            onodes.append((coll, name, key, on))
            allocated.extend(on.extents)
            if on.flags & FLAG_INLINE:
                if on.extents:
                    errors.append({
                        "object": f"{coll}/{name}",
                        "error": "inline onode with extents",
                    })
                if self.db.get(_DEFER, key) is None:
                    errors.append({
                        "object": f"{coll}/{name}",
                        "error": "deferred payload row missing",
                    })
        inline_keys = {
            key for _c, _n, key, on in onodes if on.flags & FLAG_INLINE
        }
        for k, _v in list(self.db.iterate(_DEFER)):
            if k[1] not in inline_keys:
                errors.append({
                    "key": k[1].hex(),
                    "error": "orphan deferred row (no inline onode)",
                })
        for msg in self.alloc.check(allocated):
            errors.append({"error": msg})
        if deep:
            for coll, name, key, on in onodes:
                try:
                    payload = self._read_payload(key, on, f"{coll}/{name}")
                    if on.flags & FLAG_COMPRESSED:
                        from ceph_tpu.common.compressor import factory

                        out = factory(on.comp_alg).decompress(payload)
                        if len(out) != on.size:
                            raise StoreError(
                                "EIO", "decompressed size mismatch"
                            )
                except Exception as e:  # noqa: BLE001
                    errors.append(
                        {"object": f"{coll}/{name}", "error": str(e)}
                    )
        return errors
