"""ECUtil analogues: per-shard cumulative hash metadata (HashInfo).

Re-expresses /root/reference/src/osd/ECUtil.h:101-160: every EC object
carries, as an attribute on each shard, the cumulative crc32c (seed -1) of
every shard's bytes plus the common chunk size — written at encode time and
verified by deep scrub (ECBackend::be_deep_scrub, ECBackend.cc:2461-2540).
Append-only updates extend the hashes exactly as HashInfo::append does.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ceph_tpu.common.crc import ceph_crc32c

SEED = 0xFFFFFFFF  # bufferhash(-1)


@dataclass
class HashInfo:
    total_chunk_size: int = 0
    cumulative_shard_hashes: list[int] = field(default_factory=list)

    @classmethod
    def from_shards(cls, shards: dict[int, bytes], n_chunks: int) -> "HashInfo":
        """Fresh metadata for a full write of all n_chunks shards."""
        hi = cls(0, [SEED] * n_chunks)
        size = len(next(iter(shards.values()))) if shards else 0
        hi.append({i: shards[i] for i in sorted(shards)}, size)
        return hi

    def append(self, to_append: dict[int, bytes], chunk_len: int) -> None:
        """Extend every shard's cumulative hash (ECUtil.cc HashInfo::append:
        all shards must grow by the same chunk_len)."""
        for shard, data in to_append.items():
            assert len(data) == chunk_len, "shards must append equally"
            self.cumulative_shard_hashes[shard] = ceph_crc32c(
                self.cumulative_shard_hashes[shard], data
            )
        self.total_chunk_size += chunk_len

    def get_chunk_hash(self, shard: int) -> int:
        return self.cumulative_shard_hashes[shard]
