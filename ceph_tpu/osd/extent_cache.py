"""Sub-stripe EC overwrite algebra + in-flight extent coordination.

The reference's EC overwrite pipeline (ECBackend::start_rmw,
src/osd/ECBackend.cc:1830; ECTransaction::generate_transactions,
src/osd/ECTransaction.cc:101) reads only the stripes a partial write
touches, re-encodes those, and ships per-shard sub-extents; overlapping
in-flight writes coordinate through an ExtentCache
(src/osd/ExtentCache.h:1) so pipelined RMWs see each other's pending
bytes instead of stale store state.

The TPU-native layout makes the same plan simpler. An EC object here is
a single (k, chunk_size) stripe whose parity is a per-byte-column
GF(2^8) matmul (ceph_tpu.ec.rs.ErasureCodeRs: every technique reduces
to `gen @ data` applied column-wise), so byte column c of every parity
chunk depends ONLY on byte column c of the k data chunks. "The stripes
a write touches" are therefore intra-chunk COLUMN INTERVALS: a 4 KiB
write into a 4 MiB object touches one small column window, and the RMW
reads exactly those columns of the k data shards, re-encodes that
window (through the batch EncodeService — the window is just a smaller
planar encode), and ships per-shard sub-extents via Transaction.write_at.

Coordination: writes whose column windows overlap would race on the
parity columns they share (each computes full new parity for its
window), so the ExtentCache serializes overlapping reservations in
arrival order and lets disjoint windows proceed concurrently — which
the whole-object path (everything under the PG lock) never could. This
trades the reference's pending-extent read-through for arrival-order
serialization: same consistency contract, no cross-write data plumbing.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

Interval = tuple[int, int]  # [lo, hi) byte columns within a chunk


def _align_down(x: int, unit: int) -> int:
    return x - x % unit


def _align_up(x: int, unit: int) -> int:
    return x + (unit - x % unit) % unit


def merge_intervals(ivals: list[Interval]) -> list[Interval]:
    """Sorted, coalesced (touching intervals merge)."""
    out: list[Interval] = []
    for lo, hi in sorted(ivals):
        if out and lo <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return out


def overlaps(a: list[Interval], b: list[Interval]) -> bool:
    for lo1, hi1 in a:
        for lo2, hi2 in b:
            if lo1 < hi2 and lo2 < hi1:
                return True
    return False


def write_column_intervals(
    writes: list[tuple[int, int]], bs: int, unit: int
) -> list[Interval]:
    """Column windows a set of (offset, length) object writes touch.

    Object byte X lives in logical chunk X//bs at column X%bs (the
    contiguous-split layout EncodeService.encode uses), so a write maps
    to one column segment per chunk it crosses; segments from all
    writes merge into aligned windows. Alignment to `unit` keeps every
    window a size the codec's get_chunk_size treats as its own chunk
    size, so the window re-encodes through the unmodified planar path.
    """
    ivals: list[Interval] = []
    for off, length in writes:
        if length <= 0:
            continue
        end = off + length
        for chunk in range(off // bs, (end - 1) // bs + 1):
            lo = max(off - chunk * bs, 0)
            hi = min(end - chunk * bs, bs)
            ivals.append((
                _align_down(lo, unit), min(_align_up(hi, unit), bs)
            ))
    return merge_intervals(ivals)


def patch_window(
    window: bytearray, interval: Interval, k: int,
    writes: list[tuple[int, int, bytes]], bs: int,
) -> None:
    """Apply client writes into a column-window buffer in place.

    `window` holds columns [lo,hi) of the k data chunks back to back
    (logical chunk l at window[l*W:(l+1)*W]); `writes` are
    (object_offset, length, data) in op order.
    """
    lo, hi = interval
    w = hi - lo
    for off, length, data in writes:
        end = off + length
        for chunk in range(off // bs, max(off, end - 1) // bs + 1):
            if chunk >= k:
                break
            seg_lo = max(off - chunk * bs, 0)
            seg_hi = min(end - chunk * bs, bs)
            c0, c1 = max(seg_lo, lo), min(seg_hi, hi)
            if c0 >= c1:
                continue
            src = chunk * bs + c0 - off
            dst = chunk * w + (c0 - lo)
            window[dst: dst + (c1 - c0)] = data[src: src + (c1 - c0)]


@dataclass
class _Reservation:
    name: str
    intervals: list[Interval]
    event: asyncio.Event = field(default_factory=asyncio.Event)


class ExtentCache:
    """Per-PG in-flight sub-write coordination (ExtentCache.h role).

    reserve() admits a write's column windows when no earlier in-flight
    reservation on the same object overlaps them; release() wakes the
    queue. Arrival order is preserved (no starvation: a waiter only
    yields to reservations that arrived before it).
    """

    def __init__(self) -> None:
        self._queue: list[_Reservation] = []
        self.reservations = 0
        self.conflicts = 0

    async def reserve(
        self, name: str, intervals: list[Interval]
    ) -> _Reservation:
        r = _Reservation(name, list(intervals))
        self._queue.append(r)
        self.reservations += 1
        while True:
            mine = self._queue.index(r)
            blocker = next(
                (
                    q for q in self._queue[:mine]
                    if q.name == name
                    and overlaps(q.intervals, r.intervals)
                ),
                None,
            )
            if blocker is None:
                return r
            self.conflicts += 1
            await blocker.event.wait()

    def release(self, r: _Reservation) -> None:
        if r in self._queue:  # idempotent: error paths may double-release
            self._queue.remove(r)
        r.event.set()
