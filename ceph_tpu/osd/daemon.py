"""OSDService: the storage daemon (L6).

One process-per-OSD data plane speaking the messenger, mirroring the
reference's structure (src/osd/OSD.cc boot at ceph_osd.cc:106, fast
dispatch at OSD.cc:6877) at mini scale:

  boot      bind messenger -> MonClient subscribe -> send osd_boot with our
            address -> serve once the committed map shows us up
  ops       clients send "osd_op" to the acting primary; the primary drives
            the backend (PrimaryLogPG::do_op -> PGBackend analogues):
              * replicated: apply locally + fan "rep_write" sub-ops to the
                other acting members, ack to the client when all commit
                (ReplicatedBackend sub-write fan-out)
              * EC: encode on the TPU codec, "ec_sub_write" one shard to
                each acting position, ack when all commit
                (ECBackend::start_rmw -> ECSubWrite, ECBackend.cc:1830);
                reads gather minimum_to_decode shards via "ec_sub_read"
                and decode only when degraded (objects_read_async, 2154)
  fencing   an op whose placement disagrees with our map is bounced with
            the current epoch ("wrong_primary"); the Objecter refreshes its
            map and resends — the reference drops stale-epoch ops the same
            way and relies on client resend (epoch-tagged resend contract)
  peering   on every map epoch whose acting set changed, the primary runs
            GetInfo -> GetLog -> GetMissing -> recover (PeeringState.h
            statechart collapsed to one async pass): collect pg_info from
            acting members, adopt the most advanced log (pull objects it
            names that we lack), then push log + objects/shards every
            laggard is missing; EC shards a member lacks are rebuilt by
            decoding from surviving shards. Every sub-write carries its log
            entry, so replicas' logs advance with their data, exactly like
            ECSubWrite carrying log_entries in the reference
  logs      per-PG log in the pg-meta object's omap ("log/<version>" ->
            entry, PGLog.cc role): the authoritative object inventory that
            peering compares and recovery replays
  failure   periodic pings to peers holding PGs with us; a peer silent past
            osd_heartbeat_grace is reported to the mon (OSD.cc:4547
            handle_osd_ping / heartbeat_check), which commits the down mark

Object naming: a replicated object is stored under its name in collection
"pg_<pool>_<ps>"; EC shard i of an object is "<name>.s<i>" in the same
collection — shard identity in the key, as ECBackend's shard_id_t does.
"""

from __future__ import annotations

import asyncio
import json
import time
import zlib

from ceph_tpu.common.config import Config
from ceph_tpu.common.crc import ceph_crc32c
from ceph_tpu.common.kv import KeyValueDB
from ceph_tpu.common.op_queue import QOS_RECOVERY
from ceph_tpu.common.watchdog import SharedWatchdog
from ceph_tpu.msg import (
    Dispatcher,
    Message,
    Messenger,
    Policy,
    payload_of,
    redirect_reply,
)
from ceph_tpu.msg.frames import FEATURE_SUBOP_BATCH
from ceph_tpu.mon.client import MonClient
from ceph_tpu.osd.cls import ClsError, MethodContext, default_handler
from ceph_tpu.osd.ecutil import SEED, HashInfo
from ceph_tpu.osd.extent_cache import (
    ExtentCache,
    patch_window,
    write_column_intervals,
)
from ceph_tpu.osd.objectstore import (
    StoreError,
    StoreFatalError,
    Transaction,
    create_store,
)
from ceph_tpu.osd.ops import (
    ObjectState,
    OpError,
    execute_ops,
    is_mutating,
)
from ceph_tpu.osd.osdmap import CRUSH_ITEM_NONE

_NONE = CRUSH_ITEM_NONE

#: mutations still allowed on a FULL osd: space-freeing ops only
#: (the reference lets deletes through so the operator can recover)
_FULL_OK_OPS = {
    "delete", "rmxattr", "omap_rm", "omap_clear", "unwatch",
}

#: the active trace id for the op currently executing in this task
#: (zipkin_trace.h role): set when a traced client op starts, read by
#: _peer_call so every downstream sub-op hop carries the id — async
#: context propagates through awaits AND create_task, so the EC write
#: pipeline's spawned tasks inherit it without plumbing
import contextvars as _contextvars

_trace_ctx: "_contextvars.ContextVar[str | None]" = (
    _contextvars.ContextVar("ceph_trace_id", default=None)
)


class _StalePartial(Exception):
    """A prepared sub-stripe RMW found its base superseded by a
    whole-object mutation at commit time; the caller re-prepares."""


class _PartialUnfit(Exception):
    """Sub-stripe RMW preconditions failed mid-prepare (degraded shard,
    stale version, codec geometry); fall back to whole-object RMW."""


class _SubOpCollector:
    """Stand-in connection for one op inside a subop_batch frame: the
    inner handler's sub_reply lands in a future instead of going
    straight to the wire, so the batch handler can re-coalesce the
    per-op acks into one reply frame."""

    def __init__(self, conn, fut: asyncio.Future):
        self.fut = fut
        self.peer_name = getattr(conn, "peer_name", None)
        self.peer_nonce = getattr(conn, "peer_nonce", 0)

    def send_message(self, msg) -> None:
        if not self.fut.done():
            self.fut.set_result(msg)


def pg_coll(pool: int, ps: int) -> str:
    return f"pg_{pool}_{ps}"


def shard_name(name: str, shard: int | None) -> str:
    return name if shard is None else f"{name}.s{shard}"


#: reserved xattr carrying the head's SnapSet (osd_types.h SnapSet:
#: seq + clone list + clone sizes); reserved names are invisible to
#: client getxattrs and travel with the object through every write,
#: push, and scrub path because they live in the ordinary xattr blob
SNAPSET_XATTR = "\x01ss"


def snap_store_name(name: str, snapid: int) -> str:
    """Storage name of a clone object (hobject_t's snap field folded into
    the key, like shard ids are)."""
    return f"{name}\x1f{snapid:016x}"


def snapdir_name(name: str) -> str:
    """When the head is deleted but clones survive, the SnapSet parks on
    this object (the reference's CEPH_SNAPDIR virtual object)."""
    return f"{name}\x1fsnapdir"


def load_snapset(xattrs: dict) -> dict:
    raw = xattrs.get(SNAPSET_XATTR)
    if not raw:
        return {"seq": 0, "clones": [], "sizes": {}}
    return json.loads(raw)


class PG:
    """Per-PG volatile state; durable state lives in the store.

    Persistent layout in the pg-meta object's omap:
      log/<v>    retained log entries (trimmed to osd_min_pg_log_entries,
                 PGLog::trim)
      obj/<name> the object inventory: name -> latest entry, INDEPENDENT
                 of log retention — trimming the log never forgets what
                 objects exist (the missing-set/backfill source of truth)
      info       {last_update, log_tail, head:[epoch,version]}
    """

    META = ".pgmeta"

    def __init__(self, service: "OSDService", pool: int, ps: int):
        self.service = service
        self.pool = pool
        self.ps = ps
        self.coll = pg_coll(pool, ps)
        self.lock = asyncio.Lock()  # serializes writes + peering
        store = service.store
        if not store.collection_exists(self.coll):
            store.queue_transaction(
                Transaction().create_collection(self.coll).touch(
                    self.coll, self.META
                )
            )
        self._last_update = 0
        #: versions <= log_tail have been trimmed from the log
        self._log_tail = 0
        #: last epoch this PG went active under us as primary
        #: (last_epoch_started): the horizon past-interval checks reach
        #: back to
        self.les = 0
        #: eversion of the newest entry: (epoch it was written in,
        #: version) — the reference's eversion_t, what makes two reigns'
        #: same-numbered entries distinguishable for divergence handling
        self._head: tuple[int, int] = (0, 0)
        self._inventory: dict[str, dict] = {}
        #: reqid -> version: client-op dup detection across primary
        #: failover (the reference scans the pg log for the reqid,
        #: PrimaryLogPG::check_in_progress_op); entries replicate so a new
        #: primary inherits the set
        self._reqids: dict[str, int] = {}
        #: reqids whose fan-out fully completed THIS primary's tenure: a
        #: dup whose reqid is logged but not here means the original op
        #: aborted mid-fan-out — it must be completed forward (full-state
        #: re-push) before acking, or the ack would cover a write that
        #: exists on too few members to survive the next failure
        self._reqids_done: set[str] = set()
        omap = store.omap_get(self.coll, self.META)
        raw_info = omap.get(b"info")
        if raw_info:
            info = json.loads(raw_info)
            self._last_update = info.get("last_update", 0)
            self._log_tail = info.get("log_tail", 0)
            self._head = tuple(info.get("head", (0, 0)))
            self.les = info.get("les", 0)
        #: retained log mirror (bounded by osd_min_pg_log_entries):
        #: version -> entry, so entry_at/log_entries never rescan the
        #: whole pg-meta omap (which also holds the full inventory)
        self._log: dict[int, dict] = {}
        for k, v in sorted(omap.items()):
            if k.startswith(b"obj/"):
                e = json.loads(v)
                self._inventory[e["name"]] = e
            elif k.startswith(b"log/"):
                e = json.loads(v)
                self._log[e["version"]] = e
                self._last_update = max(self._last_update, e["version"])
                if e.get("reqid"):
                    self._reqids[e["reqid"]] = e["version"]
                if (e.get("epoch", 0), e["version"]) > self._head:
                    self._head = (e.get("epoch", 0), e["version"])
        #: a primary serves client IO only once peering for the current
        #: interval finished (PeeringState: Peering -> Active); until then
        #: ops bounce with a retryable error, so a revived primary can
        #: never serve ENOENT for an object it simply hasn't learned yet
        self.active = False
        self.last_acting: list[int] | None = None
        #: lock-taking sub-ops run through this per-PG queue instead of
        #: the connection's dispatch loop — a handler awaiting pg.lock
        #: inside dispatch would stall every later frame on that
        #: connection, and lock-holders calling peers whose dispatch is
        #: likewise stalled deadlock ACROSS daemons (the reference keeps
        #: its messenger fast-dispatch non-blocking for the same reason)
        self.subop_q: asyncio.Queue = asyncio.Queue()
        self.subop_task: asyncio.Task | None = None
        #: in-flight sub-stripe overwrite coordination (ExtentCache.h
        #: role): overlapping column windows serialize, disjoint ones
        #: run their read+encode legs outside the PG lock concurrently
        self.extents = ExtentCache()
        #: name -> obj_ver of the last WHOLE-object mutation this tenure
        #: (full write / delete / truncate path); the fence a prepared
        #: sub-stripe RMW validates against at commit — disjoint partial
        #: writes may interleave freely, a full rewrite forces re-prepare
        self._full_mut: dict[str, int] = {}
        #: acting members whose logs could not be bridged (blank revival,
        #: divergence): the PG activates WITHOUT them — they take no
        #: write sub-ops and satisfy neither min_size nor reads until the
        #: background drain backfills them (the reference's async
        #: backfill with backfill_targets, PeeringState::Active +
        #: recover_backfill; PastIntervals is what keeps their stale
        #: stores from masquerading as current)
        self.backfill_targets: set[int] = set()
        self.backfill_task: asyncio.Task | None = None
        #: the primary itself revived amnesiac: it adopted the
        #: authority's log/inventory and serves (reads decode around the
        #: missing local data) while a background sweep pulls its own
        #: copies/shards back
        self.self_backfill = False
        self.self_backfill_task: asyncio.Task | None = None
        #: balanced-read activation marker on a NON-primary member: the
        #: primary's pg_activate broadcast {les, acting, backfill} —
        #: replica-side proof that peering for that interval finished and
        #: our copy set was current when it did. None means never heard
        #: (or invalidated by a map change): balanced reads redirect,
        #: because a replica has no interval knowledge of its own
        self.replica_marker: dict | None = None

    # -- the persisted log ----------------------------------------------------

    @property
    def last_update(self) -> int:
        return self._last_update

    @property
    def log_tail(self) -> int:
        return self._log_tail

    @property
    def head(self) -> tuple[int, int]:
        return self._head

    def log_entries(self, from_version: int = 0) -> list[dict]:
        return [
            self._log[v] for v in sorted(self._log)
            if v > from_version
        ]

    def entry_at(self, version: int) -> dict | None:
        return self._log.get(version)

    def _info_blob(self) -> bytes:
        return json.dumps(
            {"last_update": self._last_update,
             "log_tail": self._log_tail,
             "head": list(self._head),
             "les": self.les}
        ).encode()

    def set_les(self, epoch: int) -> None:
        self.les = max(self.les, epoch)
        self.service.store.queue_transaction(
            Transaction().omap_setkeys(
                self.coll, self.META, {b"info": self._info_blob()}
            )
        )

    def append_log(self, txn: Transaction, entry: dict) -> None:
        """Record `entry` in the transaction AND the in-memory mirror; the
        caller must queue_transaction(txn) before yielding control (all
        call sites do, under the PG lock). Trims the log to the configured
        horizon (PGLog::trim) — the obj/ inventory keeps full knowledge."""
        self._last_update = max(self._last_update, entry["version"])
        ev = (entry.get("epoch", 0), entry["version"])
        if ev > self._head:
            self._head = ev
        self._log[entry["version"]] = entry
        rows = {
            b"log/%016x" % entry["version"]: json.dumps(entry).encode(),
            b"obj/" + entry["name"].encode(): (
                json.dumps(entry).encode()
            ),
        }
        max_entries = self.service.config.get("osd_min_pg_log_entries")
        if self._last_update - self._log_tail > max_entries:
            new_tail = self._last_update - max_entries
            txn.omap_rmkeys(
                self.coll, self.META,
                [b"log/%016x" % v
                 for v in range(self._log_tail + 1, new_tail + 1)],
            )
            for v in range(self._log_tail + 1, new_tail + 1):
                self._log.pop(v, None)
            self._log_tail = new_tail
            # the dup-detection horizon tracks the trimmed log: reqids
            # below the tail are forgotten in memory exactly as a
            # restart reloading from the log would forget them
            stale = [
                r for r, v in self._reqids.items() if v <= new_tail
            ]
            for r in stale:
                del self._reqids[r]
                self._reqids_done.discard(r)
        rows[b"info"] = self._info_blob()
        txn.omap_setkeys(self.coll, self.META, rows)
        cur = self._inventory.get(entry["name"])
        if cur is None or entry["version"] > cur["version"]:
            self._inventory[entry["name"]] = entry
        if entry.get("reqid"):
            self._reqids[entry["reqid"]] = entry["version"]

    def reset_log(
        self, txn: Transaction, inventory: dict[str, dict],
        head: tuple[int, int], tail: int,
    ) -> None:
        """Backfill epilogue: adopt the authority's object inventory and
        restart the log fresh at its head (divergent local entries are
        gone — their client ops were never fully acked and will re-execute
        under new reqids on retry)."""
        omap = self.service.store.omap_get(self.coll, self.META)
        txn.omap_rmkeys(
            self.coll, self.META,
            [k for k in omap if k.startswith((b"log/", b"obj/"))],
        )
        self._inventory = {}
        self._reqids = {}
        self._log = {}
        rows = {}
        for name, e in inventory.items():
            rows[b"obj/" + name.encode()] = json.dumps(e).encode()
            self._inventory[name] = e
            if e.get("reqid"):
                self._reqids[e["reqid"]] = e["version"]
        self._last_update = head[1]
        self._log_tail = tail
        self._head = tuple(head)
        rows[b"info"] = self._info_blob()
        txn.omap_setkeys(self.coll, self.META, rows)

    def latest_objects(self) -> dict[str, dict]:
        """name -> newest entry (the recovery/backfill inventory)."""
        return self._inventory


class OSDService(Dispatcher):
    def __init__(
        self,
        osd_id: int,
        monmap,
        db: KeyValueDB | None = None,
        config: Config | None = None,
        keyring: dict[str, bytes] | None = None,
        crush_location: dict | None = None,
    ):
        self.id = osd_id
        #: e.g. {"host": "host9"} — announced at boot so the mon can place
        #: a brand-new device in the crush hierarchy (cluster expansion)
        self.crush_location = crush_location
        self.name = f"osd.{osd_id}"
        self.config = config if config is not None else Config()
        # kstore over the given KV db by default; `osd_objectstore =
        # blockstore` opts into the allocator/at-rest-checksum store
        # (its block file lands beside a FileDB's WAL)
        self.store = create_store(db, self.config)
        # distributed tracer (common/tracer): spans at every layer of
        # the op path; disabled cost is one cached-flag check per site
        from ceph_tpu.common.tracer import Tracer

        self.tracer = Tracer(self.name, config=self.config)
        self.store.tracer = self.tracer
        self.messenger = Messenger(
            self.name, config=self.config, keyring=keyring
        )
        self.messenger.tracer = self.tracer
        self.messenger.dispatcher = self
        # MonClient chains itself in front of us on the shared messenger
        self.mon = MonClient(
            self.name, monmap, config=self.config,
            messenger=self.messenger,
        )
        self.pgs: dict[tuple[int, int], PG] = {}
        self.cls = default_handler()  # in-OSD object classes (src/cls)
        # coalesces concurrent EC encodes/decodes into planar TPU
        # launches (the batch window is the write-path latency bound)
        from ceph_tpu.osd.encode_service import EncodeService

        self.encode_service = EncodeService(
            window=self.config.get("osd_ec_batch_window"),
            tracer=self.tracer,
        )
        # per-daemon perf counters, dumped via the admin surface the way
        # `ceph daemon osd.N perf dump` reads the admin socket
        from ceph_tpu.common.perf_counters import PerfCountersCollection

        self.perf_collection = PerfCountersCollection()
        self.perf = self.perf_collection.create(self.name)
        # a BlockStore keeps its own counter block (cache hits, deferred
        # queue depth/age, flush latency); adopt it so `perf dump` shows
        # the store alongside the op-path counters
        store_perf = getattr(self.store, "perf", None)
        if store_perf is not None:
            self.perf_collection.add(store_perf)
        # span latency histograms land beside the op counters, so the
        # Prometheus exporter scrapes trace timings as metrics
        self.perf_collection.add(self.tracer.perf)
        # wire-path counters (frames out, corked runs, envelope format)
        # surface through the same dump/Prometheus path
        self.perf_collection.add(self.messenger.perf)
        for key, desc in (
            ("subop_batch_tx", "coalesced multi-op frames sent to peers"),
            ("subop_batch_tx_ops", "sub-ops that rode a coalesced frame"),
            ("subop_batch_rx", "coalesced multi-op frames received"),
            ("subop_direct", "sub-ops sent as their own frame"),
        ):
            self.perf.add_u64_counter(key, desc)
        for key, desc in (
            ("op_w", "client writes served as primary"),
            ("op_w_partial", "EC writes served via sub-stripe RMW"),
            ("op_r", "client reads served as primary"),
            ("op_rw", "client cls calls served as primary"),
            ("subop_w", "replica/shard sub-writes applied"),
            ("recovery_pushes", "objects/shards pushed during recovery"),
            ("recovery_pulls", "objects/shards pulled during peering"),
            ("recovery_sub_bytes",
             "helper bytes read via fractional sub-chunk repair"),
            ("read_error_repaired",
             "primary read EIOs healed from replicas/EC survivors "
             "before the client saw them (rep_repair_primary_object)"),
            ("read_balanced",
             "client reads this OSD served as a NON-primary acting "
             "member (rados_read_policy balance/localize)"),
            ("read_redirected",
             "balanced/direct-shard reads bounced back to the primary "
             "(peering, backfill, stale marker, or local error — never "
             "served from an unproven copy)"),
            ("read_shard_direct",
             "EC data-shard ranges served straight to clients with no "
             "primary gather/decode"),
            ("scrub_errors", "inconsistencies found by scrub"),
            ("heartbeat_failures", "peer failures reported to the mon"),
            ("tier_hit", "cache-pool ops served from the cache"),
            ("tier_promote", "objects promoted from the base pool"),
            ("tier_miss", "cache misses with no base object either"),
            ("tier_flush", "dirty objects flushed to the base pool"),
            ("tier_evict", "clean objects evicted from the cache"),
            ("op_in_bytes", "client payload bytes written as primary"),
            ("op_out_bytes", "client payload bytes read as primary"),
        ):
            self.perf.add_u64_counter(key, desc)
        # sampled by the mgr report tick (and perf dump): ops queued on
        # the shards + pipelined in-flight tasks — the overload signal
        # mgr SLO rules like `osd_queue_depth.avg < N` watch
        self.perf.add_u64(
            "osd_queue_depth",
            "client ops queued on the op shards or executing",
        )
        # write-path leg timings (the l_* time_avg family the reference
        # keeps in l_osd_op_w_process_lat etc.): where a client op's
        # wall time goes, for `perf dump` + the latency investigations
        # multi-process deployment makes meaningful
        for key, desc in (
            ("l_op_total", "whole primary-side client op"),
            ("l_load_state", "EC RMW read leg (_load_state_ec)"),
            ("l_encode", "batch-encode service wait"),
            ("l_fan", "sub-write fan-out gather (RTT + shard apply)"),
            ("l_subop_apply", "shard-side sub-write apply"),
            ("l_txn", "store.queue_transaction on the shard"),
            ("l_subop_transit", "sub-write wire transit (send->dispatch)"),
            ("l_subop_queue", "sub-write shard queue wait (dispatch->pick)"),
            ("l_loop_lag", "event-loop scheduling overshoot (watchdog)"),
        ):
            self.perf.add_time_avg(key, desc)
        self._codecs: dict[int, object] = {}
        self._tids = iter(range(1, 1 << 62))
        self._waiters: dict[int, asyncio.Future] = {}
        #: one deadline sweep for the whole sub-op fan-out instead of a
        #: TimerHandle armed+cancelled per _peer_call (Objecter::tick)
        self._watchdog = SharedWatchdog()
        #: peer osd -> sub-ops queued this event-loop tick, flushed as
        #: one subop_batch frame by a call_soon (sub-op coalescing)
        self._subop_pending: dict[int, list] = {}
        self._subop_batch = bool(self.config.get("ms_subop_batch"))
        self.config.observe(
            "ms_subop_batch",
            lambda _n, v: setattr(self, "_subop_batch", bool(v)),
        )
        self._hb_last: dict[int, float] = {}
        #: heartbeat_inject_failure window end (None = disarmed)
        self._hb_inject_until: float | None = None
        #: highest up_thru epoch already requested from the mon (the
        #: OSD::up_thru_wanted role; avoids a request per peering pass)
        self._up_thru_requested = 0
        #: peer -> last failure-report time; reports repeat every grace
        #: interval while the peer stays silent and up-in-map (a one-shot
        #: report can be lost to mon leadership churn, and the mon counts
        #: distinct reporters, not report instances, so repeats are safe)
        self._reported: dict[int, float] = {}
        #: (pool, ps, name) -> [(conn, watcher, cookie)] watch sessions
        self._watchers: dict[tuple, list] = {}
        self._notify_waiters: dict[tuple, asyncio.Future] = {}
        # per-op event timeline ("slow request" reporting, TrackedOp.h)
        from ceph_tpu.common.admin import OpTracker

        self.op_tracker = OpTracker(
            slow_op_seconds=self.config.get("slow_op_seconds")
        )
        #: (pool, ps) -> error count from the last deep scrub of that PG
        #: (primary-side); feeds the PG_DAMAGED health check and clears
        #: when a rescrub comes back clean
        self._scrub_incons: dict[tuple, int] = {}
        #: trace id -> [(unix ts, "osd.N", event)] span events
        #: (ZTracer::Trace spans at mini scale)
        self.traces: dict[str, list] = {}
        # dout-style subsystem logging with the always-on recent ring
        # (src/log/Log.cc); dumped via the `log dump` admin command
        from ceph_tpu.common.log import LogRegistry

        self.logs = LogRegistry(self.config)
        self.dlog = self.logs.get_logger("osd")
        # sharded op queue (ShardedOpWQ): workers start in start(); the
        # scheduler inside each shard is selected by osd_op_queue
        # (wpq | mclock), the reference's op-queue switch
        from ceph_tpu.common.op_queue import (
            QOS_DATA_PREFETCH,
            QOS_RECOVERY,
            MClockOpQueue,
            WeightedPriorityQueue,
            data_prefetch_profile,
            recovery_profile,
        )

        queue_kind = self.config.get("osd_op_queue")
        try:
            data_weight = float(self.config.get("osd_mclock_data_weight"))
            rec_weight = float(
                self.config.get("osd_mclock_recovery_weight")
            )
            rec_res = float(
                self.config.get("osd_mclock_recovery_reservation")
            )
        # cephlint: disable=error-taxonomy (config races boot: fall back to the shipped default weight)
        except Exception:
            data_weight = 0.25
            rec_weight, rec_res = 0.25, 10.0

        def _make_queue():
            if queue_kind != "mclock":
                return WeightedPriorityQueue()
            q = MClockOpQueue()
            # bulk dataset prefetch rides a background weight profile so
            # it can't starve foreground (weight-1) client classes
            q.set_profile(
                QOS_DATA_PREFETCH, data_prefetch_profile(data_weight)
            )
            # recovery sub-ops (pulls, rebuild reads, batched pushes):
            # fractional weight caps the storm, the reservation floor
            # keeps healing from stalling to zero under client load
            q.set_profile(
                QOS_RECOVERY, recovery_profile(rec_weight, rec_res)
            )
            return q

        class _OpShard:
            def __init__(self):
                self.queue = _make_queue()
                self.kick = asyncio.Event()
                #: object name -> in-flight PIPELINED op tasks; inline
                #: ops on the same object drain these first so
                #: per-object client ordering survives pipelining
                self.inflight: dict[str, set] = {}

        self._op_shards = [_OpShard() for _ in range(4)]
        #: pool id -> client ops served as primary (cumulative); rides
        #: the mgr report's status section for `ceph top` per-pool rows
        self._pool_ops: dict[int, int] = {}
        #: object copies missing from our primary PGs (recomputed by
        #: _pg_stats_loop); rides both the mon pg-stats report and the
        #: mgr status block, feeding PG_DEGRADED / RECOVERY_SLOW
        self._degraded_objects = 0
        self._tasks: list[asyncio.Task] = []
        self._ephemeral: set[asyncio.Task] = set()
        self._next_reboot = 0.0
        self._acting_cache: dict[tuple[int, int], tuple] = {}
        self._acting_cache_epoch = -1
        self._hist_cache: dict[tuple[int, int], list] = {}
        self._hist_cache_epoch = -1
        #: bounds concurrent backfills we source (osd_max_backfills /
        #: the reservation sched_scrub-style throttle)
        self._backfill_sem = asyncio.Semaphore(
            self.config.get("osd_max_backfills")
        )
        self._stopped = False
        #: fail-stop in progress (a fatal store error fenced us); set
        #: once so repeated store failures schedule one shutdown
        self._fencing = False
        self._loop: asyncio.AbstractEventLoop | None = None
        # fail-stop contract: a write/fsync device error fences the
        # store (no further acks can lie about durability) and the
        # daemon reports itself to the mon + shuts down cleanly — the
        # callback may fire on the store's flusher thread, so it only
        # schedules onto our event loop
        self.store.on_fatal = self._note_store_fatal
        self.mon.on_map_change(self._note_map)
        self._map_dirty = asyncio.Event()

    # -- lifecycle ------------------------------------------------------------

    @property
    def osdmap(self):
        return self.mon.osdmap

    async def start(self) -> None:
        self._loop = asyncio.get_event_loop()
        await self.messenger.bind()
        self.mon.subscribe()
        await self.mon.wait_for_map()
        # serve once the quorum-committed map says we're up at our address;
        # the boot report is re-sent until then (it can race an election
        # or ride a session that dies — one-way messages need the retry)
        loop = asyncio.get_event_loop()
        end = loop.time() + 30
        next_boot = 0.0
        while loop.time() < end:
            m = self.osdmap
            if (
                self.id < m.max_osd
                and m.osd_up[self.id]
                and m.osd_addrs.get(self.id)
                == tuple(self.messenger.my_addr)
            ):
                break
            if loop.time() >= next_boot:
                self.mon.send_boot(
                    self.id, tuple(self.messenger.my_addr),
                    location=self.crush_location,
                    local_addr=self.messenger.my_local_addr,
                )
                next_boot = loop.time() + 1.0
            await asyncio.sleep(0.02)
        if (d := self.dlog.dout(1)) is not None:
            d(f"osd.{self.id} booted at {self.messenger.my_addr}, "
              f"epoch {self.osdmap.epoch}")
        self._tasks.append(asyncio.create_task(self._loop_lag_watchdog()))
        self._tasks.append(asyncio.create_task(self._slow_op_loop()))
        self._tasks.append(asyncio.create_task(self._heartbeat_loop()))
        self._tasks.append(asyncio.create_task(self._peering_loop()))
        self._tasks.append(asyncio.create_task(self._resub_loop()))
        self._tasks.append(asyncio.create_task(self._pg_stats_loop()))
        self._tasks.append(asyncio.create_task(self._mgr_report_loop()))
        if self.messenger.keyring is not None:
            # cephx: fetch the rotating service-key window so client
            # tickets verify locally, and keep it fresh through
            # rotations (the KeyServer-to-daemon distribution) — plus
            # reactively when a ticket shows up under a newer epoch
            await self._fetch_rotating_keys()
            self.messenger.on_service_keys_stale = (
                self._fetch_rotating_keys
            )
            self._tasks.append(
                asyncio.create_task(self._rotating_keys_loop())
            )
        for shard in self._op_shards:
            self._tasks.append(
                asyncio.create_task(self._op_shard_worker(shard))
            )
        self._note_map(self.osdmap)

    # -- cross-daemon tracing (src/common/zipkin_trace.h role) ----------------

    def _trace(self, trace_id: str | None, event: str) -> None:
        """Record one span event under a trace id. Each daemon keeps its
        own span store; `dump_trace` on the admin surface hands the
        events out and the client stitches the full multi-daemon
        timeline (wall clock: every daemon shares the host's)."""
        if not trace_id:
            return
        import time as _time

        store = self.traces.setdefault(trace_id, [])
        store.append((_time.time(), f"osd.{self.id}", event))
        if len(self.traces) > 256:  # bound the span store
            self.traces.pop(next(iter(self.traces)))

    def statfs(self) -> dict:
        """Store utilization (ObjectStore::statfs): advertised capacity
        comes from config (the disk-size role), used bytes from the live
        KV footprint. Cached briefly — the scan is O(rows)."""
        loop = asyncio.get_event_loop()
        cached = getattr(self, "_statfs_cache", None)
        ttl = float(self.config.get("osd_statfs_cache_sec"))
        if cached is not None and loop.time() - cached[0] < ttl:
            return cached[1]
        total = self.config.get("osd_statfs_total_bytes")
        used = self.store.used_bytes()
        st = {
            "total": int(total),
            "used": int(used),
            "available": max(0, int(total) - int(used)),
        }
        comp = getattr(self.store, "compression_stats", None)
        if comp is not None:
            st.update(comp())
        self._statfs_cache = (loop.time(), st)
        return st

    def _is_full(self) -> bool:
        st = self.statfs()
        return st["used"] >= st["total"] * self.config.get(
            "mon_osd_full_ratio"
        )

    async def _slow_op_loop(self) -> None:
        """Warn the MOMENT an op crosses slow_op_seconds (the reference's
        op_tracker check_ops_in_flight -> cluster-log "slow request"
        lines, OSD.cc tick path) — slow ops must not stay invisible
        until someone polls dump_ops_in_flight. One line per op."""
        interval = min(
            1.0, max(0.05, self.op_tracker.slow_op_seconds / 4)
        )
        while not self._stopped:
            await asyncio.sleep(interval)
            for op_id, dump in self.op_tracker.check_slow():
                last = (
                    dump["events"][-1]["event"]
                    if dump["events"] else "none"
                )
                tr = dump.get("trace_id")
                line = (
                    f"slow request: op {op_id} "
                    f"({dump['description']}) blocked for "
                    f"{dump['age']:.3f}s, last event: {last}"
                    + (f" trace={tr}" if tr else "")
                )
                if (d := self.dlog.dout(0)) is not None:
                    d(line)
                self._cluster_log("WRN", line)

    async def _loop_lag_watchdog(self) -> None:
        """Samples how late a 10ms sleep fires: the single cheapest
        signal for 'something blocked the event loop' (jax dispatch, a
        long callback) — the latency killer multi-process deployment
        surfaces as mysterious wire-transit time."""
        loop = asyncio.get_event_loop()
        while not self._stopped:
            t0 = loop.time()
            await asyncio.sleep(0.01)
            self.perf.tinc("l_loop_lag", max(0.0, loop.time() - t0 - 0.01))

    def _spawn(self, coro) -> None:
        """Short-lived task that prunes itself on completion (notifies,
        peering nudges): `_tasks` must not grow with daemon lifetime."""
        task = asyncio.create_task(coro)
        self._ephemeral.add(task)
        task.add_done_callback(self._ephemeral.discard)

    async def stop(self) -> None:
        self._stopped = True
        self._watchdog.stop()
        self._subop_pending.clear()
        # never cancel the task running stop() itself (the fail-stop
        # path shuts the daemon down from inside an ephemeral task)
        cur = asyncio.current_task()
        tasks = [
            t for t in list(self._tasks) + list(self._ephemeral)
            if t is not cur
        ]
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        await self.messenger.shutdown()
        # a BlockStore owns a background deferred-write flusher: umount
        # joins it before the device closes and drains the backlog
        umount = getattr(self.store, "umount", None)
        if umount is not None:
            try:
                umount()
            except Exception:  # noqa: BLE001 - shutdown must not throw
                if (d := self.dlog.dout(1)) is not None:
                    d(f"osd.{self.id}: store umount failed at stop")
        self.tracer.close()

    def _cluster_log(self, level: str, message: str) -> None:
        """Best-effort clog to the mon (LogClient role): warning events
        must never take the data path down with them."""
        try:
            self.mon.cluster_log(level, message)
        # cephlint: disable=error-taxonomy (the dout line already landed; cluster log is best-effort)
        except Exception:  # noqa: BLE001 - the dout line already landed
            pass

    # -- fail-stop fencing (the Rebello et al. fsync-error contract) ----------

    def _note_store_fatal(self, reason: str) -> None:
        """The store fenced itself after a write/fsync device error.
        May be called from the store's flusher thread mid-lock: only
        schedule the fail-stop onto the event loop here."""
        if self._fencing or self._stopped:
            return
        self._fencing = True
        loop = self._loop
        if loop is None:
            return  # never started; nothing to tear down
        loop.call_soon_threadsafe(
            lambda: self._spawn(self._fail_stop(reason))
        )

    async def _fail_stop(self, reason: str) -> None:
        """Fail-stop: the store can no longer promise acks imply
        durability, so the daemon must go down rather than keep serving
        (RADOS assumes fail-stop OSDs). Report ourselves to the mon via
        the existing failure path — heartbeat peers confirm as our pings
        go silent — then shut down cleanly; the mon marks us down,
        peering re-targets, and data stays available on the survivors."""
        if (d := self.dlog.dout(0)) is not None:
            d(f"osd.{self.id}: store fenced ({reason}); fail-stop: "
              f"reporting ourselves to the mon and shutting down")
        self._cluster_log(
            "ERR",
            f"osd.{self.id}: store fenced ({reason}); fail-stop",
        )
        box = self._write_black_box(reason)
        if box is not None:
            # the pointer rides the cluster log so an operator reading
            # `ceph log last` knows exactly where the causal history is
            self._cluster_log(
                "ERR", f"osd.{self.id}: black box: {box}"
            )
        try:
            self.mon.report_failure(self.id)
        # cephlint: disable=error-taxonomy (one-way death report: peers will report us anyway)
        except Exception:  # noqa: BLE001 - peers will report us anyway
            pass
        # give the one-way report a beat on the wire before the
        # messenger dies with the rest of the daemon
        await asyncio.sleep(0.05)
        await self.stop()

    def _write_black_box(self, reason: str) -> str | None:
        """Crash black-box: on a fatal store error, persist the flight
        ring (recent span history regardless of sampling), the op
        tracker state, and the recent in-memory log lines to a file so
        the causal history of the crash survives the daemon. Best
        effort by design — the daemon is dying and must not hang on a
        diagnostic write."""
        dump_dir = self.config.get("tracer_crash_dump_dir")
        if not dump_dir:
            return None
        try:
            import os

            os.makedirs(dump_dir, exist_ok=True)
            path = os.path.join(dump_dir, f"osd.{self.id}.blackbox.json")
            box = {
                "daemon": f"osd.{self.id}",
                "reason": reason,
                "time": time.time(),
                "flight_spans": self.tracer.flight_snapshot(),
                "ops_in_flight": self.op_tracker.dump_ops_in_flight(),
                "historic_ops": self.op_tracker.dump_historic_ops(),
                "recent_log": self.logs.dump_recent(),
            }
            with open(path, "w") as fh:
                json.dump(box, fh, indent=1)
            return path
        # cephlint: disable=error-taxonomy (diagnostic write on the death path)
        except Exception:  # noqa: BLE001 - never let diagnostics block death
            return None

    # -- placement helpers ----------------------------------------------------

    def codec(self, pool_id: int):
        if pool_id not in self._codecs:
            pool = self.osdmap.pools[pool_id]
            if not pool.is_erasure():
                self._codecs[pool_id] = None
            else:
                from ceph_tpu.ec.registry import factory

                profile = dict(
                    self.osdmap.erasure_code_profiles[
                        pool.erasure_code_profile
                    ]
                )
                plugin = profile.pop("plugin", "tpu")
                self._codecs[pool_id] = factory(plugin, profile)
        return self._codecs[pool_id]

    def acting_of(self, pool_id: int, ps: int) -> tuple[list[int], int]:
        """Per-epoch memoized placement: heartbeats and per-op targeting
        would otherwise re-run the scalar CRUSH mapper thousands of times
        per second for identical answers (the OSDMapMapping cache role)."""
        m = self.osdmap
        if self._acting_cache_epoch != m.epoch:
            self._acting_cache_epoch = m.epoch
            self._acting_cache = {}
        hit = self._acting_cache.get((pool_id, ps))
        if hit is None:
            _up, _upp, acting, primary = m.pg_to_up_acting_osds(
                pool_id, ps
            )
            hit = (acting, primary)
            self._acting_cache[(pool_id, ps)] = hit
        return hit

    def object_pg(self, pool_id: int, name: str) -> int:
        from ceph_tpu.common.hash import ceph_str_hash_rjenkins

        pool = self.osdmap.pools[pool_id]
        return pool.raw_pg_to_pg(ceph_str_hash_rjenkins(name))

    def _osd_conn(self, osd: int):
        addr = self.osdmap.osd_addrs.get(osd)
        if addr is None:
            raise RuntimeError(f"no address for osd.{osd}")
        return self.messenger.connect(
            tuple(addr), Policy.lossless_client(),
            local_addr=self.osdmap.osd_local_addrs.get(osd),
        )

    async def _peer_call(
        self, osd: int, msg_type: str, payload: dict,
        timeout: float = 10.0, raw: bytes = b"",
        batchable: bool = False,
    ) -> dict:
        """Request/response to a peer OSD (sub-op + ack). Bulk bytes ride
        the raw frame segment, never hex-in-JSON (frames_v2 multi-segment
        shape); the reply's raw segment surfaces as reply["_raw"].

        `batchable` sub-ops to the same peer within one event-loop tick
        coalesce into a single subop_batch frame (a k+m stripe touching
        4 peers costs 4 frames, not k+m). Only sub-ops whose senders
        tolerate per-op timeout+retry (idempotent via the replica's
        version gate) may opt in."""
        tid = next(self._tids)
        payload = dict(payload)
        payload["tid"] = tid
        payload["reply_to"] = self.id
        payload["_sent_at"] = time.time()
        trace_id = _trace_ctx.get()
        if trace_id is not None:
            payload["trace_id"] = trace_id
            self._trace(trace_id, f"{msg_type} -> osd.{osd}")
        # fork a child span per sub-op (the per-replica/EC-shard leg):
        # covers send -> peer apply -> ack, and its context rides the
        # Message so the peer's spans hang off it
        sp = self.tracer.child(
            f"subop_{msg_type}", tags={"to": f"osd.{osd}"}
        )
        wire = "" if sp is None else sp.context().encode()
        fut = asyncio.get_event_loop().create_future()
        self._waiters[tid] = fut
        try:
            conn = self._osd_conn(osd)
            if (
                batchable
                and self._subop_batch
                and conn.is_connected
                and conn.has_feature(FEATURE_SUBOP_BATCH)
            ):
                self._queue_subop(osd, msg_type, payload, raw, wire)
            else:
                # ordering: entries already queued for this peer were
                # logically sent first — they must hit the wire first,
                # or the replica's version gate drops the later one
                self._flush_subops(osd)
                self.perf.inc("subop_direct")
                conn.send_message(
                    Message(type=msg_type, tid=tid,
                            epoch=self.osdmap.epoch,
                            payload=payload, raw=raw, trace=wire)
                )
            return await self._watchdog.wait(fut, timeout)
        finally:
            self._waiters.pop(tid, None)
            if sp is not None:
                sp.finish()

    #: bound one coalesced frame (keeps head-of-line blocking and the
    #: receiver's slice bookkeeping sane under deep fan-out backlogs)
    SUBOP_BATCH_MAX = 32

    def _queue_subop(
        self, osd: int, msg_type: str, payload: dict, raw, wire: str
    ) -> None:
        raw = raw if isinstance(raw, (bytes, bytearray, memoryview)) \
            else bytes(raw)
        pend = self._subop_pending.setdefault(osd, [])
        pend.append((msg_type, payload, raw, wire))
        if len(pend) >= self.SUBOP_BATCH_MAX:
            self._flush_subops(osd)
        elif len(pend) == 1:
            # flush a few ticks out, not one: sub-ops submitted by ops
            # that the CURRENT tick's callbacks wake (an EC encode
            # completing, a batch of client writes resuming) still join
            # this frame — the extra ticks are microseconds against a
            # ms-scale sub-op round trip, and ordering is safe because
            # every direct send flushes this peer's queue first
            loop = asyncio.get_event_loop()
            loop.call_soon(
                loop.call_soon, loop.call_soon, self._flush_subops, osd
            )

    def _flush_subops(self, osd: int) -> None:
        """Put this peer's pending sub-ops on the wire: one subop_batch
        frame when several coalesced, the plain per-op message when one.
        A send failure here is absorbed — every queued op has a waiter
        with a deadline, and _sub_op_persist retries on timeout."""
        pend = self._subop_pending.pop(osd, None)
        if not pend:
            return
        try:
            conn = self._osd_conn(osd)
            if len(pend) == 1:
                mtype, payload, raw, wire = pend[0]
                self.perf.inc("subop_direct")
                conn.send_message(
                    Message(type=mtype, tid=payload["tid"],
                            epoch=self.osdmap.epoch,
                            payload=payload, raw=raw, trace=wire)
                )
                return
            ops = [
                {"type": mtype, "payload": payload,
                 "raw_len": len(raw), "trace": wire}
                for mtype, payload, raw, wire in pend
            ]
            btid = next(self._tids)
            conn.send_message(
                Message(type="subop_batch", tid=btid,
                        epoch=self.osdmap.epoch,
                        payload={"tid": btid, "ops": ops},
                        raw=b"".join(raw for _, _, raw, _ in pend))
            )
            self.perf.inc("subop_batch_tx")
            self.perf.inc("subop_batch_tx_ops", len(pend))
        # cephlint: disable=error-taxonomy (waiters time out; _sub_op_persist re-targets/retries)
        except Exception:
            pass  # waiters time out; _sub_op_persist re-targets/retries

    def _reply_peer(
        self, conn, tid: int, payload: dict, raw: bytes = b""
    ) -> None:
        payload = dict(payload)
        payload["tid"] = tid
        conn.send_message(
            Message(type="sub_reply", tid=tid,
                    epoch=self.osdmap.epoch,
                    payload=payload, raw=raw)
        )

    # -- dispatch -------------------------------------------------------------

    async def ms_dispatch(self, conn, msg: Message) -> None:
        p = payload_of(msg)
        p["_raw"] = msg.raw  # the bulk data segment, bytes verbatim
        if msg.trace:
            p["_trace"] = msg.trace  # span context rides to the handler
        if msg.type == "sub_reply":
            replies = p.get("replies")
            if replies is not None:
                # coalesced ack for a subop_batch: fan the per-op
                # replies back out to their waiters
                raw, off = p["_raw"], 0
                for r in replies:
                    n = int(r.pop("_raw_len", 0))
                    r["_raw"] = raw[off:off + n]
                    off += n
                    fut = self._waiters.get(r.get("tid"))
                    if fut is not None and not fut.done():
                        fut.set_result(r)
                return
            fut = self._waiters.get(p.get("tid"))
            if fut is not None and not fut.done():
                fut.set_result(p)
            return
        handler = getattr(self, f"_h_{msg.type}", None)
        if handler is not None:
            await handler(conn, p)

    async def _h_subop_batch(self, conn, p) -> None:
        """Coalesced same-peer sub-ops (one frame, many ops): run each
        inner op through its normal handler IN ORDER — the ordered
        handlers enqueue synchronously, so the per-PG FIFO sees sender
        order and the _sub_op_persist invariant holds. The ack gather
        runs as its own task: dispatch stays on the read loop's fast
        path and a stalled inner op never blocks this connection."""
        self.perf.inc("subop_batch_rx")
        loop = asyncio.get_event_loop()
        raw, off = p["_raw"], 0
        futs = []
        for op in p.get("ops") or []:
            ip = dict(op["payload"])
            n = int(op.get("raw_len") or 0)
            ip["_raw"] = raw[off:off + n]
            off += n
            if op.get("trace"):
                ip["_trace"] = op["trace"]
            handler = getattr(self, f"_h_{op['type']}", None)
            if handler is None:
                continue  # sender's per-op timeout retries it
            fut = loop.create_future()
            await handler(_SubOpCollector(conn, fut), ip)
            futs.append(fut)
        if futs:
            self._spawn(
                self._subop_batch_ack(conn, p.get("tid", 0), futs)
            )

    async def _subop_batch_ack(self, conn, btid: int, futs) -> None:
        """One coalesced sub_reply for every inner op that acked within
        the window; each op acks/fails INDEPENDENTLY — a straggler is
        acked on its own when it completes (or the sender's per-op
        deadline retries it) rather than holding the batch hostage.
        The window is shorter than _sub_op_persist's 2.0s per-op
        timeout so on-time acks always beat the sender's retry."""
        done, pending = await asyncio.wait(futs, timeout=1.5)
        for fut in pending:
            fut.add_done_callback(
                lambda f, c=conn: self._subop_late_ack(c, f)
            )
        replies, raws = [], []
        for fut in futs:
            if fut not in done or fut.cancelled() or fut.exception():
                continue
            m = fut.result()
            rp = dict(
                m.payload if m.payload is not None
                else json.loads(m.data) if m.data else {}
            )
            rp["_raw_len"] = len(m.raw)
            replies.append(rp)
            raws.append(m.raw)
        if replies:
            conn.send_message(
                Message(type="sub_reply", tid=btid,
                        epoch=self.osdmap.epoch,
                        payload={"tid": btid, "replies": replies},
                        raw=b"".join(raws))
            )

    def _subop_late_ack(self, conn, fut) -> None:
        if fut.cancelled() or fut.exception() is not None:
            return
        try:
            conn.send_message(fut.result())
        # cephlint: disable=error-taxonomy (the sender's retry loop owns recovery)
        except Exception:
            pass  # the sender's retry loop owns recovery

    # -- heartbeats + failure detection ---------------------------------------

    def _hb_peers(self) -> set[int]:
        """OSDs sharing at least one PG with us (the heartbeat peer set)."""
        peers: set[int] = set()
        for (pool, ps) in self.pgs:
            acting, _ = self.acting_of(pool, ps)
            peers.update(o for o in acting if o != _NONE and o != self.id)
        return peers

    async def _resub_loop(self) -> None:
        """Periodic subscription refresh: a monitor that restarted loses
        its subscriber table, and our lossless connection reconnects
        SILENTLY — without this the daemon's map stream freezes forever
        (MonClient::tick's renew_subs role). Idempotent and cheap: the
        mon replies only the incrementals we lack."""
        interval = max(1.0, self.config.get("mon_lease") * 2)
        while not self._stopped:
            await asyncio.sleep(interval)
            try:
                self.mon.subscribe(
                    from_epoch=self.osdmap.epoch if self.osdmap else 0
                )
            except Exception:
                if (d := self.dlog.dout(20)) is not None:
                    d("renew_subs failed; next tick retries")

    async def _heartbeat_loop(self) -> None:
        """Periodic concurrent pings + a separate deadline scan (the
        reference's tick-driven MOSDPing send vs heartbeat_check split,
        OSD.cc:4547/4746): a ping RPC gets the full grace to come home, so
        a momentarily busy event loop never fakes peer silence, and one
        dead peer never stalls pings to the others."""
        interval = self.config.get("osd_heartbeat_interval")
        grace = self.config.get("osd_heartbeat_grace")
        loop = asyncio.get_event_loop()

        async def ping(peer: int) -> None:
            try:
                await self._peer_call(peer, "osd_ping", {}, timeout=grace)
                self._hb_last[peer] = loop.time()
                self._reported.pop(peer, None)
            except (asyncio.TimeoutError, RuntimeError):
                pass  # the deadline scan decides what silence means

        prev_iter = loop.time()
        while not self._stopped:
            if loop.time() - prev_iter > interval * 3:
                # OUR loop stalled (jit compile, GC, CPU burst): peers'
                # apparent silence is our own fault — forgive it rather
                # than report healthy daemons (HeartbeatMap's is_healthy
                # self-check role)
                for peer in list(self._hb_last):
                    self._hb_last[peer] = max(
                        self._hb_last[peer], loop.time() - interval
                    )
            prev_iter = loop.time()
            peers = self._hb_peers()
            for peer in peers:
                if self.osdmap.is_down(peer):
                    self._hb_last.pop(peer, None)
                    self._reported.pop(peer, None)
                    continue
                self._hb_last.setdefault(peer, loop.time())
                self._spawn(ping(peer))
            for peer in list(self._hb_last):
                if peer not in peers or self.osdmap.is_down(peer):
                    continue
                silent = loop.time() - self._hb_last[peer]
                last_report = self._reported.get(peer)
                if silent > grace and (
                    last_report is None
                    or loop.time() - last_report > grace
                ):
                    if (d := self.dlog.dout(1)) is not None:
                        d(f"peer osd.{peer} silent {silent:.1f}s: "
                          f"reporting failure")
                    self.mon.report_failure(peer)
                    self._reported[peer] = loop.time()
                    self.perf.inc("heartbeat_failures")
            await asyncio.sleep(interval)

    async def _h_osd_ping(self, conn, p) -> None:
        inject = int(self.config.get("heartbeat_inject_failure"))
        if inject:
            # heartbeat_inject_failure=N: drop incoming pings for N
            # seconds (options.cc:822) — peers see silence and report us
            loop = asyncio.get_event_loop()
            if self._hb_inject_until is None:
                self._hb_inject_until = loop.time() + inject
            if loop.time() < self._hb_inject_until:
                return
        else:
            self._hb_inject_until = None  # re-armable once cleared
        self._reply_peer(conn, p["tid"], {"ok": True})

    # -- map handling + peering -----------------------------------------------

    def _note_map(self, _osdmap) -> None:
        self._map_dirty.set()

    async def _peering_loop(self) -> None:
        """Re-evaluate PG responsibility on every map change."""
        while not self._stopped:
            await self._map_dirty.wait()
            self._map_dirty.clear()
            try:
                await self._handle_map_change()
            except asyncio.CancelledError:
                raise
            # cephlint: disable=error-taxonomy (next epoch retries)
            except Exception:
                pass  # next epoch retries

    async def _handle_map_change(self) -> None:
        m = self.osdmap
        # alive but marked down (a false failure report, or mon churn ate
        # our boot): re-boot, the reference's OSD::start_boot-on-mark-down
        # behavior — without this a spurious down mark is permanent
        loop = asyncio.get_event_loop()
        if (
            self.id >= m.max_osd
            or not m.osd_up[self.id]
            or m.osd_addrs.get(self.id) != tuple(self.messenger.my_addr)
        ):
            if loop.time() >= self._next_reboot:
                self._next_reboot = loop.time() + 1.0
                self.mon.send_boot(
                    self.id, tuple(self.messenger.my_addr),
                    location=self.crush_location,
                    local_addr=self.messenger.my_local_addr,
                )

            async def renudge():
                # the boot can be lost to mon churn; keep retrying until
                # a committed map shows us up again
                await asyncio.sleep(1.1)
                self._map_dirty.set()

            self._spawn(renudge())
            return
        self._maybe_split_pools()
        mine: set[tuple[int, int]] = set()
        for pool_id, pool in m.pools.items():
            for ps in range(pool.pg_num):
                acting, primary = self.acting_of(pool_id, ps)
                if self.id in [o for o in acting if o != _NONE]:
                    mine.add((pool_id, ps))
        for key in mine:
            if key not in self.pgs:
                self.pgs[key] = PG(self, *key)
        # primaries drive recovery for their PGs; the interval's acting set
        # is the peering trigger (PastIntervals role): unchanged acting on
        # an already-active PG needs no new pass
        retry_needed = False
        for (pool_id, ps) in sorted(mine):
            acting, primary = self.acting_of(pool_id, ps)
            pg = self.pgs[(pool_id, ps)]
            if primary != self.id:
                pg.active = False
                pg.last_acting = None
                mk = pg.replica_marker
                if mk is not None and list(acting) != list(mk["acting"]):
                    # acting moved past the marker's interval: stop
                    # serving balanced reads now instead of waiting for
                    # the history check to notice at read time
                    pg.replica_marker = None
                continue
            if pg.active and pg.last_acting == acting:
                # same acting as when we activated — but an interval may
                # have come and GONE in between (member died and revived
                # while our peering pass was busy elsewhere): if the
                # interval archive shows nothing since activation, skip;
                # otherwise re-peer, or a flapped member would silently
                # keep missing every write from the gap interval
                ivs = await self._pg_history(pg)
                if ivs is None or all(iv[0] <= pg.les for iv in ivs):
                    continue
            pg.active = False
            try:
                async with pg.lock:
                    complete = await self._peer_and_recover(pg, acting)
                # serviceability: activation with backfill targets still
                # needs enough COMPLETE members to reconstruct every
                # object (k shards for EC, one copy replicated) — else
                # keep peering and let revivals/drains change the math
                ec = self.codec(pool_id)
                need = 1 if ec is None else ec.get_data_chunk_count()
                ready = sum(
                    1 for o in acting
                    if o != _NONE and not m.is_down(o)
                    and o not in pg.backfill_targets
                )
                if complete and ready >= need:
                    if not await self._ensure_up_thru(
                        getattr(pg, "up_thru_need", m.epoch)
                    ):
                        # alive-confirmation not committed yet: serving
                        # writes before up_thru would let this interval
                        # hold acked data that future peering (which
                        # skips !maybe_went_rw intervals) could miss
                        retry_needed = True
                        continue
                    pg.active = True
                    pg.last_acting = list(acting)
                    pg.set_les(m.epoch)
                    if (d := self.dlog.dout(5)) is not None:
                        d(f"pg {pool_id}.{ps} active, acting {acting}, "
                          f"backfilling {sorted(pg.backfill_targets)}")
                    # tell the replicas peering finished so they may
                    # serve balanced reads for this interval
                    self._spawn(
                        self._broadcast_activate(pg, list(acting))
                    )
                    if pg.backfill_targets and (
                        pg.backfill_task is None
                        or pg.backfill_task.done()
                    ):
                        pg.backfill_task = asyncio.create_task(
                            self._drain_backfill(pg)
                        )
                        self._ephemeral.add(pg.backfill_task)
                        pg.backfill_task.add_done_callback(
                            self._ephemeral.discard
                        )
                    if pg.self_backfill and (
                        pg.self_backfill_task is None
                        or pg.self_backfill_task.done()
                    ):
                        pg.self_backfill_task = asyncio.create_task(
                            self._drain_self_backfill(pg)
                        )
                        self._ephemeral.add(pg.self_backfill_task)
                        pg.self_backfill_task.add_done_callback(
                            self._ephemeral.discard
                        )
                else:
                    retry_needed = True  # partial recovery: stay peering
            except asyncio.CancelledError:
                raise
            # cephlint: disable=error-taxonomy (transient peer trouble: retry_needed re-queries)
            except Exception:
                retry_needed = True  # transient peer trouble: try again
        if retry_needed and not self._stopped:
            async def nudge():
                await asyncio.sleep(0.3)
                self._map_dirty.set()

            self._spawn(nudge())
        self._spawn(self._trim_removed_snaps())

    async def _fetch_rotating_keys(self) -> None:
        from ceph_tpu.auth.cephx import unseal

        rep = await self.mon.command(
            "auth rotating", {"service": "osd"}, timeout=10.0
        )
        if "sealed" in rep:
            payload = unseal(
                self.messenger.keyring[self.name],
                bytes.fromhex(rep["sealed"]),
            )
            if payload is None:
                raise RuntimeError("rotating keys unopenable")
            window = json.loads(payload)
        else:
            window = rep["keys"]
        self.messenger.service_keys = {
            int(e): bytes.fromhex(k) for e, k in window.items()
        }

    async def _rotating_keys_loop(self) -> None:
        interval = max(
            1.0, self.config.get("auth_service_ticket_ttl") / 4
        )
        delay = interval
        while not self._stopped:
            await asyncio.sleep(delay)
            try:
                await self._fetch_rotating_keys()
                delay = interval
            # cephlint: disable=error-taxonomy (mon churn: keep retrying fast)
            except Exception:
                delay = 1.0  # mon churn: keep retrying fast

    async def _pg_stats_loop(self) -> None:
        """Primaries report PG state sums to the mon on the
        osd_mon_report_interval cadence (OSD::ms_handle osd_stat /
        MPGStats flow): the feed for the mon's health checks."""
        while not self._stopped:
            await asyncio.sleep(
                self.config.get("osd_mon_report_interval")
            )
            stats = {"num_pgs": 0, "degraded": 0, "undersized": 0,
                     "backfilling": 0, "peering": 0, "inconsistent": 0,
                     "degraded_objects": 0, "statfs": self.statfs()}
            for (pool_id, ps), pg in list(self.pgs.items()):
                pool = self.osdmap.pools.get(pool_id)
                if pool is None:
                    continue
                acting, primary = self.acting_of(pool_id, ps)
                if primary != self.id:
                    continue
                stats["num_pgs"] += 1
                if not pg.active:
                    stats["peering"] += 1
                    continue
                live = [
                    o for o in acting
                    if o != _NONE and not self.osdmap.is_down(o)
                ]
                complete = [
                    o for o in live if o not in pg.backfill_targets
                ]
                if len(live) < pool.size:
                    stats["undersized"] += 1
                if len(complete) < pool.size or pg.self_backfill:
                    stats["degraded"] += 1
                if pg.backfill_targets or pg.self_backfill:
                    stats["backfilling"] += 1
                # object-granular durability debt: one unit per live
                # object copy/shard a degraded member is missing — the
                # reference's "N/M objects degraded" numerator
                short = (pool.size - len(complete)) + (
                    1 if pg.self_backfill else 0
                )
                if short > 0:
                    nlive = sum(
                        1 for e in pg.latest_objects().values()
                        if e["kind"] != "delete"
                    )
                    stats["degraded_objects"] += nlive * short
                stats["inconsistent"] += self._scrub_incons.get(
                    (pool_id, ps), 0
                )
            self._degraded_objects = stats["degraded_objects"]
            try:
                await self.mon.command(
                    "pg stats report",
                    {"osd": self.id, "stats": stats}, timeout=5.0,
                )
            # cephlint: disable=error-taxonomy (mon churn: next interval re-reports)
            except Exception:
                pass  # mon churn: next interval re-reports

    def _update_queue_depth(self) -> int:
        """Refresh the osd_queue_depth gauge: ops waiting on the shard
        queues plus pipelined tasks already executing."""
        depth = 0
        for shard in self._op_shards:
            depth += len(shard.queue)
            depth += sum(len(s) for s in shard.inflight.values())
        self.perf.set("osd_queue_depth", depth)
        return depth

    async def _mgr_report_loop(self) -> None:
        """Push perf-counter reports to the ACTIVE mgr every
        mgr_report_interval (MgrClient::_send_report): the mgr never
        pulls `perf dump`s on its scrape path. Reports are
        delta-compacted — only counters that changed since the last
        send ride the wire — but values stay CUMULATIVE, so a dropped
        report just widens the next sample's span instead of corrupting
        rates. The active mgr's address rides the MgrMap the mon builds
        from mgr beacons; on failover we re-prime with a full report so
        the new mgr's empty store gets complete baselines."""
        last_sent: dict[tuple[str, str], object] = {}
        target: tuple[str, tuple] | None = None
        refreshed = float("-inf")
        seq = 0
        while not self._stopped:
            interval = self.config.get("mgr_report_interval")
            await asyncio.sleep(interval)
            loop = asyncio.get_event_loop()
            # refresh the MgrMap on the stale horizon ONLY — a cluster
            # with no mgr at all must not pay a mon round-trip per tick
            if loop.time() - refreshed > max(4 * interval, 2.0):
                try:
                    rep = await self.mon.command("mgr map", timeout=5.0)
                # cephlint: disable=error-taxonomy (mon churn: next tick retries)
                except Exception:
                    continue
                refreshed = loop.time()
                mm = rep.get("mgrmap") or {}
                active = mm.get("active")
                addr = (mm.get("addrs") or {}).get(active)
                if not active or not addr:
                    target = None
                else:
                    fresh = (active, tuple(addr))
                    if target != fresh:
                        target = fresh
                        last_sent = {}
            if target is None:
                continue
            queue_depth = self._update_queue_depth()
            full = not last_sent
            counters: dict[str, dict] = {}
            for block, kv in self.perf_collection.dump().items():
                for key, val in kv.items():
                    if full or last_sent.get((block, key)) != val:
                        counters.setdefault(block, {})[key] = val
                        last_sent[(block, key)] = val
            seq += 1
            report = {
                "daemon": self.name,
                "seq": seq,
                "full": full,
                "counters": counters,
                "status": {
                    "queue_depth": queue_depth,
                    "inflight_ops": self.op_tracker.num_in_flight,
                    "degraded_objects": self._degraded_objects,
                    "pool_ops": {
                        str(pid): n for pid, n in self._pool_ops.items()
                    },
                },
                # tail-sampling surface: promoted traces for the mgr
                # collector, their exemplars for the Prometheus
                # histograms, and the capture-predicate version we hold
                # (a stale version makes the mgr push fresh predicates
                # back down this same connection)
                "capture_ver": self.tracer.capture_version,
            }
            promoted = self.tracer.drain_promoted()
            if promoted:
                report["traces"] = promoted
            exemplars = self.tracer.exemplars()
            if exemplars:
                report["exemplars"] = exemplars
            try:
                conn = self.messenger.connect(
                    target[1], Policy.lossy_client()
                )
                conn.send_message(
                    Message(type="mgr_report", payload=report)
                )
            # cephlint: disable=error-taxonomy (mgr down/failover: rediscover next tick)
            except Exception:
                target = None  # force a mgr map refresh next tick

    async def _trim_removed_snaps(self) -> None:
        """SnapTrimmer: drop clones whose snap was deleted from the pool
        (PrimaryLogPG's SnapTrimmer machinery; removed_snaps is the
        OSDMap-carried work queue). Primaries trim their own PGs; the
        deletes replicate like any delete."""
        for (pool_id, ps), pg in list(self.pgs.items()):
            pool = self.osdmap.pools.get(pool_id)
            if pool is None or not pool.removed_snaps or not pg.active:
                continue
            acting, primary = self.acting_of(pool_id, ps)
            if primary != self.id:
                continue
            removed = set(pool.removed_snaps)
            for sname, entry in list(pg.latest_objects().items()):
                if entry["kind"] != "modify":
                    continue
                if "\x1f" in sname and not sname.endswith("snapdir"):
                    continue  # clones are trimmed via their snapset owner
                name = (
                    sname[: -len("\x1fsnapdir")]
                    if sname.endswith("\x1fsnapdir") else sname
                )
                is_snapdir = sname != name
                ss = load_snapset(self._head_xattrs(pg, acting, sname))
                doomed = [c for c in ss["clones"] if c in removed]
                if not doomed:
                    continue
                try:
                    async with pg.lock:
                        for c in doomed:
                            await self._primary_delete(
                                pg, acting, snap_store_name(name, c)
                            )
                        ss["clones"] = [
                            c for c in ss["clones"] if c not in removed
                        ]
                        for c in doomed:
                            ss["sizes"].pop(str(c), None)
                        if is_snapdir and not ss["clones"]:
                            # last clone gone: the snapdir evaporates
                            await self._primary_delete(pg, acting, sname)
                        else:
                            await self._primary_ops(
                                pg, acting, sname,
                                [{"op": "setxattr",
                                  "name": SNAPSET_XATTR,
                                  "value": json.dumps(
                                      ss
                                  ).encode().hex()}],
                                [], None,
                            )
                except (asyncio.CancelledError,):
                    raise
                # cephlint: disable=error-taxonomy (next map change retries)
                except Exception:
                    continue  # next map change retries

    # -- PG splitting (pool pg_num growth; PG::split_into) --------------------

    _OSD_META = "osd_meta"

    def _seen_pg_num(self, pool_id: int) -> int | None:
        raw = self.store.omap_get(self._OSD_META, ".meta").get(
            b"pgnum/%d" % pool_id
        )
        return int(raw) if raw else None

    def _maybe_split_pools(self) -> None:
        """Deterministic local split on pg_num growth: every member moves
        the objects whose stable-mod home changed into the child PG's
        collection with fresh child log entries; peering then reconciles
        the child's acting set (which may differ from the parent's). The
        watermark persists so a member that was down during the commit
        still splits on revival."""
        if not self.store.collection_exists(self._OSD_META):
            self.store.queue_transaction(
                Transaction().create_collection(self._OSD_META).touch(
                    self._OSD_META, ".meta"
                )
            )
        for pool_id, pool in self.osdmap.pools.items():
            seen = self._seen_pg_num(pool_id)
            if seen == pool.pg_num:
                continue  # the common no-change case: no store traffic
            if seen is not None and pool.pg_num > seen:
                self._split_pool(pool_id, seen, pool.pg_num)
            self.store.queue_transaction(
                Transaction().omap_setkeys(
                    self._OSD_META, ".meta",
                    {b"pgnum/%d" % pool_id: str(pool.pg_num).encode()},
                )
            )

    def _split_pool(self, pool_id: int, old_n: int, new_n: int) -> None:
        from ceph_tpu.common.hash import ceph_str_hash_rjenkins

        pool = self.osdmap.pools[pool_id]
        for ps in range(old_n):
            coll = pg_coll(pool_id, ps)
            if not self.store.collection_exists(coll):
                continue
            parent = self._pg_of((pool_id, ps))
            moves: dict[int, list[dict]] = {}
            for name, entry in sorted(parent.latest_objects().items()):
                newps = pool.raw_pg_to_pg(ceph_str_hash_rjenkins(name))
                if newps != ps:
                    moves.setdefault(newps, []).append(entry)
            if not moves:
                continue
            # store names per logical name (plain, .sN shards)
            by_logical: dict[str, list[str]] = {}
            for sname in self.store.list_objects(coll):
                if sname == parent.META:
                    continue
                logical = sname
                base, sep, tail = sname.rpartition(".s")
                if sep and tail.isdigit():
                    logical = base
                by_logical.setdefault(logical, []).append(sname)
            moved_names = set()
            for newps, entries in sorted(moves.items()):
                child = self._pg_of((pool_id, newps))
                txn = Transaction()
                for e in sorted(entries, key=lambda x: x["name"]):
                    moved_names.add(e["name"])
                    for sname in by_logical.get(e["name"], []):
                        try:
                            data = self.store.read(coll, sname)
                            attrs = self.store.getattrs(coll, sname)
                        except StoreError:
                            continue
                        txn.write(child.coll, sname, data, attrs=attrs)
                        omap = self.store.omap_get(coll, sname)
                        if omap:
                            txn.omap_setkeys(child.coll, sname, omap)
                        txn.remove(coll, sname)
                    child.append_log(
                        txn,
                        {**e, "version": child.last_update + 1,
                         "epoch": self.osdmap.epoch},
                    )
                self.store.queue_transaction(txn)
            # drop the moved names from the parent's inventory AND its
            # retained log, or recovery would try to resurrect them
            txn = Transaction()
            rm_keys = [
                b"obj/" + n.encode() for n in moved_names
            ]
            for le in parent.log_entries(0):
                if le["name"] in moved_names:
                    rm_keys.append(b"log/%016x" % le["version"])
                    parent._log.pop(le["version"], None)
            txn.omap_rmkeys(coll, parent.META, rm_keys)
            for n in moved_names:
                parent._inventory.pop(n, None)
            self.store.queue_transaction(txn)
            if (d := self.dlog.dout(1)) is not None:
                d(f"split pg {pool_id}.{ps}: moved "
                  f"{len(moved_names)} objects across {len(moves)} "
                  f"children (pg_num {old_n} -> {new_n})")

    async def _peer_and_recover(self, pg: PG, acting: list[int]) -> bool:
        """GetInfo -> GetLog -> GetMissing -> push/backfill, one pass.
        True only when the PG is known complete (safe to go active).

        Info is collected from acting members AND every other up OSD: a
        remap (cluster expansion, failed host) can hand the whole acting
        set to newcomers, leaving the authoritative log only on strays.

        Authority is the max HEAD EVERSION (epoch, version) — the
        reference's eversion ordering, which makes a new reign's entries
        outrank a dead primary's divergent same-numbered tail. A member
        whose log cannot be bridged (behind the tail, or divergent) gets
        a full backfill instead of log recovery."""
        members = [o for o in acting if o != _NONE and o != self.id]
        infos: dict[int, dict] = {
            self.id: {"last_update": pg.last_update,
                      "head": list(pg.head), "tail": pg.log_tail}
        }
        for osd in set(members) | set(self._up_peers()):
            try:
                rep = await self._peer_call(
                    osd, "pg_info", {"pgid": [pg.pool, pg.ps]},
                    timeout=2.0,
                )
                infos[osd] = rep
            except (asyncio.TimeoutError, RuntimeError):
                continue
        # past-intervals gate (PeeringState::build_prior): any interval
        # since our last activation that could have served writes must
        # have at least one member among the peers we actually reached —
        # else an unreached member may hold acked writes we cannot see,
        # and going active would serve (and later un-serve) stale state
        intervals = await self._pg_history(pg)
        if intervals is None:
            return False  # no map history without a mon quorum: wait
        # the CURRENT interval's start epoch (same_interval_since): the
        # up_thru value activation must confirm. Taken from the mon's
        # interval archive, NOT from when this daemon first noticed the
        # interval — a first-seen epoch would ratchet with every
        # up_thru commit and a mass PG split would cascade epochs
        pg.up_thru_need = intervals[-1][0] if intervals else (
            self.osdmap.epoch
        )
        pool = self.osdmap.pools[pg.pool]
        contacted = set(infos)
        for interval in intervals:
            _epoch, acting_h, primary_h = interval[:3]
            # interval-accurate prior set (PastIntervals maybe_went_rw,
            # osd_types.h:3030): a closed interval whose primary never
            # committed up_thru inside it cannot hold acked writes —
            # skip it instead of blocking on its unreachable members
            rw = interval[3] if len(interval) > 3 else True
            if not rw:
                continue
            live = [o for o in acting_h if o != _NONE]
            if primary_h in (-1, _NONE) or len(live) < pool.min_size:
                continue  # could not have gone active
            if not (set(live) & contacted):
                return False
        best_osd = max(
            infos,
            key=lambda o: (tuple(infos[o]["head"]), o == self.id),
        )
        ok = True
        if tuple(infos[best_osd]["head"]) > pg.head:
            ok = await self._pull_from_authority(
                pg, best_osd, infos[best_osd], acting
            )
        member_infos = {
            o: v for o, v in infos.items() if o in members or o == self.id
        }
        pushed = await self._push_missing(pg, acting, member_infos)
        return ok and pushed

    async def _ensure_up_thru(self, need: int) -> bool:
        """Alive-confirmation gate (OSD::send_alive -> OSDMonitor::
        prepare_alive): True once the committed map's up_thru for this
        daemon reaches `need` (the first epoch we saw the activating
        interval). Serving writes before the commit would create an
        interval that future peering — which skips !maybe_went_rw
        intervals — could not know to consult."""
        m = self.osdmap
        if self.id < m.max_osd and int(m.osd_up_thru[self.id]) >= need:
            return True
        if self._up_thru_requested >= need:
            # commit in flight; the committed inc will dirty the map and
            # re-run this pass
            return False
        self._up_thru_requested = need
        try:
            rep = await self.mon.command(
                "osd up-thru", {"osd": self.id, "epoch": need},
                timeout=5.0,
            )
            return int(rep.get("up_thru", 0)) >= need
        # cephlint: disable=error-taxonomy (mon churn: clear the request so the next pass re-asks)
        except Exception:
            self._up_thru_requested = 0  # mon churn: re-request
            return False

    async def _pg_history(self, pg: PG):
        """Past intervals for `pg`, fetched in ONE bulk mon command per
        map epoch for every local PG and memoized (per-PG commands from
        the whole fleet each epoch would swamp the mon and the loop)."""
        epoch = self.osdmap.epoch
        key = (pg.pool, pg.ps)
        if self._hist_cache_epoch != epoch or key not in self._hist_cache:
            queries = {
                f"{p}.{s}": self.pgs[(p, s)].les
                for (p, s) in self.pgs
            }
            queries[f"{pg.pool}.{pg.ps}"] = pg.les
            try:
                rep = await self.mon.command(
                    "pg history", {"queries": queries}, timeout=8.0
                )
            # cephlint: disable=error-taxonomy (mon churn: peering retries without the history cache)
            except Exception:
                return None
            self._hist_cache = {
                tuple(int(x) for x in pgid.split(".")): iv
                for pgid, iv in rep["histories"].items()
            }
            self._hist_cache_epoch = epoch
        return self._hist_cache.get(key, [])

    def _needs_backfill(self, pg: PG, info: dict) -> bool:
        """Log recovery can bridge a peer only when its head is an
        ancestor of ours: same entry at its head version, and within our
        retained log (PGLog::merge_log's fallback-to-backfill rule)."""
        head = tuple(info["head"])
        if head == tuple(pg.head):
            return False
        if head == (0, 0):
            # empty peer: log-bridgeable only if our log reaches back to 0
            return pg.log_tail > 0
        if head[1] > pg.last_update or head[1] <= pg.log_tail:
            return True
        mine = pg.entry_at(head[1])
        return mine is None or (
            mine.get("epoch", 0), mine["version"]
        ) != head

    async def _pull_from_authority(
        self, pg: PG, source: int, source_info: dict, acting: list[int]
    ) -> bool:
        """Catch ourselves up from the authoritative holder: log pull when
        bridgeable, else backfill ourselves from its inventory."""
        rep = await self._peer_call(
            source, "pg_log",
            {"pgid": [pg.pool, pg.ps], "from": pg.last_update,
             "head": list(pg.head)},
        )
        if rep.get("bridgeable"):
            return await self._apply_log_entries(
                pg, rep["entries"], acting
            )
        return await self._backfill_self(pg, source, acting)

    async def _apply_log_entries(
        self, pg: PG, entries: list[dict], acting: list[int]
    ) -> bool:
        """Adopt a more advanced holder's log tail (GetLog + pull). Aborts
        at the first entry whose data is unreachable: appending later
        entries past a gap would advance last_update and silently orphan
        the skipped one forever.

        The pulls run as ONE bounded-concurrency batch up front (the
        batched recovery engine): the sub-op reads coalesce into
        subop_batch frames and concurrent EC rebuilds share decode
        launches, then the log entries apply strictly in order against
        the pulled results — the gap-abort contract is unchanged."""
        my_shard = self._my_shard(pg, acting)
        newest: dict[str, dict] = {}
        for e in entries:
            newest[e["name"]] = e
        need = [
            e for e in entries
            if e["kind"] != "delete"
            and newest[e["name"]]["version"] == e["version"]
        ]
        results = await self._recovery_gather(
            self._pull_object(pg, e["name"], my_shard, acting, e)
            for e in need
        )
        pulled = {
            (e["name"], e["version"]): got
            for e, got in zip(need, results)
        }
        for e in entries:
            txn = Transaction()
            if e["kind"] == "delete":
                txn.remove(pg.coll, shard_name(e["name"], my_shard))
            elif newest[e["name"]]["version"] != e["version"]:
                pass  # superseded within this pull: newest entry has it
            else:
                got = pulled.get((e["name"], e["version"]))
                if got is None:
                    return False  # retry the whole tail next pass
                data, attrs = got
                self._write_fetched(
                    txn, pg.coll, shard_name(e["name"], my_shard),
                    data, attrs,
                )
            pg.append_log(txn, e)
            self.store.queue_transaction(txn)
            self.perf.inc("recovery_pulls")
        return True

    async def _recovery_gather(self, coros) -> list:
        """Run recovery fetches concurrently, bounded by
        `osd_recovery_batch_max` (the reference's osd_recovery_max_active
        window): results come back in submission order, a failed fetch
        becomes None (recovery call sites already treat None as
        retry-next-pass). Concurrency is what lets the per-peer sub-op
        coalescer fold the reads into batch frames and the EncodeService
        fold the EC rebuilds into shared decode launches."""
        limit = max(1, int(self.config.get("osd_recovery_batch_max")))
        sem = asyncio.Semaphore(limit)

        async def run(c):
            async with sem:
                try:
                    return await c
                except (asyncio.TimeoutError, RuntimeError):
                    return None

        return await asyncio.gather(*(run(c) for c in coros))

    def _local_logical_names(self, pg: PG) -> dict[str, str]:
        """logical object name -> store name for our copies/shards."""
        out = {}
        for sname in self.store.list_objects(pg.coll):
            if sname == pg.META:
                continue
            logical = sname
            # strip a shard suffix (EC layout folds shard id in the key)
            base, sep, tail = sname.rpartition(".s")
            if sep and tail.isdigit():
                logical = base
            out[logical] = sname
        return out

    async def _backfill_self(
        self, pg: PG, source: int, acting: list[int]
    ) -> bool:
        """Resync FROM the authority (recover_backfill pulling): adopt
        its inventory + log head NOW — dropping local strays — and let
        the PG activate immediately; the object DATA heals in the
        background (_drain_self_backfill). An amnesiac primary can serve
        the moment it knows WHAT exists: EC reads decode around the
        missing local shard, replicated reads fall back to peer copies,
        and new writes land fresh locally. Blocking the whole PG behind
        a full self-pull was the availability hole the thrasher kept
        finding (the reference's answer is PastIntervals + pg_temp: a
        complete member serves while the newcomer backfills)."""
        try:
            rep = await self._peer_call(
                source, "pg_inventory", {"pgid": [pg.pool, pg.ps]},
                timeout=10.0,
            )
        except (asyncio.TimeoutError, RuntimeError):
            return False
        inventory = rep["inventory"]
        txn = Transaction()
        for logical, sname in self._local_logical_names(pg).items():
            e = inventory.get(logical)
            if e is None or e["kind"] == "delete":
                txn.remove(pg.coll, sname)
        pg.reset_log(
            txn, inventory, tuple(rep["head"]), rep["tail"]
        )
        self.store.queue_transaction(txn)
        pg.self_backfill = True
        return True

    async def _drain_self_backfill(self, pg: PG) -> None:
        """Pull our own missing/stale copies/shards back while serving
        (the puller half of async backfill). Each landed object is
        version-gated against concurrent client writes: a pull result
        older than what a write just stored locally is dropped — the
        next sweep sees the newer inventory entry already satisfied."""
        while pg.self_backfill and not self._stopped:
            acting, primary = self.acting_of(pg.pool, pg.ps)
            if primary != self.id or not pg.active:
                return
            my = self._my_shard(pg, acting)
            missing = 0
            work: list[tuple[str, str, dict]] = []
            for name, e in sorted(pg.latest_objects().items()):
                if e["kind"] == "delete":
                    continue
                sname = shard_name(name, my)
                try:
                    if (
                        self.store.getattrs(pg.coll, sname).get("ver")
                        == e["obj_ver"]
                    ):
                        continue
                except StoreError:
                    pass
                work.append((name, sname, e))
            # one bounded-concurrency batch per sweep: the pulls
            # coalesce into subop_batch frames / shared decode launches
            results = await self._recovery_gather(
                self._pull_object(pg, name, my, acting, e)
                for name, _sname, e in work
            )
            for (name, sname, e), got in zip(work, results):
                cur = pg.latest_objects().get(name)
                if got is None or cur is None:
                    missing += 1
                    continue
                if cur["obj_ver"] != e["obj_ver"]:
                    missing += 1  # advanced mid-pull: next sweep
                    continue
                try:
                    local_ver = self.store.getattrs(
                        pg.coll, sname
                    ).get("ver") or 0
                except StoreError:
                    local_ver = 0
                if local_ver == cur["obj_ver"]:
                    continue  # a concurrent write healed it for us
                # any other local version — including a HIGHER one from
                # a divergent past reign — is stale; overwrite it
                txn = Transaction()
                self._write_fetched(txn, pg.coll, sname, got[0], got[1])
                self.store.queue_transaction(txn)
                self.perf.inc("recovery_pulls")
            if missing == 0:
                # one re-check pass: anything written mid-sweep has a
                # fresh local copy already (writes apply locally too)
                pg.self_backfill = False
                if (d := self.dlog.dout(5)) is not None:
                    d(f"pg {pg.pool}.{pg.ps} self-backfill complete")
                return
            await asyncio.sleep(0.2)

    def _write_fetched(
        self, txn: Transaction, coll: str, sname: str, data: bytes,
        attrs: dict,
    ) -> None:
        """Store a recovered copy/shard, applying the _omap rider as real
        omap rows (replacing any stale local ones). The hinfo digest for
        THIS position is recomputed from the bytes being stored: attrs
        travel from whichever shard sourced the recovery, and after
        sub-stripe overwrites each shard's hinfo is only authoritative
        for its own position."""
        attrs = dict(attrs)
        omap_hex = attrs.pop("_omap", None)
        hinfo = attrs.get("hinfo")
        if hinfo is not None:
            base, sep, tail = sname.rpartition(".s")
            if sep and tail.isdigit():
                pos = int(tail)
                hashes = list(hinfo.cumulative_shard_hashes)
                if pos < len(hashes):
                    hashes[pos] = ceph_crc32c(SEED, data)
                attrs["hinfo"] = HashInfo(len(data), hashes)
        txn.write(coll, sname, data, attrs=attrs)
        if omap_hex:
            existing = self.store.omap_get(coll, sname)
            if existing:
                txn.omap_rmkeys(coll, sname, list(existing))
            txn.omap_setkeys(
                coll, sname,
                {bytes.fromhex(k): bytes.fromhex(v)
                 for k, v in omap_hex.items()},
            )

    def _my_shard(self, pg: PG, acting: list[int]) -> int | None:
        if self.codec(pg.pool) is None:
            return None
        try:
            return acting.index(self.id)
        except ValueError:
            return None

    def _up_peers(self) -> list[int]:
        m = self.osdmap
        return [
            o for o in sorted(m.osd_addrs)
            if o != self.id and o < m.max_osd and not m.is_down(o)
        ]

    def _holders_for(self, acting: list[int], pos: int | None) -> list[int]:
        """Candidate holders of a copy/shard: the acting home first, then
        every other up OSD — after a remap the surviving data lives on
        previous-interval STRAYS, which is exactly what the reference's
        MissingLoc tracks (src/osd/MissingLoc.cc). Includes self (local
        store) since we may hold stray shards of other positions."""
        out = []
        if pos is not None and pos < len(acting):
            home = acting[pos]
            if home != _NONE and not self.osdmap.is_down(home):
                out.append(home)
        if self.id not in out:
            out.append(self.id)
        acting_set = set(acting)
        out.extend(
            o for o in self._up_peers()
            if o not in acting_set and o not in out
        )
        # remaining acting members too (replicated: any member has a copy)
        out.extend(
            o for o in acting
            if o not in (_NONE, *out) and not self.osdmap.is_down(o)
        )
        return out

    async def _fetch_copy(self, pg: PG, sname: str, ver: int, candidates):
        """First current-version (data, attrs) among candidates, or None.
        attrs may carry an "_omap" rider: the object's user omap travels
        with its data during recovery (hex kv; applied, never stored as an
        attr)."""
        for osd in candidates:
            if osd == self.id:
                try:
                    data = self.store.read(pg.coll, sname)
                    attrs = self.store.getattrs(pg.coll, sname)
                except StoreError:
                    continue
                if attrs.get("ver") == ver:
                    omap = self.store.omap_get(pg.coll, sname)
                    if omap:
                        attrs = dict(attrs)
                        attrs["_omap"] = {
                            k.hex(): v.hex() for k, v in omap.items()
                        }
                    return data, attrs
                continue
            try:
                # recovery-tagged + batchable: concurrent pulls to the
                # same peer fold into one subop_batch frame, and the
                # receiver admits the read under the mclock recovery
                # class instead of the client default
                rep = await self._peer_call(
                    osd, "obj_read",
                    {"coll": pg.coll, "name": sname, "ver": ver,
                     "qos": QOS_RECOVERY},
                    timeout=2.0, batchable=True,
                )
            except (asyncio.TimeoutError, RuntimeError):
                continue
            if rep.get("ok"):
                return rep["_raw"], _attrs_from(rep)
        return None

    async def _rebuild_shard_subchunks(
        self, pg: PG, name: str, shard: int, acting: list[int], ver: int,
        exclude: int | None,
    ):
        """Fractional repair over the wire (the CLAY contract): fetch
        ONLY the repair sub-chunk runs minimum_to_decode names from the
        d helper shards at their acting homes — d*(1/q) of the data a
        whole-shard rebuild would move (ErasureCodeClay::minimum_to_decode,
        src/erasure-code/clay/ErasureCodeClay.cc:304+, read via the
        ECSubRead sub-extent shape, src/osd/ECBackend.cc:1605). Returns
        (bytes, attrs) or None to fall back to the whole-shard path
        (helpers missing at acting homes, or no fractional saving)."""
        ec = self.codec(pg.pool)
        sub = ec.get_sub_chunk_count()
        avail = set()
        for pos, osd in enumerate(acting):
            if (
                pos == shard or osd in (_NONE, exclude)
                or self.osdmap.is_down(osd)
                or osd in pg.backfill_targets
            ):
                continue
            avail.add(pos)
        try:
            minimum = ec.minimum_to_decode({shard}, avail)
        # cephlint: disable=error-taxonomy (unrecoverable with current shards: caller takes full recovery)
        except Exception:
            return None
        if all(
            list(runs) == [(0, sub)] for runs in minimum.values()
        ):
            return None  # whole-shard reads anyway: use the plain path
        chunks: dict[int, bytes] = {}
        attrs = cs = None
        for pos, runs in sorted(minimum.items()):
            osd = acting[pos]
            sname = shard_name(name, pos)
            if osd == self.id:
                try:
                    a = self.store.getattrs(pg.coll, sname)
                    data = self.store.read(pg.coll, sname)
                except StoreError:
                    return None
                if a.get("ver") != ver:
                    return None
                cs = len(data)
                unit = cs // sub
                raw = b"".join(
                    data[o * unit: (o + c) * unit] for o, c in runs
                )
            else:
                if cs is None:
                    # one attrs-only probe tells us the object size and
                    # therefore the shard/sub-chunk geometry
                    try:
                        probe = await self._peer_call(
                            osd, "obj_read",
                            {"coll": pg.coll, "name": sname,
                             "ver": ver, "runs": [],
                             "qos": QOS_RECOVERY},
                            timeout=2.0, batchable=True,
                        )
                    except (asyncio.TimeoutError, RuntimeError):
                        return None
                    size = (
                        _attrs_from(probe).get("size")
                        if probe.get("ok") else None
                    )
                    if not size:
                        return None
                    cs = ec.get_chunk_size(size)
                unit = cs // sub
                try:
                    rep = await self._peer_call(
                        osd, "obj_read",
                        {"coll": pg.coll, "name": sname, "ver": ver,
                         "runs": [[o * unit, c * unit]
                                  for o, c in runs],
                         "qos": QOS_RECOVERY},
                        timeout=2.0, batchable=True,
                    )
                except (asyncio.TimeoutError, RuntimeError):
                    return None
                if not rep.get("ok"):
                    return None
                raw = rep["_raw"]
                a = _attrs_from(rep)
            chunks[pos] = raw
            attrs = attrs or a
            self.perf.inc("recovery_sub_bytes", len(raw))
        try:
            rebuilt = ec.decode({shard}, chunks, chunk_size=cs)[shard]
        # cephlint: disable=error-taxonomy (decode failed: caller falls back to full-object recovery)
        except Exception:
            return None
        return rebuilt, attrs

    async def _rebuild_shard(
        self, pg: PG, name: str, shard: int, acting: list[int], ver: int,
        exclude: int | None = None,
    ):
        """Decode shard `shard` from current-version source shards found at
        acting homes or strays (RecoveryOp READING with MissingLoc)."""
        ec = self.codec(pg.pool)
        if ec.get_sub_chunk_count() > 1:
            got = await self._rebuild_shard_subchunks(
                pg, name, shard, acting, ver, exclude
            )
            if got is not None:
                return got
        chunks: dict[int, bytes] = {}
        attrs = None
        k = ec.get_data_chunk_count()

        async def fetch(pos: int):
            cands = [
                o for o in self._holders_for(acting, pos) if o != exclude
            ]
            return await self._fetch_copy(
                pg, shard_name(name, pos), ver, cands
            )

        # fetch the first k source positions concurrently (every rebuild
        # of this stripe geometry picks the SAME lowest positions, so
        # concurrent rebuilds share a (present, targets) signature and
        # coalesce below), topping up serially only past failures
        positions = [p for p in range(len(acting)) if p != shard]
        first = positions[:k]
        for pos, got in zip(first, await asyncio.gather(
            *(fetch(p) for p in first)
        )):
            if got is not None:
                chunks[pos] = got[0]
                attrs = attrs or got[1]
        for pos in positions[k:]:
            if len(chunks) >= k:
                break
            got = await fetch(pos)
            if got is not None:
                chunks[pos] = got[0]
                attrs = attrs or got[1]
        if len(chunks) < k:
            return None
        # decode through the batch service: concurrent rebuilds (a
        # batched recovery pass pulls many objects at once) sharing a
        # source signature fuse into ONE decode launch across objects
        try:
            out = await self.encode_service.decode(ec, {shard}, chunks)
        # cephlint: disable=error-taxonomy (decode failed: caller treats the object as unrecoverable this pass)
        except Exception:
            return None
        return out[shard], attrs

    async def _pull_object(
        self, pg: PG, name: str, shard: int | None, acting: list[int], entry
    ):
        """Fetch our copy/shard: direct from any holder (acting or stray),
        else (EC) rebuild by decoding (RecoveryOp READING)."""
        cands = [
            o for o in self._holders_for(acting, shard) if o != self.id
        ]
        got = await self._fetch_copy(
            pg, shard_name(name, shard), entry["obj_ver"], cands
        )
        if got is not None:
            return got
        ec = self.codec(pg.pool)
        if ec is None or shard is None:
            return None
        return await self._rebuild_shard(
            pg, name, shard, acting, entry["obj_ver"]
        )

    async def _push_missing(
        self, pg: PG, acting: list[int], infos: dict[int, dict]
    ) -> bool:
        """Push log entries + object data to every laggard member; a
        member whose log can't be bridged becomes a BACKFILL TARGET
        instead of blocking here — the PG activates without it and the
        background drain resyncs it (async backfill: the reference goes
        Active with backfill_targets excluded from acting-set service
        rather than wedging client IO behind a full resync). True when
        every non-target member is known complete."""
        inventory = pg.latest_objects()
        ec = self.codec(pg.pool)
        complete = True
        targets: set[int] = set()
        for pos, osd in enumerate(acting):
            if osd in (self.id, _NONE) or self.osdmap.is_down(osd):
                continue
            info = infos.get(osd)
            if info is None:
                complete = False  # unreachable member: state unknown
                continue
            if tuple(info["head"]) > tuple(pg.head):
                # the member OUTRANKS us (we failed to pull from the
                # authority this pass): never push — a backfill here
                # would wipe the only copy of acked writes. Stay
                # incomplete; the next pass pulls first.
                complete = False
                continue
            shard = pos if ec is not None else None
            if self._needs_backfill(pg, info):
                targets.add(osd)
                continue
            since = info["last_update"]
            if since >= pg.last_update:
                continue

            async def resolve(e, _shard=shard):
                latest = inventory.get(e["name"])
                if (
                    latest is None
                    or latest["version"] != e["version"]
                    or e["kind"] == "delete"
                ):
                    # superseded entry: the newest one carries the data
                    return {"entry": e, "has_data": False}, b""
                got = await self._object_for_push(
                    pg, e, _shard, acting
                )
                if got is None:
                    return None  # sources unavailable right now
                raw, attrs = got
                return {
                    "entry": e,
                    "has_data": True,
                    "attrs": _attrs_to(attrs),
                }, raw

            _acked, ok = await self._push_batches(
                pg, osd, shard, list(pg.log_entries(since)), resolve
            )
            if not ok:
                complete = False  # next pass retries this member
        pg.backfill_targets = targets
        return complete

    async def _push_batches(
        self, pg: PG, osd: int, shard: int | None, entries: list,
        resolve, skip_unresolved: bool = True,
    ) -> tuple[list, bool]:
        """Ship recovery pushes to `osd` as ordered obj_push_batch
        frames of up to `osd_recovery_batch_max` items: payloads resolve
        concurrently (fetches/rebuilds coalesce), then one frame and one
        ack move the whole batch instead of a round trip per object.
        Returns (entries acked, everything resolved AND acked). An
        unresolvable payload is skipped (`skip_unresolved`) or aborts
        the remaining batches — either way the result reads incomplete.
        Batches to a member go strictly one at a time: the receiver's
        admission queue must never reorder two in-flight batches, or
        log versions would land out of order and leave holes."""
        limit = max(1, int(self.config.get("osd_recovery_batch_max")))
        acked: list = []
        ok = True
        for i in range(0, len(entries), limit):
            group = entries[i:i + limit]
            payloads = await self._recovery_gather(
                resolve(e) for e in group
            )
            items: list[dict] = []
            raws: list[bytes] = []
            for got in payloads:
                if got is None:
                    ok = False
                    if not skip_unresolved:
                        return acked, False
                    continue
                payload, raw = got
                payload = dict(payload)
                payload["raw_len"] = len(raw)
                items.append(payload)
                raws.append(raw)
            if not items:
                continue
            try:
                rep = await self._peer_call(
                    osd, "obj_push_batch",
                    {"pgid": [pg.pool, pg.ps], "shard": shard,
                     "items": items, "qos": QOS_RECOVERY},
                    timeout=10.0, raw=b"".join(raws),
                )
            except (asyncio.TimeoutError, RuntimeError):
                return acked, False
            if not rep.get("ok"):
                return acked, False
            acked.extend(it["entry"] for it in items)
            self.perf.inc("recovery_pushes", len(items))
        return acked, ok

    async def _drain_backfill(self, pg: PG) -> None:
        """Background backfill of this PG's targets — concurrently,
        bounded by the osd_max_backfills semaphore — while the PG
        serves client IO (recover_backfill running under the Active
        state). Ends when no targets remain or primaryship moves (the
        next peering pass re-evaluates)."""
        while pg.backfill_targets and not self._stopped:
            acting, primary = self.acting_of(pg.pool, pg.ps)
            if primary != self.id or not pg.active:
                return
            ec = self.codec(pg.pool)
            progressed = False
            live: list[int] = []
            for osd in sorted(pg.backfill_targets):
                if osd not in acting or self.osdmap.is_down(osd):
                    pg.backfill_targets.discard(osd)
                    progressed = True
                    continue
                live.append(osd)

            async def drain_one(osd: int) -> bool:
                shard = acting.index(osd) if ec is not None else None
                return await self._backfill_member(
                    pg, acting, osd, shard
                )

            done = await asyncio.gather(*(drain_one(o) for o in live))
            for osd, finished in zip(live, done):
                if finished:
                    pg.backfill_targets.discard(osd)
                    progressed = True
                    if (d := self.dlog.dout(5)) is not None:
                        d(f"pg {pg.pool}.{pg.ps} backfill of osd.{osd} "
                          "complete")
            if progressed:
                # the backfill set shrank: refresh the replicas' marker
                # so the drained member becomes a balanced-read target
                self._spawn(self._broadcast_activate(pg, acting))
            else:
                await asyncio.sleep(0.2)

    async def _backfill_member(
        self, pg: PG, acting: list[int], osd: int, shard: int | None
    ) -> bool:
        """Full resync TO a member whose log we can't bridge: push every
        live object at its current version, then hand it our inventory +
        head so it drops strays and restarts its log (recover_backfill +
        the reservation throttle, PeeringState WaitRemoteBackfillReserved:
        osd_max_backfills bounds concurrent backfills we source).

        Runs while the PG serves writes (the target takes no write
        sub-ops meanwhile): unlocked convergence passes push the moving
        inventory until a pass finds nothing new, then one final pass
        under the PG lock quiesces writes for the (tiny) residue and the
        inventory/head handoff — the backfill-finish-under-lock step."""
        async with self._backfill_sem:
            pushed: dict[str, int] = {}

            async def resolve(e):
                if e["kind"] == "delete":
                    return {"entry": e, "has_data": False}, b""
                got = await self._object_for_push(pg, e, shard, acting)
                if got is None:
                    return None
                raw, attrs = got
                return {"entry": e, "has_data": True, "force": True,
                        "attrs": _attrs_to(attrs)}, raw

            async def push_diff() -> int | None:
                work = [
                    e for name, e in sorted(pg.latest_objects().items())
                    if pushed.get(name) != e["version"]
                ]
                acked, ok = await self._push_batches(
                    pg, osd, shard, work, resolve,
                    skip_unresolved=False,
                )
                for e in acked:
                    pushed[e["name"]] = e["version"]
                if not ok:
                    return None
                return len(work)

            for _pass in range(5):
                n = await push_diff()
                if n is None:
                    return False
                if n == 0:
                    break  # converged against the live inventory
            # under SUSTAINED writes unlocked passes may never find an
            # empty diff — after the pass cap, quiesce and finish: the
            # locked pass is correct for any residue, just holds the
            # lock proportionally longer
            async with pg.lock:
                if await push_diff() is None:
                    return False
                try:
                    await self._peer_call(
                        osd, "pg_backfill_done",
                        {"pgid": [pg.pool, pg.ps],
                         "inventory": pg.latest_objects(),
                         "head": list(pg.head), "tail": pg.log_tail},
                        timeout=10.0,
                    )
                except (asyncio.TimeoutError, RuntimeError):
                    return False
            return True

    async def _object_for_push(
        self, pg: PG, entry: dict, shard: int | None, acting: list[int]
    ):
        """Data for the target's copy/shard: our own copy when we hold it
        at the right version, else fetched/rebuilt from acting + stray
        holders."""
        ver = entry["obj_ver"]
        my = self._my_shard(pg, acting)
        ec = self.codec(pg.pool)
        if ec is None or shard == my:
            sname = shard_name(entry["name"], my if ec is not None else None)
            got = await self._fetch_copy(
                pg, sname, ver, self._holders_for(acting, my)
            )
            return got
        return await self._rebuild_shard(
            pg, entry["name"], shard, acting, ver
        )

    # -- peer sub-op servers --------------------------------------------------

    async def _h_pg_info(self, conn, p) -> None:
        pg = self._pg_of(p["pgid"])
        self._reply_peer(
            conn, p["tid"],
            {"last_update": pg.last_update, "head": list(pg.head),
             "tail": pg.log_tail},
        )

    async def _h_pg_log(self, conn, p) -> None:
        """Log tail for a puller; `bridgeable` is false when the puller's
        head is not an ancestor of ours (divergent or behind our tail) —
        it must backfill instead (merge_log's divergence rule)."""
        pg = self._pg_of(p["pgid"])
        frm = p.get("from", 0)
        bridgeable = frm >= pg.log_tail
        if bridgeable and p.get("head") is not None:
            head = tuple(p["head"])
            if head != (0, 0) and head != tuple(pg.head):
                mine = pg.entry_at(head[1])
                if mine is None or (
                    mine.get("epoch", 0), mine["version"]
                ) != head:
                    bridgeable = False
        self._reply_peer(
            conn, p["tid"],
            {"entries": pg.log_entries(frm) if bridgeable else [],
             "bridgeable": bridgeable, "tail": pg.log_tail},
        )

    async def _h_pg_inventory(self, conn, p) -> None:
        pg = self._pg_of(p["pgid"])
        self._reply_peer(
            conn, p["tid"],
            {"inventory": pg.latest_objects(), "head": list(pg.head),
             "tail": pg.log_tail},
        )

    async def _h_pg_backfill_done(self, conn, p) -> None:
        self._enqueue_subop(p, self._do_pg_backfill_done, conn)

    async def _do_pg_backfill_done(self, conn, p) -> None:
        """Backfill epilogue at the target: adopt the authority's
        inventory/head, drop strays (objects it no longer has)."""
        pg = self._pg_of(p["pgid"])
        async with pg.lock:
            if tuple(p["head"]) < pg.head:
                # a stale reign's backfill must never wipe newer state
                self._reply_peer(
                    conn, p["tid"], {"ok": False, "stale": True}
                )
                return
            inventory = p["inventory"]
            txn = Transaction()
            for logical, sname in self._local_logical_names(pg).items():
                e = inventory.get(logical)
                if e is None or e["kind"] == "delete":
                    txn.remove(pg.coll, sname)
            pg.reset_log(
                txn, inventory, tuple(p["head"]), p["tail"]
            )
            self.store.queue_transaction(txn)
        self._reply_peer(conn, p["tid"], {"ok": True})

    def _admit_recovery(self, conn, p, fn) -> bool:
        """Recovery-class admission: a sub-op tagged `qos: recovery`
        takes a detour through the sharded op queue under the mclock
        recovery profile before its handler runs — client ops keep
        their weight share against a recovery storm, and the recovery
        reservation keeps healing off zero under client storms. Returns
        True when the op was queued (caller returns; the shard worker
        re-enters `fn` with the admission marker set). Gating here in
        the handler (not ms_dispatch) covers batch-inner sub-ops too —
        _h_subop_batch calls handlers directly."""
        if p.get("qos") != QOS_RECOVERY or p.pop("_admitted", False):
            return False
        p["_admitted"] = True
        p["_rfn"] = fn
        key = str(p.get("name") or p.get("pgid"))
        shard = self._op_shards[
            zlib.crc32(key.encode()) % len(self._op_shards)
        ]
        shard.queue.enqueue(
            63,
            max(1, len(p.get("_raw") or b"") // 4096),
            (conn, p),
            klass=QOS_RECOVERY,
        )
        shard.kick.set()
        return True

    async def _h_obj_read(self, conn, p) -> None:
        """handle_sub_read: local read (+ version check when asked).
        `runs` = [[off,len],...] requests sub-extent ranges only — the
        ECSubRead (offset,count) shape (src/osd/ECMsgTypes.h to_read)
        that sub-stripe RMW reads and CLAY fractional repairs ride."""
        if self._admit_recovery(conn, p, self._h_obj_read):
            return
        reader = self.store.read
        if p.get("verify"):
            # deep-scrub fetch: read device truth, not the buffer cache
            reader = getattr(self.store, "read_verify", reader)
        try:
            data = reader(p["coll"], p["name"])
            attrs = self.store.getattrs(p["coll"], p["name"])
        except StoreError as e:
            # carry the errno so the scrubbing primary can tell at-rest
            # corruption (EIO -> read_error) from an absent copy
            self._reply_peer(
                conn, p["tid"], {"ok": False, "error": e.code}
            )
            return
        if p.get("ver") is not None and attrs.get("ver") != p["ver"]:
            self._reply_peer(conn, p["tid"], {"ok": False, "stale": True})
            return
        if p.get("runs") is not None:
            data = b"".join(
                data[off: off + ln] for off, ln in p["runs"]
            )
        attrs_out = _attrs_to(attrs)
        omap = self.store.omap_get(p["coll"], p["name"])
        if omap:
            attrs_out["_omap"] = {
                k.hex(): v.hex() for k, v in omap.items()
            }
        self._reply_peer(
            conn, p["tid"],
            {"ok": True, "attrs": attrs_out}, raw=data,
        )

    async def _h_obj_push(self, conn, p) -> None:
        self._enqueue_subop(p, self._do_obj_push, conn)

    async def _do_obj_push(self, conn, p) -> None:
        """Recovery push: store the object/shard + its log entry. The
        data write is version-gated: a backfill/recovery push must never
        regress a copy that a concurrent client write already advanced
        past the pushed version."""
        pg = self._pg_of(p["pgid"])
        e = p["entry"]
        sname = shard_name(e["name"], p.get("shard"))
        txn = Transaction()
        if e["version"] > pg.last_update:
            pg.append_log(txn, e)
        if p.get("has_data"):
            # backfill pushes are authoritative (full resync from the
            # primary: "force") — obj_vers from a divergent reign are
            # not comparable and must be overwritten. Non-forced pushes
            # (repair, forward-completion) share our log lineage, so
            # the gate keeps them from regressing a newer local write.
            pushed_ver = _attrs_from(p).get("ver") or 0
            try:
                local_ver = self.store.getattrs(
                    pg.coll, sname
                ).get("ver") or 0
            except StoreError:
                local_ver = 0
            if p.get("force") or local_ver <= pushed_ver:
                self._write_fetched(
                    txn, pg.coll, sname, p["_raw"], _attrs_from(p)
                )
        elif e["kind"] == "delete":
            txn.remove(pg.coll, sname)
        self.store.queue_transaction(txn)
        self._reply_peer(conn, p["tid"], {"ok": True})

    async def _h_obj_push_batch(self, conn, p) -> None:
        if self._admit_recovery(conn, p, self._h_obj_push_batch):
            return
        self._enqueue_subop(p, self._do_obj_push_batch, conn)

    async def _do_obj_push_batch(self, conn, p) -> None:
        """Many recovery pushes, one frame, one commit, one ack (the
        batched recovery engine's push leg). Items apply strictly IN
        ORDER — log versions must land monotonically or the
        `version > last_update` gate would punch holes — under the same
        per-item version/force gates as _do_obj_push, and the whole
        batch lands in one store transaction."""
        pg = self._pg_of(p["pgid"])
        raw = p.get("_raw") or b""
        off = 0
        txn = Transaction()
        for item in p["items"]:
            e = item["entry"]
            n = int(item.get("raw_len") or 0)
            data = raw[off:off + n]
            off += n
            sname = shard_name(e["name"], p.get("shard"))
            if e["version"] > pg.last_update:
                pg.append_log(txn, e)
            if item.get("has_data"):
                pushed_ver = _attrs_from(item).get("ver") or 0
                try:
                    local_ver = self.store.getattrs(
                        pg.coll, sname
                    ).get("ver") or 0
                except StoreError:
                    local_ver = 0
                if item.get("force") or local_ver <= pushed_ver:
                    self._write_fetched(
                        txn, pg.coll, sname, data, _attrs_from(item)
                    )
            elif e["kind"] == "delete":
                txn.remove(pg.coll, sname)
        self.store.queue_transaction(txn)
        self._reply_peer(
            conn, p["tid"], {"ok": True, "applied": len(p["items"])}
        )

    async def _h_rep_write(self, conn, p) -> None:
        self._enqueue_subop(p, self._do_rep_write, conn)

    async def _do_rep_write(self, conn, p) -> None:
        """ReplicatedBackend sub-write: apply locally, ack; idempotent on
        resend (the entry version gate)."""
        pg = self._pg_of(p["pgid"])
        e = p["entry"]
        async with pg.lock:
            if e["version"] > pg.last_update:
                txn = Transaction()
                if e["kind"] == "delete":
                    txn.remove(pg.coll, e["name"])
                elif e["kind"] == "clone":
                    self._local_clone(txn, pg, e["src"], e["name"])
                else:
                    txn.write(
                        pg.coll, e["name"], p["_raw"],
                        attrs=_attrs_from(p),
                    )
                    if p.get("omap_delta"):
                        self._omap_delta_txn(
                            txn, pg.coll, e["name"], p["omap_delta"]
                        )
                pg.append_log(txn, e)
                self.store.queue_transaction(txn)
                self.perf.inc("subop_w")
        self._reply_peer(conn, p["tid"], {"ok": True})

    async def _h_ec_sub_write(self, conn, p) -> None:
        self._enqueue_subop(p, self._do_ec_sub_write, conn)

    async def _do_ec_sub_write(self, conn, p) -> None:
        self._trace(
            p.get("trace_id"),
            f"ec_sub_write apply shard={p.get('shard')}",
        )
        with self.perf.time("l_subop_apply"):
            await self._do_ec_sub_write_inner(conn, p)
        self._trace(p.get("trace_id"), "ec_sub_write acked")

    async def _do_ec_sub_write_inner(self, conn, p) -> None:
        """ECBackend::handle_sub_write for our shard."""
        pg = self._pg_of(p["pgid"])
        e = p["entry"]
        async with pg.lock:
            if e["version"] > pg.last_update:
                txn = Transaction()
                if e["kind"] == "delete":
                    txn.remove(
                        pg.coll, shard_name(e["name"], p["shard"])
                    )
                elif e["kind"] == "clone":
                    self._local_clone(
                        txn, pg,
                        shard_name(e["src"], p["shard"]),
                        shard_name(e["name"], p["shard"]),
                    )
                elif p.get("partial"):
                    extents, cur = [], 0
                    for off, ln in p.get("extents") or []:
                        extents.append(
                            (off, p["_raw"][cur: cur + ln])
                        )
                        cur += ln
                    self._partial_shard_txn(
                        txn, pg, shard_name(e["name"], p["shard"]),
                        p["shard"], extents, e["obj_ver"],
                    )
                else:
                    txn.write(
                        pg.coll,
                        shard_name(e["name"], p["shard"]),
                        p["_raw"],
                        attrs=_attrs_from(p),
                    )
                pg.append_log(txn, e)
                with self.perf.time("l_txn"):
                    self.store.queue_transaction(txn)
                self.perf.inc("subop_w")
        self._reply_peer(conn, p["tid"], {"ok": True})

    # -- cache tiering (PrimaryLogPG promote/flush/proxy, .cc:2341/2305) ------

    TIER_DIRTY_XATTR = "_cache_dirty"

    async def _h_obj_copy_get(self, conn, p) -> None:
        self._enqueue_subop(p, self._do_obj_copy_get, conn)

    async def _do_obj_copy_get(self, conn, p) -> None:
        """Full object state for copy-from/promote/flush (the
        object_copy_data_t GET side, PrimaryLogPG::do_copy_get)."""
        pg = self._pg_of(p["pgid"])
        name = p["name"]
        ec = self.codec(pg.pool)
        async with pg.lock:
            acting, _primary = self.acting_of(pg.pool, pg.ps)
            if ec is None:
                state = self._load_state_local(pg, name)
            else:
                state = await self._load_state_ec(
                    pg, acting, name, need_data=True
                )
            if not state.exists:
                self._reply_peer(
                    conn, p["tid"], {"ok": False, "errno": "ENOENT"}
                )
                return
            omap = {}
            if ec is None:
                try:
                    omap = self.store.omap_get(pg.coll, name)
                except StoreError:
                    omap = {}
            self._reply_peer(
                conn, p["tid"],
                {"ok": True,
                 "xattrs": {k: v.hex()
                            for k, v in state.xattrs.items()},
                 "omap": {k.hex(): v.hex()
                          for k, v in (omap or {}).items()}},
                raw=bytes(state.data),
            )

    async def _h_tier_put(self, conn, p) -> None:
        self._enqueue_subop(p, self._do_tier_put, conn)

    async def _do_tier_put(self, conn, p) -> None:
        """Apply a full-object state at this (base-pool) primary — the
        flush/copy-from WRITE side. Runs the normal primary mutation so
        it replicates/EC-encodes like any client write."""
        pg = self._pg_of(p["pgid"])
        try:
            if self.codec(pg.pool) is not None and p.get("omap"):
                p = dict(p)
                p.pop("omap")  # EC base: omap cannot land, drop it
            async with pg.lock:
                acting, _primary = self.acting_of(pg.pool, pg.ps)
                await self._primary_ops(
                    pg, acting, p["name"],
                    self._state_put_ops(p), [p["_raw"]], None,
                )
            self._reply_peer(conn, p["tid"], {"ok": True})
        except Exception as e:
            self._reply_peer(
                conn, p["tid"], {"ok": False, "error": str(e)}
            )

    async def _h_tier_delete(self, conn, p) -> None:
        self._enqueue_subop(p, self._do_tier_delete, conn)

    async def _do_tier_delete(self, conn, p) -> None:
        pg = self._pg_of(p["pgid"])
        try:
            async with pg.lock:
                acting, _primary = self.acting_of(pg.pool, pg.ps)
                await self._primary_ops(
                    pg, acting, p["name"], [{"op": "delete"}], [], None,
                )
            self._reply_peer(conn, p["tid"], {"ok": True})
        except OpError as e:
            ok = e.code == "ENOENT"  # deleting the never-flushed is fine
            self._reply_peer(
                conn, p["tid"], {"ok": ok, "errno": e.code}
            )
        except Exception as e:
            self._reply_peer(
                conn, p["tid"], {"ok": False, "error": str(e)}
            )

    @staticmethod
    def _state_put_ops(p) -> list[dict]:
        """write_full + xattr/omap restore vector from a copy payload;
        the cache's own dirty flag never travels to the base."""
        ops = [{"op": "write_full"}]
        for k, vhex in (p.get("xattrs") or {}).items():
            if k == OSDService.TIER_DIRTY_XATTR:
                continue
            ops.append({"op": "setxattr", "name": k, "value": vhex})
        if p.get("omap"):
            ops.append({"op": "omap_set", "kv": dict(p["omap"])})
        return ops

    async def _expand_copy_from(
        self, pool_id: int, ops: list[dict], datas: list[bytes]
    ) -> tuple[list[dict], list[bytes]]:
        out_ops, out_datas, di = [], [], 0
        consuming = {"write", "write_full", "append"}
        for op in ops:
            if op["op"] != "copy_from":
                out_ops.append(op)
                if op["op"] in consuming:
                    out_datas.append(datas[di])
                    di += 1
                continue
            src = await self._tier_get(
                int(op.get("src_pool", pool_id)), op["src_name"]
            )
            if src is None:
                raise OpError(
                    "ENOENT", f"copy_from: no object {op['src_name']!r}"
                )
            if self.codec(pool_id) is not None:
                # EC destinations have no omap (ECBackend's EOPNOTSUPP);
                # data + xattrs travel, omap is dropped like the
                # reference's copy-get omap gate
                src = dict(src)
                src.pop("omap", None)
            out_ops.extend(self._state_put_ops(src))
            out_datas.append(src["_raw"])
        return out_ops, out_datas

    def _tier_primary_of(self, pool_id: int, name: str) -> int:
        ps = self.object_pg(pool_id, name)
        _acting, primary = self.acting_of(pool_id, ps)
        return primary

    async def _tier_call(
        self, pool_id: int, name: str, mtype: str, payload: dict,
        raw: bytes = b"",
    ) -> dict:
        """Internal op against another pool's primary (which may be this
        very daemon — then the handler runs locally via a loopback
        conn-less path to keep one code path)."""
        primary = self._tier_primary_of(pool_id, name)
        ps = self.object_pg(pool_id, name)
        payload = dict(payload)
        payload["pgid"] = [pool_id, ps]
        payload["name"] = name
        return await self._peer_call(
            primary, mtype, payload, timeout=10.0, raw=raw
        )

    async def _tier_get(self, pool_id: int, name: str) -> dict | None:
        rep = await self._tier_call(pool_id, name, "obj_copy_get", {})
        if not rep.get("ok"):
            if rep.get("errno") == "ENOENT":
                return None
            raise RuntimeError(rep.get("error", "copy-get failed"))
        return rep

    def _tier_dirty_set(self, pg: PG) -> dict:
        if not hasattr(pg, "tier_dirty"):
            pg.tier_dirty = {}  # name -> True, insertion-ordered
        return pg.tier_dirty

    def _tier_exists_here(self, pg: PG, name: str) -> bool:
        e = pg.latest_objects().get(name)
        return e is not None and e["kind"] != "delete"

    async def _tier_promote(
        self, pool, pg: PG, acting, name: str
    ) -> bool:
        """Copy the base pool's object into the cache PG (clean).
        Returns False when the base has no such object either."""
        src = await self._tier_get(pool.tier_of, name)
        if src is None:
            self.perf.inc("tier_miss")
            return False
        async with pg.lock:
            if not self._tier_exists_here(pg, name):  # re-check: raced
                await self._primary_ops(
                    pg, acting, name,
                    self._state_put_ops(src), [src["_raw"]], None,
                )
        self.perf.inc("tier_promote")
        return True

    async def _tier_flush(
        self, pool, pg: PG, acting, name: str, evict: bool = False
    ) -> None:
        """Write the cached object back to the base pool, clear its
        dirty mark (and optionally evict the now-clean copy)."""
        async with pg.lock:
            state = self._load_state_local(pg, name)
            if not state.exists:
                self._tier_dirty_set(pg).pop(name, None)
                return
            payload = {
                "xattrs": {k: v.hex() for k, v in state.xattrs.items()},
            }
            try:
                omap = self.store.omap_get(pg.coll, name)
            except StoreError:
                omap = {}
            if omap:
                payload["omap"] = {
                    k.hex(): v.hex() for k, v in omap.items()
                }
            data = bytes(state.data)
        dirty = self.TIER_DIRTY_XATTR in payload["xattrs"]
        if dirty:
            rep = await self._tier_call(
                pool.tier_of, name, "tier_put", payload, raw=data
            )
            if not rep.get("ok"):
                raise RuntimeError(rep.get("error", "tier flush failed"))
            async with pg.lock:
                await self._primary_ops(
                    pg, acting, name,
                    [{"op": "rmxattr",
                      "name": self.TIER_DIRTY_XATTR}], [], None,
                )
            self.perf.inc("tier_flush")
        self._tier_dirty_set(pg).pop(name, None)
        if evict:
            async with pg.lock:
                await self._primary_ops(
                    pg, acting, name, [{"op": "delete"}], [], None,
                )
            self.perf.inc("tier_evict")

    async def _tier_agent(self, pool, pg: PG, acting) -> None:
        """Flush oldest dirty objects once the PG exceeds the pool's
        dirty budget (the tier agent's dirty_ratio trigger). One agent
        per PG: a concurrent pair would pick the same oldest name and
        flush it twice."""
        if getattr(pg, "tier_agent_busy", False):
            return
        pg.tier_agent_busy = True
        try:
            dirty = self._tier_dirty_set(pg)
            while len(dirty) > pool.cache_target_dirty_max:
                name = next(iter(dirty))
                try:
                    await self._tier_flush(pool, pg, acting, name)
                # cephlint: disable=error-taxonomy (flush failure keeps the object TRACKED for the next pass)
                except Exception:
                    # keep it TRACKED (dropping it would orphan the
                    # only durable copy in the cache): rotate to the
                    # back and stop this pass; the next trigger retries
                    dirty.pop(name, None)
                    dirty[name] = True
                    break
        finally:
            pg.tier_agent_busy = False

    async def _tier_before_op(
        self, conn, p, pool, pg: PG, acting, name: str
    ) -> bool:
        """Writeback-cache behavior in front of the normal op dispatch.
        Returns True when the op was fully handled (replied) here."""
        op = p.get("op")
        if op in ("cache_flush", "cache_evict"):
            # explicit per-object flush/evict (the rados cache-flush /
            # cache-evict commands; the test's determinism lever)
            try:
                await self._tier_flush(
                    pool, pg, acting, name, evict=(op == "cache_evict")
                )
                reply = {"tid": p["tid"], "ok": True}
            except Exception as e:
                reply = {"tid": p["tid"], "ok": False,
                         "error": str(e)}
            conn.send_message(
                Message(type="osd_op_reply", tid=p["tid"],
                        epoch=self.osdmap.epoch, payload=reply)
            )
            return True
        if op == "delete":
            # deletes write through: cache copy AND base object go
            # (mini semantics — the reference caches a whiteout). A
            # failed base delete must NOT be swallowed: the local copy
            # going while the base copy survives would resurrect the
            # object on the next promote
            rep = await self._tier_call(
                pool.tier_of, name, "tier_delete", {}
            )
            base_had = rep.get("ok") and rep.get("errno") != "ENOENT"
            if not rep.get("ok"):
                raise RuntimeError(
                    rep.get("error", "tier base delete failed")
                )  # retryable: the client resends
            self._tier_dirty_set(pg).pop(name, None)
            if not self._tier_exists_here(pg, name):
                # base-only object (flushed + evicted): the base delete
                # IS the whole operation — answer here, or the normal
                # path would ENOENT an object we just deleted
                reply = {"tid": p["tid"], "ok": True}
                if not base_had:
                    reply = {"tid": p["tid"], "ok": False,
                             "errno": "ENOENT",
                             "error": f"no such object {name!r}"}
                conn.send_message(
                    Message(type="osd_op_reply", tid=p["tid"],
                            epoch=self.osdmap.epoch, payload=reply)
                )
                return True
            return False
        if not self._tier_exists_here(pg, name):
            await self._tier_promote(pool, pg, acting, name)
        else:
            self.perf.inc("tier_hit")
        # mutating vectors mark the cached object dirty atomically
        if op == "write":
            p["op"] = "ops"
            p["ops"] = [
                {"op": "write_full"},
                {"op": "setxattr", "name": self.TIER_DIRTY_XATTR,
                 "value": b"1".hex()},
            ]
            p["data_lens"] = [len(p["_raw"])]
        elif op == "ops" and is_mutating(p.get("ops") or []):
            p["ops"] = list(p["ops"]) + [
                {"op": "setxattr", "name": self.TIER_DIRTY_XATTR,
                 "value": b"1".hex()},
            ]
        else:
            return False
        dirty = self._tier_dirty_set(pg)
        dirty.pop(name, None)
        dirty[name] = True
        self._spawn(self._tier_agent(pool, pg, acting))
        return False

    def _pg_of(self, pgid) -> PG:
        key = (pgid[0], pgid[1])
        if key not in self.pgs:
            self.pgs[key] = PG(self, *key)
        return self.pgs[key]

    def _enqueue_subop(self, p, fn, conn) -> None:
        """Queue a lock-taking sub-op for ordered per-PG execution off
        the dispatch path (per-connection arrival order is preserved by
        the FIFO, which is the ordering _sub_op_persist relies on)."""
        pg = self._pg_of(p["pgid"])
        if pg.subop_task is None or pg.subop_task.done():
            pg.subop_task = asyncio.create_task(self._subop_worker(pg))
            self._tasks.append(pg.subop_task)
        if "_sent_at" in p:
            self.perf.tinc("l_subop_transit", time.time() - p["_sent_at"])
        p["_queued_at"] = time.time()
        qs = self.tracer.join(p.get("_trace"), "op_queue")
        if qs is not None:
            p["_qspan"] = qs
        pg.subop_q.put_nowait((fn, conn, p))

    async def _subop_worker(self, pg: PG) -> None:
        while not self._stopped:
            fn, conn, p = await pg.subop_q.get()
            if "_queued_at" in p:
                self.perf.tinc(
                    "l_subop_queue", time.time() - p["_queued_at"]
                )
            qs = p.pop("_qspan", None)
            if qs is not None:
                qs.finish()
            # sub-op handlers run under the SENDER's fork span, so the
            # shard-side journal/store spans attach to the right branch
            stoken = self.tracer.use_wire(p.get("_trace"))
            try:
                await fn(conn, p)
            except asyncio.CancelledError:
                raise
            # cephlint: disable=error-taxonomy (the sender retries; never kill the worker)
            except Exception:
                pass  # the sender retries; never kill the worker
            finally:
                self.tracer.release(stoken)

    # -- client ops (the primary path) ----------------------------------------

    async def _h_osd_op(self, conn, p) -> None:
        """Client ops ride the sharded weighted op queue (ShardedOpWQ,
        OSD.cc:9490 enqueue_op -> dequeue_op): the shard is picked by
        object name so same-object ops keep their arrival order, and
        within a shard the WPQ's deficit round-robin over client klasses
        fair-shares service by op cost."""
        if self.osdmap.is_blocklisted(conn.peer_name, conn.peer_nonce):
            # fencing (OSD::ms_verify_authorizer + op blacklist check):
            # an evicted/blocklisted entity's ops — including writes that
            # were in flight when the blocklist committed — are refused
            # with a terminal errno at EVERY osd, so it can never race
            # the client that took over its caps/locks
            conn.send_message(
                Message(
                    type="osd_op_reply", tid=p["tid"],
                    epoch=self.osdmap.epoch,
                    payload={"tid": p["tid"], "ok": False,
                             "errno": "EBLOCKLISTED",
                             "error": f"{conn.peer_name} is blocklisted"},
                )
            )
            return
        self._trace(p.get("trace_id"), "op_dispatch")
        shard = self._op_shards[
            zlib.crc32(p["name"].encode()) % len(self._op_shards)
        ]
        # queue-wait span: enqueue here, finished when the shard worker
        # picks the op — the ShardedOpWQ wait is a first-class trace leg
        # the queue class: a client-declared QoS class (ioctx.qos_class,
        # e.g. background data prefetch) wins over the per-client default
        klass = p.get("qos") or conn.peer_name
        qs = self.tracer.join(
            p.get("_trace"), "op_queue",
            tags={"klass": klass},
        )
        if qs is not None:
            p["_qspan"] = qs
        shard.queue.enqueue(
            63,  # osd_client_op_priority
            max(1, len(p["_raw"]) // 4096),
            (conn, p),
            klass=klass,
        )
        shard.kick.set()

    async def _op_shard_worker(self, shard) -> None:
        while not self._stopped:
            item = shard.queue.dequeue()
            if item is None:
                if len(shard.queue):
                    # mclock limit throttling: ops exist but none are
                    # eligible until the clock advances — poll, don't
                    # sleep on the kick (no new op may ever arrive)
                    await asyncio.sleep(0.005)
                    continue
                shard.kick.clear()
                await shard.kick.wait()
                continue
            conn, p = item
            rfn = p.pop("_rfn", None)
            if rfn is not None:
                # admitted recovery sub-op: re-enter its handler as an
                # ephemeral task (the handler replies to the peer; an
                # obj_push_batch re-queues itself on the PG FIFO) so a
                # slow store op can't block the shard's client ops
                task = asyncio.create_task(rfn(conn, p))
                self._ephemeral.add(task)
                task.add_done_callback(self._ephemeral.discard)
                continue
            name = p.get("name")
            inflight = shard.inflight.get(name)
            if self._op_pipelines(p):
                # EC all-write vectors run as their own tasks so the
                # sub-stripe RMW read+encode legs of in-flight writes
                # overlap (ECBackend pipelines rmw ops the same way,
                # ECBackend.cc:1830); the ExtentCache serializes
                # conflicting column windows in SPAWN order (reserve is
                # reached before the task's first yield point), the
                # _full_mut fence catches full-rewrite races, and
                # version assignment + fan-out still serialize under
                # the PG lock.
                task = asyncio.create_task(
                    self._run_client_op(conn, p)
                )
                self._ephemeral.add(task)
                bucket = shard.inflight.setdefault(name, set())
                bucket.add(task)

                def _done(t, name=name, bucket=bucket):
                    self._ephemeral.discard(t)
                    bucket.discard(t)
                    if not bucket and shard.inflight.get(
                        name
                    ) is bucket:
                        del shard.inflight[name]

                task.add_done_callback(_done)
            else:
                # strict per-object order for everything else: an
                # inline op (read, mixed vector, full rewrite) must
                # observe every previously-queued pipelined write on
                # its object — same-client read-your-writes
                if inflight:
                    await asyncio.gather(
                        *list(inflight), return_exceptions=True
                    )
                await self._run_client_op(conn, p)

    def _op_pipelines(self, p) -> bool:
        if p.get("op") != "ops":
            return False
        try:
            if self.codec(p["pool"]) is None:
                return False
        # cephlint: disable=error-taxonomy (not an EC pool or codec unavailable: not a planar candidate)
        except Exception:
            return False
        ops = p.get("ops") or []
        return bool(ops) and all(o.get("op") == "write" for o in ops)

    async def _run_client_op(self, conn, p) -> None:
        pool_id = p["pool"]
        name = p["name"]
        token = _trace_ctx.set(p.get("trace_id"))
        qs = p.pop("_qspan", None)
        if qs is not None:
            qs.finish()
        # execution span: child of the client's op_submit root; made the
        # task-local current context so every downstream site — sub-op
        # forks, encode batches, journal commits, store reads — parents
        # to it without plumbing
        # tail=True: the execution span runs its own keep/drop decision
        # at completion — a server-slow op promotes its trace even when
        # the client never relays (e.g. the client died mid-op)
        span = self.tracer.join(
            p.get("_trace"), "osd_op",
            tags={"op": p.get("op"), "object": f"{pool_id}/{name}"},
            tail=True,
        )
        stoken = None if span is None else self.tracer.use(span)
        self._trace(
            p.get("trace_id"),
            f"op_execute {p.get('op')} {pool_id}/{name}",
        )
        try:
            with self.op_tracker.track(
                f"osd_op({p.get('op')} {pool_id}/{name} "
                f"from {conn.peer_name})", span=span
            ) as tracked, self.perf.time("l_op_total"):
                await self._do_osd_op(conn, p, pool_id, name, tracked)
            self._trace(p.get("trace_id"), "op_replied")
        finally:
            if span is not None:
                span.finish()
                self.tracer.release(stoken)
            _trace_ctx.reset(token)

    async def _do_osd_op(self, conn, p, pool_id, name, tracked) -> None:
        try:
            if pool_id not in self.osdmap.pools:
                raise RuntimeError(f"no pool {pool_id}")
            ps = self.object_pg(pool_id, name)
            acting, primary = self.acting_of(pool_id, ps)
            tracked.mark_event("placed")
            if p["op"] == "shard_read":
                # EC direct-shard read: served by whichever acting
                # member homes the requested data shard (possibly the
                # primary itself); does its own state checks + redirect
                await self._serve_shard_read(
                    conn, p, pool_id, name, ps, acting, primary
                )
                return
            if primary != self.id:
                if p.get("balanced"):
                    if await self._serve_balanced_read(
                        conn, p, pool_id, name, ps, acting, primary
                    ):
                        return
                    # cannot prove our copy current: bounce to the
                    # primary, never serve unproven data — and when our
                    # marker names the PG's backfill targets (we may be
                    # one), ship them so the client's round robin stops
                    # landing reads here while the backfill drains
                    self.perf.inc("read_redirected")
                    mk = self._pg_of((pool_id, ps)).replica_marker
                    conn.send_message(
                        Message(
                            type="osd_op_reply", tid=p["tid"],
                            epoch=self.osdmap.epoch,
                            payload=redirect_reply(
                                p["tid"], primary, self.osdmap.epoch,
                                "replica cannot prove its copy current",
                                backfill=(mk or {}).get("backfill"),
                            ),
                        )
                    )
                    return
                conn.send_message(
                    Message(
                        type="osd_op_reply", tid=p["tid"],
                        epoch=self.osdmap.epoch,
                        payload={"tid": p["tid"], "ok": False,
                                 "wrong_primary": True,
                                 "epoch": self.osdmap.epoch},
                    )
                )
                return
            pg = self._pg_of((pool_id, ps))
            if not pg.active:
                raise RuntimeError(
                    f"pg {pool_id}.{ps} is peering"
                )  # retryable: no errno, the client resends
            pool = self.osdmap.pools.get(pool_id)
            if (
                pool is not None
                and pool.tier_of >= 0
                and pool.cache_mode == "writeback"
            ):
                handled = await self._tier_before_op(
                    conn, p, pool, pg, acting, name
                )
                if handled:
                    return
            reply_raw = b""
            if p["op"] in ("ops", "write", "delete"):
                if p["op"] == "ops":
                    ops, datas, off = p["ops"], [], 0
                    for ln in p.get("data_lens", []):
                        datas.append(p["_raw"][off: off + ln])
                        off += ln
                elif p["op"] == "write":
                    ops, datas = [{"op": "write_full"}], [p["_raw"]]
                else:
                    ops, datas = [{"op": "delete"}], []
                if any(o["op"] == "copy_from" for o in ops):
                    # CEPH_OSD_OP_COPY_FROM (PrimaryLogPG.cc:5622): the
                    # DEST primary fetches the source object server-side
                    # (any pool, its own included) and applies it as a
                    # normal mutation vector — so it replicates/encodes
                    # exactly like a client write
                    ops, datas = await self._expand_copy_from(
                        pool_id, ops, datas
                    )
                # instance nonce distinguishes a restarted client whose
                # fresh tid counter would otherwise collide with its old
                # reqids (osd_reqid_t carries the client instance too)
                reqid = (
                    f"{conn.peer_name}.{conn.peer_nonce}:{p['tid']}"
                )
                if (
                    is_mutating(ops)
                    and not all(
                        o["op"] in _FULL_OK_OPS for o in ops
                    )
                    and self._is_full()
                ):
                    # full handling (OSD::check_full_status / the
                    # FAILSAFE path of PrimaryLogPG): space-consuming
                    # writes are refused with ENOSPC once usage crosses
                    # mon_osd_full_ratio; deletes still run so the
                    # operator can dig the cluster out
                    raise OpError(
                        "ENOSPC",
                        f"osd.{self.id} is full "
                        f"({self.statfs()['used']} of "
                        f"{self.statfs()['total']} bytes)",
                    )
                if is_mutating(ops):
                    # EC writes do their heavy lifting BEFORE the PG
                    # lock: full-object writes pre-encode (concurrent
                    # writes coalesce into one planar launch); partial
                    # overwrites run the whole sub-stripe read+encode
                    # leg outside too, coordinated by the ExtentCache —
                    # version assignment + fan-out stay serialized
                    pre_encoded = None
                    partial = None
                    ec = self.codec(pool_id)
                    if (
                        ec is not None
                        and ops[0]["op"] == "write_full"
                        and len(ops) == 1
                    ):
                        pre_encoded = await self.encode_service.encode(
                            ec, datas[0]
                        )
                    elif ec is not None:
                        partial = await self._prepare_partial_ec(
                            pg, acting, name, ops, datas,
                            p.get("snapc"),
                        )
                    try:
                        for _attempt in range(3):
                            try:
                                async with pg.lock:
                                    op_results, reply_raw = (
                                        await self._primary_ops(
                                            pg, acting, name, ops,
                                            datas, reqid,
                                            snapc=p.get("snapc"),
                                            pre_encoded=pre_encoded,
                                            partial=partial,
                                        )
                                    )
                                break
                            except _StalePartial:
                                # a whole-object write superseded our
                                # base between prepare and commit:
                                # re-prepare against the new state
                                pg.extents.release(partial["token"])
                                partial = None
                                partial = (
                                    await self._prepare_partial_ec(
                                        pg, acting, name, ops, datas,
                                        p.get("snapc"),
                                    )
                                )
                        else:
                            raise RuntimeError(
                                f"partial write to {name!r} kept "
                                "racing full rewrites"
                            )  # retryable: client resends
                    finally:
                        if partial is not None:
                            pg.extents.release(partial["token"])
                    self.perf.inc("op_w")
                    self.perf.inc("op_in_bytes", sum(
                        len(d_) for d_ in datas if d_
                    ))
                else:
                    op_results, reply_raw = await self._primary_ops(
                        pg, acting, name, ops, datas, None,
                        snapid=p.get("snapid"),
                    )
                    self.perf.inc("op_r")
                    self.perf.inc(
                        "op_out_bytes", len(reply_raw) if reply_raw else 0
                    )
                result = {"results": op_results}
            elif p["op"] == "read":
                rname = name
                if p.get("snapid") is not None:
                    rname = self._resolve_snap(
                        pg, acting, name, p["snapid"]
                    )
                reply_raw = await self._primary_read(pg, acting, rname)
                result = {}
                self.perf.inc("op_r")
                self.perf.inc(
                    "op_out_bytes", len(reply_raw) if reply_raw else 0
                )
            elif p["op"] == "stat":
                result = self._primary_stat(pg, name)
            elif p["op"] == "call":
                async with pg.lock:
                    result = await self._primary_call(pg, acting, name, p)
                self.perf.inc("op_rw")
            elif p["op"] == "watch":
                result = await self._h_op_watch(pg, conn, p)
            elif p["op"] == "unwatch":
                result = await self._h_op_unwatch(pg, conn, p)
            elif p["op"] == "notify":
                # replied by a task: waiting for acks inline would wedge
                # this conn's dispatch loop, and the notifier may well be
                # one of the watchers being notified on this very conn
                self._spawn(self._notify_and_reply(pg, conn, p))
                return
            else:
                raise RuntimeError(f"unknown op {p['op']!r}")
            reply = {"tid": p["tid"], "ok": True, **result}
            self._pool_ops[pool_id] = self._pool_ops.get(pool_id, 0) + 1
        except (StoreError, ClsError, OpError) as e:
            if isinstance(e, StoreFatalError) or e.code == "EROFS":
                # fail-stop: our store just fenced (we are about to go
                # down) — never surface a terminal errno for an op we
                # could not durably apply; the client retries against
                # the re-targeted acting set once the mon marks us down
                reply = {"tid": p["tid"], "ok": False, "error": str(e)}
            else:
                # permanent, client-visible errno (ENOENT/EBUSY/...):
                # the client surfaces these instead of retrying
                reply = {"tid": p["tid"], "ok": False, "error": str(e),
                         "errno": e.code}
            reply_raw = b""
        except Exception as e:
            reply = {"tid": p["tid"], "ok": False, "error": str(e)}
            reply_raw = b""
        conn.send_message(
            Message(type="osd_op_reply", tid=p["tid"],
                    epoch=self.osdmap.epoch,
                    payload=reply, raw=reply_raw)
        )

    def _obj_version(self, pg: PG, name: str) -> int:
        e = pg.latest_objects().get(name)
        return 0 if e is None else e["obj_ver"]

    def _check_min_size(self, pg: PG, acting: list[int]) -> None:
        """The reference blocks IO below pool min_size: acking a write
        that landed on fewer than min_size members risks silently losing
        it if the lone holder then fails and stale replicas re-peer. The
        error is retryable (no errno) so the client resends once the
        cluster heals."""
        pool = self.osdmap.pools[pg.pool]
        # backfill targets don't count: an amnesiac-revived store takes
        # no writes and holds nothing yet, so letting it satisfy
        # min_size would ack writes that live on too few REAL copies to
        # survive the next failure (the hole PastIntervals closes in the
        # reference, osd_types.h:3030)
        alive = sum(
            1 for o in acting
            if o != _NONE and not self.osdmap.is_down(o)
            and o not in pg.backfill_targets
        )
        if alive < pool.min_size:
            raise RuntimeError(
                f"pg {pg.pool}.{pg.ps} has {alive} complete acting "
                f"members, below min_size {pool.min_size}"
            )

    async def _sub_op_persist(
        self, pg: PG, osd: int, mtype: str, payload: dict, raw: bytes = b""
    ) -> None:
        """Send a sub-op and retry until it acks, the target leaves the
        map, or the interval changes under us. Within one interval every
        acting member therefore applies every entry IN ORDER — the
        invariant that lets op-vector sub-ops mutate replica state
        incrementally (a skipped entry would diverge a replica silently).
        The reference gets the same guarantee from ordered lossless
        sessions plus peering on connection loss."""
        start_acting, start_primary = self.acting_of(pg.pool, pg.ps)
        while True:
            if self.osdmap.is_down(osd):
                return  # peering will resync it when it returns
            acting, primary = self.acting_of(pg.pool, pg.ps)
            if primary != self.id or osd not in acting:
                raise RuntimeError(
                    f"pg {pg.pool}.{pg.ps} interval changed mid-write"
                )
            try:
                rep = await self._peer_call(
                    osd, mtype, payload, timeout=2.0, raw=raw,
                    batchable=True,
                )
            except (asyncio.TimeoutError, RuntimeError):
                await asyncio.sleep(0.05)
                continue  # down-mark or ack resolves the wait
            if rep.get("ok"):
                return
            await asyncio.sleep(0.05)

    # -- the object context (do_osd_ops execution) ----------------------------

    def _load_state_local(self, pg: PG, name: str) -> ObjectState:
        """ObjectState from the local store (replicated pools; also used
        by replicas applying op vectors)."""
        entry = pg.latest_objects().get(name)
        exists = entry is not None and entry["kind"] != "delete"
        state = ObjectState(exists=exists)
        if exists:
            try:
                state.data = bytearray(self.store.read(pg.coll, name))
            except StoreError:
                state.data = bytearray()
            attrs = self.store.getattrs(pg.coll, name)
            blob = attrs.get("xattr")
            if blob:
                state.xattrs = {
                    k: bytes.fromhex(v)
                    for k, v in json.loads(blob).items()
                }
            state.omap = self.store.omap_get(pg.coll, name) or None
        return state

    def _persist_state_txn(
        self, pg: PG, name: str, state: ObjectState, obj_ver: int,
        keep_user: bytes | None = None,
    ) -> Transaction:
        """Compile the mutated state into a store transaction (replicated
        object layout: data row + ver/xattr attrs + omap delta)."""
        txn = Transaction()
        if state.deleted:
            txn.remove(pg.coll, name)
            return txn
        attrs: dict = {"ver": obj_ver}
        if state.xattrs:
            attrs["xattr"] = json.dumps(
                {k: v.hex() for k, v in state.xattrs.items()},
                sort_keys=True,
            ).encode()
        if keep_user is not None:
            attrs["user"] = keep_user
        txn.write(pg.coll, name, bytes(state.data), attrs=attrs)
        if state.omap_cleared:
            existing = self.store.omap_get(pg.coll, name)
            if existing:
                txn.omap_rmkeys(pg.coll, name, list(existing))
        if state.omap_rms:
            txn.omap_rmkeys(pg.coll, name, state.omap_rms)
        if state.omap_sets:
            txn.omap_setkeys(pg.coll, name, state.omap_sets)
        return txn

    async def _primary_ops(
        self, pg: PG, acting: list[int], name: str, ops: list[dict],
        datas: list[bytes], reqid: str | None,
        snapc: dict | None = None, snapid: int | None = None,
        pre_encoded: dict[int, bytes] | None = None,
        partial: dict | None = None,
    ) -> tuple[list[dict], bytes]:
        """Execute a client op vector (execute_ctx -> do_osd_ops ->
        issue_repop): run against the object context, and when it mutated,
        log one entry and replicate — replicated pools ship the op vector
        for deterministic re-execution, EC pools re-encode the final
        object and ship whole shards (full-stripe RMW overwrite).

        `snapc` (writes) triggers clone-on-first-write-after-snap
        (make_writeable); `snapid` (reads) redirects the context to the
        clone covering that snap."""
        if reqid is not None and reqid in pg._reqids:
            # duplicate of an already-logged op (client resend after a
            # lost reply / primary failover): never re-execute the
            # mutation — but if the original aborted mid-fan-out, finish
            # distributing its result first, or this ack would cover a
            # write that lives on too few members
            if reqid not in pg._reqids_done:
                if not await self._complete_entry_forward(
                    pg, acting, name
                ):
                    # some live member still lacks the entry: do NOT ack
                    # (the write would exist on too few members); the
                    # client's next resend tries again
                    raise RuntimeError(
                        f"op {reqid} logged but not fully replicated yet"
                    )
                pg._reqids_done.add(reqid)
            return [], b""
        ec = self.codec(pg.pool)
        mutating = is_mutating(ops)
        if mutating and snapid is not None:
            raise OpError("EINVAL", "cannot write at a snapshot")
        if mutating:
            self._check_min_size(pg, acting)
        if snapid is not None:
            name = self._resolve_snap(pg, acting, name, snapid)
        if partial is not None:
            # commit leg of a prepared sub-stripe RMW: valid only while
            # no whole-object mutation superseded the base it read from
            # (disjoint partial writes in between are fine — column
            # independence + the ExtentCache reservation)
            cur = self._obj_version(pg, name)
            if (
                pg._full_mut.get(name, 0) > partial["base_obj_ver"]
                or cur < partial["base_obj_ver"]
            ):
                raise _StalePartial
            entry = {
                "version": pg.last_update + 1,
                "name": name,
                "obj_ver": cur + 1,
                "kind": "modify",
                "epoch": self.osdmap.epoch,
            }
            if reqid is not None:
                entry["reqid"] = reqid
            await self._fan_ec_partial(pg, acting, name, entry, partial)
            if reqid is not None:
                pg._reqids_done.add(reqid)
            return [{} for _ in ops], b""
        if ec is None:
            state = self._load_state_local(pg, name)
        else:
            # EC persistence rewrites whole shards from state.data, so
            # ANY mutation needs the prior data decoded (the RMW read
            # leg) — unless the vector's first op replaces or removes the
            # object outright (ECBackend skips reads for aligned
            # full-stripe writes for the same reason)
            if mutating:
                need_data = ops[0]["op"] not in ("write_full", "delete")
            else:
                need_data = any(
                    op["op"] in ("read", "stat") for op in ops
                )
            with self.perf.time("l_load_state"):
                state = await self._load_state_ec(
                    pg, acting, name, need_data=need_data
                )
        pre_snapset = load_snapset(state.xattrs)
        if mutating and snapc:
            if not state.exists:
                # recreate after delete: adopt the snapdir's SnapSet so
                # older clones stay linked to the new head
                sd = load_snapset(
                    self._head_xattrs(pg, acting, snapdir_name(name))
                )
                if sd["clones"]:
                    state.xattrs[SNAPSET_XATTR] = json.dumps(sd).encode()
            new_ss = await self._make_writeable(
                pg, acting, name, state, snapc
            )
            if new_ss is not None:
                # the SnapSet update replicates as a real op in the
                # vector, so every replica's head carries it too
                ops = [
                    {"op": "setxattr", "name": SNAPSET_XATTR,
                     "value": json.dumps(new_ss).encode().hex()}
                ] + list(ops)
                pre_snapset = new_ss
        results, reads = execute_ops(state, ops, datas)
        if not mutating:
            return results, b"".join(reads)
        entry = {
            "version": pg.last_update + 1,
            "name": name,
            "obj_ver": self._obj_version(pg, name) + 1,
            "kind": "delete" if state.deleted else "modify",
            "epoch": self.osdmap.epoch,
        }
        if reqid is not None:
            entry["reqid"] = reqid
        if state.deleted and pre_snapset["clones"]:
            # the head is going away but clones remain: park the SnapSet
            # on the snapdir object (find_object_context's CEPH_SNAPDIR)
            await self._primary_ops(
                pg, acting, snapdir_name(name),
                [{"op": "setxattr", "name": SNAPSET_XATTR,
                  "value": json.dumps(pre_snapset).encode().hex()}],
                [], None,
            )
            entry["version"] = pg.last_update + 1
        if ec is None:
            user = None
            try:
                user = self.store.getattrs(pg.coll, name).get("user")
            except StoreError:
                pass
            txn = self._persist_state_txn(
                pg, name, state, entry["obj_ver"], keep_user=user
            )
            pg.append_log(txn, entry)
            self.store.queue_transaction(txn)
            waits = [
                self._sub_op_persist(
                    pg, osd, "rep_ops",
                    {"pgid": [pg.pool, pg.ps], "entry": entry,
                     "ops": ops,
                     "data_lens": [len(d) for d in datas]},
                    raw=b"".join(datas),
                )
                for osd in acting
                if osd not in (self.id, _NONE)
                and not self.osdmap.is_down(osd)
                and osd not in pg.backfill_targets
            ]
            if waits:
                with self.perf.time("l_fan"):
                    await asyncio.gather(*waits)
        elif state.deleted:
            await self._fan_ec_delete(pg, acting, entry)
        else:
            # preserve the cls "user" attr across data writes, like the
            # replicated branch's keep_user (a client append must not
            # erase a held cls lock)
            local = shard_name(name, self._my_shard(pg, acting))
            try:
                user = self.store.getattrs(pg.coll, local).get("user")
            except StoreError:
                user = None
            await self._fan_ec_write(
                pg, acting, name, bytes(state.data), entry,
                xattrs=state.xattrs, user_blob=user,
                pre_encoded=pre_encoded,
            )
        if reqid is not None:
            pg._reqids_done.add(reqid)
        return results, b"".join(reads)

    def _head_xattrs(self, pg: PG, acting: list[int], name: str) -> dict:
        """The head object's xattr blob (local copy or our shard)."""
        ec = self.codec(pg.pool)
        sname = shard_name(
            name, self._my_shard(pg, acting) if ec is not None else None
        )
        try:
            blob = self.store.getattrs(pg.coll, sname).get("xattr")
        except StoreError:
            blob = None
        if not blob:
            return {}
        return {
            k: bytes.fromhex(v) for k, v in json.loads(blob).items()
        }

    def _resolve_snap(
        self, pg: PG, acting: list[int], name: str, snapid: int
    ) -> str:
        """Which object serves a read at `snapid`: the oldest clone whose
        id >= snapid, else the head (SnapSet resolution,
        PrimaryLogPG::find_object_context's snapdir walk)."""
        ss = load_snapset(self._head_xattrs(pg, acting, name))
        if not ss["clones"]:
            sd = load_snapset(
                self._head_xattrs(pg, acting, snapdir_name(name))
            )
            if sd["clones"]:
                ss = sd  # deleted head: the SnapSet parked on snapdir
        covering = [c for c in sorted(ss["clones"]) if c >= snapid]
        if not covering:
            if ss["seq"] >= snapid:
                # the head was first written AFTER this snap (else that
                # write would have cloned): the object did not exist at
                # snap time
                raise StoreError(
                    "ENOENT", f"{name!r} did not exist at snap {snapid}"
                )
            return name  # head unchanged since the snap: it IS the state
        return snap_store_name(name, covering[0])

    async def _make_writeable(
        self, pg: PG, acting: list[int], name: str, state: ObjectState,
        snapc: dict,
    ) -> None:
        """Clone-on-first-write-after-snap (PrimaryLogPG::make_writeable,
        src/osd/PrimaryLogPG.cc:6500+): when the write's snap context is
        newer than the head's SnapSet, every acting member copies its
        LOCAL head (whole object, or its own EC shard — no re-encode) to
        the clone object before the mutation lands."""
        ss = load_snapset(state.xattrs)
        seq = int(snapc.get("seq", 0))
        if seq <= ss["seq"]:
            return None
        if state.exists:
            cloneid = seq
            entry = {
                "version": pg.last_update + 1,
                "name": snap_store_name(name, cloneid),
                "obj_ver": self._obj_version(pg, name),
                "kind": "clone",
                "src": name,
                "epoch": self.osdmap.epoch,
            }
            ec = self.codec(pg.pool)
            waits = []
            for pos, osd in enumerate(acting):
                if (osd == _NONE or self.osdmap.is_down(osd)
                        or osd in pg.backfill_targets):
                    continue
                shard = pos if ec is not None else None
                if osd == self.id:
                    txn = Transaction()
                    self._local_clone(
                        txn, pg,
                        shard_name(name, shard),
                        shard_name(entry["name"], shard),
                    )
                    pg.append_log(txn, entry)
                    self.store.queue_transaction(txn)
                    continue
                mtype = "ec_sub_write" if ec is not None else "rep_write"
                waits.append(
                    self._sub_op_persist(
                        pg, osd, mtype,
                        {"pgid": [pg.pool, pg.ps], "shard": shard,
                         "entry": entry},
                    )
                )
            if waits:
                await asyncio.gather(*waits)
            ss["clones"].append(cloneid)
            ss["sizes"][str(cloneid)] = len(state.data)
        ss["seq"] = seq
        return ss

    def _local_clone(
        self, txn: Transaction, pg: PG, src: str, dst: str
    ) -> None:
        """Copy our local copy/shard (data + attrs + omap) to the clone's
        storage name — clone creation never crosses the wire."""
        try:
            data = self.store.read(pg.coll, src)
            attrs = self.store.getattrs(pg.coll, src)
        except StoreError:
            return  # nothing local to clone (recovery will fill it)
        txn.write(pg.coll, dst, data, attrs=attrs)
        omap = self.store.omap_get(pg.coll, src)
        if omap:
            txn.omap_setkeys(pg.coll, dst, omap)

    async def _complete_entry_forward(
        self, pg: PG, acting: list[int], name: str
    ) -> bool:
        """Finish a partially-fanned entry by pushing the object's current
        full state (idempotent: version-gated at receivers) to every live
        acting member — the forward-completion half of the reference's
        in-progress-op handling. True only when EVERY live member took
        the push: acking on anything less would cover a write that still
        lives on too few members to survive the next failure."""
        entry = pg.latest_objects().get(name)
        if entry is None:
            return True
        ec = self.codec(pg.pool)
        ok = True
        for pos, osd in enumerate(acting):
            if (osd in (self.id, _NONE) or self.osdmap.is_down(osd)
                    or osd in pg.backfill_targets):
                continue
            shard = pos if ec is not None else None
            if entry["kind"] == "delete":
                payload = {"entry": entry, "has_data": False}
                raw = b""
            else:
                got = await self._object_for_push(
                    pg, entry, shard, acting
                )
                if got is None:
                    ok = False  # sources unavailable right now
                    continue
                raw, attrs = got
                payload = {"entry": entry, "has_data": True,
                           "attrs": _attrs_to(attrs)}
            try:
                await self._peer_call(
                    osd, "obj_push",
                    {"pgid": [pg.pool, pg.ps], "shard": shard,
                     **payload},
                    timeout=5.0, raw=raw,
                )
            except (asyncio.TimeoutError, RuntimeError):
                ok = False
        return ok

    async def _load_state_ec(
        self, pg: PG, acting: list[int], name: str, need_data: bool = True
    ) -> ObjectState:
        """EC object context: decode the current object (the RMW read leg,
        ECBackend::start_rmw's reads), xattrs off our shard's attrs."""
        entry = pg.latest_objects().get(name)
        exists = entry is not None and entry["kind"] != "delete"
        state = ObjectState(exists=exists, omap_supported=False)
        if exists:
            if need_data:
                state.data = bytearray(
                    await self._primary_read(pg, acting, name)
                )
            state.xattrs = self._head_xattrs(pg, acting, name)
        return state

    async def _fan_ec_write(
        self, pg: PG, acting: list[int], name: str, data: bytes,
        entry: dict, xattrs: dict[str, bytes] | None = None,
        user_blob: bytes | None = None,
        pre_encoded: dict[int, bytes] | None = None,
    ) -> None:
        """Encode and ship whole shards to every acting position
        (ECBackend sub-write fan-out). `pre_encoded` carries shards
        already produced by the batch service outside the PG lock."""
        ec = self.codec(pg.pool)
        if pre_encoded is not None:
            encoded = pre_encoded
        else:
            with self.perf.time("l_encode"):
                encoded = await self.encode_service.encode(ec, data)
        hinfo = HashInfo.from_shards(encoded, ec.get_chunk_count())
        attrs = {"ver": entry["obj_ver"], "hinfo": hinfo,
                 "size": len(data)}
        if xattrs:
            attrs["xattr"] = json.dumps(
                {k: v.hex() for k, v in xattrs.items()}, sort_keys=True
            ).encode()
        if user_blob is not None:
            attrs["user"] = user_blob
        pg._full_mut[name] = entry["obj_ver"]
        waits = []
        for pos, osd in enumerate(acting):
            if (osd == _NONE or self.osdmap.is_down(osd)
                    or osd in pg.backfill_targets):
                continue  # degraded write: that shard stays missing
            if osd == self.id:
                txn = Transaction().write(
                    pg.coll, shard_name(name, pos), encoded[pos],
                    attrs=attrs,
                )
                pg.append_log(txn, entry)
                self.store.queue_transaction(txn)
                continue
            waits.append(
                self._sub_op_persist(
                    pg, osd, "ec_sub_write",
                    {"pgid": [pg.pool, pg.ps], "shard": pos,
                     "entry": entry, "attrs": _attrs_to(attrs)},
                    raw=encoded[pos],
                )
            )
        if waits:
            with self.perf.time("l_fan"):
                await asyncio.gather(*waits)

    # -- sub-stripe EC overwrite (start_rmw / ExtentCache analogue) -----------

    async def _prepare_partial_ec(
        self, pg: PG, acting: list[int], name: str, ops: list[dict],
        datas: list[bytes], snapc: dict | None,
    ) -> dict | None:
        """The read+encode leg of a sub-stripe RMW, run OUTSIDE the PG
        lock (ECBackend::start_rmw's reads + ECTransaction's re-encode,
        src/osd/ECBackend.cc:1830, ECTransaction.cc:101): map the write
        ops to intra-chunk column windows, read exactly those columns of
        the k data shards, patch the client bytes in, and re-encode the
        windows through the batch service. Returns the per-shard
        sub-extents for _primary_ops to commit, or None when the vector
        doesn't qualify (growth, degraded data shard, clone-on-write
        pending, non-column-independent codec) — the caller then takes
        the whole-object path. The returned ctx holds an ExtentCache
        reservation the caller MUST release."""
        ec = self.codec(pg.pool)
        if ec is None or not getattr(ec, "column_independent", False):
            return None
        if not ops or any(op["op"] != "write" for op in ops):
            return None
        if len(datas) != len(ops):
            return None
        entry = pg.latest_objects().get(name)
        if entry is None or entry["kind"] == "delete":
            return None
        base_ver = entry["obj_ver"]
        my = self._my_shard(pg, acting)
        if my is None:
            return None
        try:
            attrs = self.store.getattrs(pg.coll, shard_name(name, my))
        except StoreError:
            return None
        size = attrs.get("size")
        if attrs.get("ver") != base_ver or not size:
            return None
        writes: list[tuple[int, int, bytes]] = []
        for op, data in zip(ops, datas):
            off = int(op.get("off", 0))
            if not data or off + len(data) > size:
                return None  # growth or no-op: whole-object path
            writes.append((off, len(data), data))
        if snapc:
            ss = load_snapset(self._head_xattrs(pg, acting, name))
            if int(snapc.get("seq", 0)) > ss["seq"]:
                return None  # make_writeable must clone first
        k, n = ec.get_data_chunk_count(), ec.get_chunk_count()
        bs = ec.get_chunk_size(size)
        unit = ec.get_chunk_size(1)
        intervals = write_column_intervals(
            [(o, ln) for o, ln, _ in writes], bs, unit
        )
        if sum(hi - lo for lo, hi in intervals) >= bs:
            return None  # windows span the whole stripe: nothing saved
        token = await pg.extents.reserve(name, intervals)
        try:
            sub: dict[int, list[tuple[int, bytes]]] = {}
            for lo, hi in intervals:
                w = hi - lo
                if ec.get_chunk_size(k * w) != w:
                    raise _PartialUnfit
                window = bytearray(k * w)
                pieces = await asyncio.gather(*(
                    self._read_shard_columns(
                        pg, acting, name, ec.chunk_index(logical),
                        lo, w, base_ver,
                    )
                    for logical in range(k)
                ))
                for logical, piece in enumerate(pieces):
                    window[logical * w: (logical + 1) * w] = piece
                before = bytes(window)
                patch_window(window, (lo, hi), k, writes, bs)
                encoded = await self.encode_service.encode(
                    ec, bytes(window)
                )
                for logical in range(k):
                    phys = ec.chunk_index(logical)
                    seg = bytes(window[logical * w: (logical + 1) * w])
                    if seg != before[logical * w: (logical + 1) * w]:
                        sub.setdefault(phys, []).append((lo, seg))
                for logical in range(k, n):
                    phys = ec.chunk_index(logical)
                    sub.setdefault(phys, []).append((lo, encoded[phys]))
            return {
                "token": token, "base_obj_ver": base_ver,
                "size": size, "sub": sub, "intervals": intervals,
            }
        except _PartialUnfit:
            pg.extents.release(token)
            return None
        except Exception:
            pg.extents.release(token)
            raise

    async def _read_shard_columns(
        self, pg: PG, acting: list[int], name: str, phys: int,
        lo: int, w: int, base_ver: int,
    ) -> bytes:
        """Columns [lo, lo+w) of one data shard at version >= base_ver.
        `>=` not `==`: a concurrent DISJOINT sub-stripe write bumps the
        shard version without touching our columns (reservation excludes
        overlapping ones), and an intervening whole-object write is
        fenced at commit via _full_mut — so newer is safe here."""
        osd = acting[phys] if phys < len(acting) else _NONE
        if (osd == _NONE or self.osdmap.is_down(osd)
                or osd in pg.backfill_targets):
            raise _PartialUnfit
        sname = shard_name(name, phys)
        if osd == self.id:
            try:
                attrs = self.store.getattrs(pg.coll, sname)
                data = self.store.read(pg.coll, sname)
            except StoreError:
                raise _PartialUnfit
            if (attrs.get("ver") or 0) < base_ver:
                raise _PartialUnfit
            piece = data[lo: lo + w]
        else:
            try:
                rep = await self._peer_call(
                    osd, "obj_read",
                    {"coll": pg.coll, "name": sname,
                     "runs": [[lo, w]]},
                    timeout=2.0,
                )
            except (asyncio.TimeoutError, RuntimeError):
                raise _PartialUnfit
            if not rep.get("ok"):
                raise _PartialUnfit
            if (_attrs_from(rep).get("ver") or 0) < base_ver:
                raise _PartialUnfit
            piece = rep["_raw"]
        if len(piece) != w:
            raise _PartialUnfit
        return piece

    def _partial_shard_txn(
        self, txn: Transaction, pg: PG, sname: str, pos: int,
        extents: list[tuple[int, bytes]], new_ver: int,
    ) -> None:
        """One shard's share of a sub-stripe write: patch the extents via
        write_at (store traffic = bytes touched), bump the version, and
        refresh this position's crc in the hinfo attr — each shard keeps
        its OWN position's digest exact, which is all deep scrub ever
        checks against it. A shard that is absent or not at new_ver-1
        takes the log entry only and stays stale for recovery to repair
        (the reference records it missing the same way)."""
        try:
            old = self.store.read(pg.coll, sname)
            attrs = self.store.getattrs(pg.coll, sname)
        except StoreError:
            return
        if attrs.get("ver") != new_ver - 1:
            return
        new_attrs: dict = {"ver": new_ver}
        if extents:
            patched = bytearray(old)
            for off, data in extents:
                patched[off: off + len(data)] = data
                txn.write_at(pg.coll, sname, off, data)
            hinfo = attrs.get("hinfo")
            if hinfo is not None:
                hashes = list(hinfo.cumulative_shard_hashes)
                if pos < len(hashes):
                    hashes[pos] = ceph_crc32c(SEED, bytes(patched))
                new_attrs["hinfo"] = HashInfo(len(patched), hashes)
        txn.setattrs(pg.coll, sname, new_attrs)

    async def _fan_ec_partial(
        self, pg: PG, acting: list[int], name: str, entry: dict,
        partial: dict,
    ) -> None:
        """Commit leg of the sub-stripe RMW: per-shard sub-extents to
        touched data + parity positions, a metadata-only version bump to
        untouched data shards (their bytes didn't change but the object
        version did), the log entry to everyone. Wire cost scales with
        the column windows, never the object size."""
        self.perf.inc("op_w_partial")
        sub = partial["sub"]
        waits = []
        for pos, osd in enumerate(acting):
            if (osd == _NONE or self.osdmap.is_down(osd)
                    or osd in pg.backfill_targets):
                continue
            extents = sub.get(pos, [])
            if osd == self.id:
                txn = Transaction()
                self._partial_shard_txn(
                    txn, pg, shard_name(name, pos), pos, extents,
                    entry["obj_ver"],
                )
                pg.append_log(txn, entry)
                self.store.queue_transaction(txn)
                continue
            payload = {
                "pgid": [pg.pool, pg.ps], "shard": pos,
                "entry": entry, "partial": True,
                "extents": [[off, len(d)] for off, d in extents],
            }
            waits.append(
                self._sub_op_persist(
                    pg, osd, "ec_sub_write", payload,
                    raw=b"".join(d for _off, d in extents),
                )
            )
        if waits:
            await asyncio.gather(*waits)

    async def _fan_ec_delete(
        self, pg: PG, acting: list[int], entry: dict
    ) -> None:
        pg._full_mut[entry["name"]] = entry["obj_ver"]
        waits = []
        for pos, osd in enumerate(acting):
            if (osd == _NONE or self.osdmap.is_down(osd)
                    or osd in pg.backfill_targets):
                continue
            if osd == self.id:
                txn = Transaction().remove(
                    pg.coll, shard_name(entry["name"], pos)
                )
                pg.append_log(txn, entry)
                self.store.queue_transaction(txn)
                continue
            waits.append(
                self._sub_op_persist(
                    pg, osd, "ec_sub_write",
                    {"pgid": [pg.pool, pg.ps], "shard": pos,
                     "entry": entry},
                )
            )
        if waits:
            await asyncio.gather(*waits)

    async def _h_rep_ops(self, conn, p) -> None:
        self._enqueue_subop(p, self._do_rep_ops, conn)

    async def _do_rep_ops(self, conn, p) -> None:
        """Replica-side op-vector application (the sub-op carries the ops,
        the reference carries the compiled transaction — both re-apply
        deterministically; _sub_op_persist guarantees in-order arrival)."""
        self._trace(p.get("trace_id"), "rep_ops apply")
        pg = self._pg_of(p["pgid"])
        e = p["entry"]
        async with pg.lock:
            if e["version"] > pg.last_update:
                datas, off = [], 0
                for ln in p.get("data_lens", []):
                    datas.append(p["_raw"][off: off + ln])
                    off += ln
                state = self._load_state_local(pg, e["name"])
                try:
                    execute_ops(state, p["ops"], datas)
                except OpError:
                    pass  # primary already validated; state is what counts
                user = None
                try:
                    user = self.store.getattrs(
                        pg.coll, e["name"]
                    ).get("user")
                except StoreError:
                    pass
                txn = self._persist_state_txn(
                    pg, e["name"], state, e["obj_ver"], keep_user=user
                )
                pg.append_log(txn, e)
                self.store.queue_transaction(txn)
                self.perf.inc("subop_w")
        self._reply_peer(conn, p["tid"], {"ok": True})

    def _omap_delta_txn(
        self, txn: Transaction, coll: str, name: str, delta: dict
    ) -> None:
        if delta.get("clear"):
            existing = self.store.omap_get(coll, name)
            if existing:
                txn.omap_rmkeys(coll, name, list(existing))
        if delta.get("rms"):
            txn.omap_rmkeys(
                coll, name, [bytes.fromhex(k) for k in delta["rms"]]
            )
        if delta.get("sets"):
            txn.omap_setkeys(
                coll, name,
                {bytes.fromhex(k): bytes.fromhex(v)
                 for k, v in delta["sets"].items()},
            )

    async def _primary_write(
        self, pg: PG, acting: list[int], name: str, data: bytes,
        user_attrs: dict | None = None, omap_delta: dict | None = None,
    ) -> None:
        """Full-object write fan-out. `user_attrs` (cls xattrs) ride along
        as a json blob on every replica/shard; a plain client write_full
        resets them, matching its replace-the-object semantics.
        `omap_delta` (cls omap mutations) replicates exactly."""
        entry = {
            "version": pg.last_update + 1,
            "name": name,
            "obj_ver": self._obj_version(pg, name) + 1,
            "kind": "modify",
            "epoch": self.osdmap.epoch,
        }
        user_blob = (
            json.dumps(user_attrs, sort_keys=True).encode()
            if user_attrs else None
        )
        self._check_min_size(pg, acting)
        ec = self.codec(pg.pool)
        if ec is None:
            attrs = {"ver": entry["obj_ver"]}
            if user_blob is not None:
                attrs["user"] = user_blob
            else:
                # a plain write_full replaces the object, but cls writes
                # and client data writes must not clobber each other's
                # orthogonal attrs
                try:
                    old = self.store.getattrs(pg.coll, name)
                    if old.get("xattr"):
                        attrs["xattr"] = old["xattr"]
                except StoreError:
                    pass
            txn = Transaction().write(pg.coll, name, data, attrs=attrs)
            if omap_delta:
                self._omap_delta_txn(txn, pg.coll, name, omap_delta)
            pg.append_log(txn, entry)
            self.store.queue_transaction(txn)
            payload = {"pgid": [pg.pool, pg.ps], "entry": entry,
                       "attrs": _attrs_to(attrs)}
            if omap_delta:
                payload["omap_delta"] = omap_delta
            waits = [
                self._sub_op_persist(pg, osd, "rep_write", payload,
                                     raw=data)
                for osd in acting
                if osd not in (self.id, _NONE)
                and not self.osdmap.is_down(osd)
                and osd not in pg.backfill_targets
            ]
            if waits:
                with self.perf.time("l_fan"):
                    await asyncio.gather(*waits)
            return
        await self._fan_ec_write(
            pg, acting, name, data, entry, user_blob=user_blob
        )

    async def _primary_delete(
        self, pg: PG, acting: list[int], name: str
    ) -> None:
        entry = {
            "version": pg.last_update + 1,
            "name": name,
            "obj_ver": self._obj_version(pg, name) + 1,
            "kind": "delete",
            "epoch": self.osdmap.epoch,
        }
        self._check_min_size(pg, acting)
        ec = self.codec(pg.pool)
        if ec is not None:
            await self._fan_ec_delete(pg, acting, entry)
            return
        txn = Transaction().remove(pg.coll, name)
        pg.append_log(txn, entry)
        self.store.queue_transaction(txn)
        waits = [
            self._sub_op_persist(
                pg, osd, "rep_write",
                {"pgid": [pg.pool, pg.ps], "entry": entry},
            )
            for osd in acting
            if osd not in (self.id, _NONE)
            and not self.osdmap.is_down(osd)
            and osd not in pg.backfill_targets
        ]
        if waits:
            await asyncio.gather(*waits)

    async def _recover_read_error(
        self, pg: PG, acting: list[int], name: str, shard: int | None,
        entry: dict,
    ):
        """Self-healing read (PrimaryLogPG::rep_repair_primary_object):
        our local copy/shard raised EIO — pull the object from a peer
        replica (replicated) or reconstruct the lost shard by decoding
        the survivors (EC), write-back-repair the local copy, and hand
        the recovered (data, attrs) to the caller so the client op
        succeeds without ever seeing the error. None when no verified
        source is reachable (the caller falls back / retries)."""
        ver = entry["obj_ver"]
        sname = shard_name(name, shard)
        # recovery reads are traced at their own rate
        # (tracer_sample_rate_recovery): a child span when the op is
        # already sampled, else a fresh root so operators can run
        # recovery at 100% while steady-state IO stays sampled
        sp = self.tracer.child("recovery_read")
        if sp is None:
            sp = self.tracer.start("recovery_read", op_type="recovery")
        if sp is not None:
            sp.set_tag("recovery_read", 1)
            sp.set_tag("object", f"{pg.pool}/{sname}")
        try:
            if shard is None:
                got = await self._fetch_copy(
                    pg, sname, ver,
                    [o for o in self._holders_for(acting, None)
                     if o != self.id and o not in pg.backfill_targets],
                )
            else:
                got = await self._rebuild_shard(
                    pg, name, shard, acting, ver, exclude=self.id
                )
            if got is None:
                if sp is not None:
                    sp.set_tag("error", "no verified source reachable")
                return None
            data, attrs = got
            try:
                txn = Transaction()
                self._write_fetched(txn, pg.coll, sname, data, attrs)
                self.store.queue_transaction(txn)
            except StoreError:
                # a store that cannot take the write-back (fenced, full)
                # still serves the client from the recovered bytes
                pass
            self.perf.inc("read_error_repaired")
            if (d := self.dlog.dout(0)) is not None:
                d(f"osd.{self.id}: read error on {pg.coll}/{sname} "
                  f"healed from peers (recovery read, ver {ver})")
            self._cluster_log(
                "WRN",
                f"osd.{self.id}: read error on {pg.coll}/{sname} "
                f"healed from peers",
            )
            return data, attrs
        finally:
            if sp is not None:
                sp.finish()

    async def _primary_read(
        self, pg: PG, acting: list[int], name: str
    ) -> bytes:
        entry = pg.latest_objects().get(name)
        if entry is None or entry["kind"] == "delete":
            raise StoreError("ENOENT", f"no such object {name!r}")
        ec = self.codec(pg.pool)
        if ec is None:
            try:
                data = self.store.read(pg.coll, name)
                attrs = self.store.getattrs(pg.coll, name)
                if attrs.get("ver") == entry["obj_ver"]:
                    return data
            except StoreError as e:
                if e.code == "EIO":
                    # at-rest corruption / device read error: heal from
                    # a replica before the client ever sees it
                    got = await self._recover_read_error(
                        pg, acting, name, None, entry
                    )
                    if got is not None:
                        return got[0]
            # local copy missing/stale (self-backfilling primary):
            # serve from any current-version holder instead of wedging
            got = await self._fetch_copy(
                pg, name, entry["obj_ver"],
                [o for o in self._holders_for(acting, None)
                 if o != self.id and o not in pg.backfill_targets],
            )
            if got is None:
                raise RuntimeError(
                    f"no current copy of {name!r} reachable"
                )  # retryable
            return got[0]

        # EC: probe current-version shard availability at acting homes
        available: dict[int, int] = {}
        chunks: dict[int, bytes] = {}
        size = None
        for pos, osd in enumerate(acting):
            if (osd == _NONE or self.osdmap.is_down(osd)
                    or osd in pg.backfill_targets):
                continue
            if osd == self.id:
                try:
                    data = self.store.read(pg.coll, shard_name(name, pos))
                    attrs = self.store.getattrs(
                        pg.coll, shard_name(name, pos)
                    )
                except StoreError as e:
                    if e.code == "EIO":
                        # our shard is rotten: reconstruct it from the
                        # survivors, rewrite it, and serve the read
                        got = await self._recover_read_error(
                            pg, acting, name, pos, entry
                        )
                        if (
                            got is not None
                            and got[1].get("ver") == entry["obj_ver"]
                        ):
                            available[pos] = osd
                            chunks[pos] = got[0]
                            size = got[1].get("size", size)
                    continue
                if attrs.get("ver") == entry["obj_ver"]:
                    available[pos] = osd
                    chunks[pos] = data
                    size = attrs.get("size", size)
            else:
                available[pos] = osd
        want = {ec.chunk_index(i)
                for i in range(ec.get_data_chunk_count())}
        async def _fetch_shard(s: int) -> tuple[int, dict]:
            try:
                return s, await self._peer_call(
                    available[s], "obj_read",
                    {"coll": pg.coll, "name": shard_name(name, s),
                     "ver": entry["obj_ver"]},
                    timeout=2.0, batchable=True,
                )
            except (asyncio.TimeoutError, RuntimeError):
                return s, {"ok": False}

        while True:
            minimum = ec.minimum_to_decode(want, set(available))
            fetch = [s for s in minimum if s not in chunks]
            failed = None
            # all missing shards in flight at once: one gather instead of
            # k serial round trips, and same-tick fetches to one peer ride
            # a single batched sub-op frame
            results = await asyncio.gather(
                *(_fetch_shard(s) for s in fetch)
            )
            for s, rep in results:
                if not rep.get("ok"):
                    if failed is not None:
                        continue  # one miss already drives the retry
                    # acting home lacks the shard (mid-recovery interval):
                    # previous-interval strays may still hold it
                    stray = await self._fetch_copy(
                        pg, shard_name(name, s), entry["obj_ver"],
                        [o for o in self._up_peers()
                         if o not in set(acting)],
                    )
                    if stray is not None:
                        chunks[s] = stray[0]
                        if size is None:
                            size = stray[1].get("size")
                        continue
                    failed = s
                    continue
                chunks[s] = rep["_raw"]
                if size is None:
                    size = _attrs_from(rep).get("size")
            if failed is None:
                break
            del available[failed]
            chunks.pop(failed, None)
        decoded = await self.encode_service.decode(
            ec, want, {s: chunks[s] for s in minimum}
        )
        out = b"".join(
            decoded[ec.chunk_index(i)]
            for i in range(ec.get_data_chunk_count())
        )
        return out[:size] if size is not None else out

    def _primary_stat(self, pg: PG, name: str) -> dict:
        entry = pg.latest_objects().get(name)
        if entry is None or entry["kind"] == "delete":
            raise StoreError("ENOENT", f"no such object {name!r}")
        out = {"obj_ver": entry["obj_ver"],
               "pg_version": entry["version"]}
        # size without shipping data: local length (replicated) or the
        # size attr stamped on our shard (EC) — never a decode read
        ec = self.codec(pg.pool)
        acting, _ = self.acting_of(pg.pool, pg.ps)
        sname = shard_name(
            name, self._my_shard(pg, acting) if ec is not None else None
        )
        try:
            if ec is None:
                out["size"] = len(self.store.read(pg.coll, sname))
            else:
                size = self.store.getattrs(pg.coll, sname).get("size")
                if size is not None:
                    out["size"] = size
        except StoreError:
            pass  # mid-recovery: the client's operate fallback covers it
        return out

    # -- balanced replica reads & EC direct-shard reads ------------------------
    # (the reference's Octopus balanced reads: osd_read_from_replica /
    # CEPH_OSD_FLAG_BALANCE_READS lets a clean replica serve reads; here
    # the license is an explicit activation marker from the primary,
    # cross-checked against the mon's interval archive, and every state
    # the marker cannot vouch for bounces back with a redirect reply)

    async def _broadcast_activate(
        self, pg: PG, acting: list[int]
    ) -> None:
        """Hand every clean acting member the activation marker that
        licenses it to serve balanced reads this interval. Best-effort:
        write correctness never depends on the marker, so a lost
        broadcast only costs that member its share of read traffic."""
        marker = {
            "pgid": [pg.pool, pg.ps],
            "les": pg.les,
            "acting": list(acting),
            "backfill": sorted(pg.backfill_targets),
        }
        for osd in acting:
            if osd in (self.id, _NONE) or self.osdmap.is_down(osd):
                continue
            try:
                await self._peer_call(
                    osd, "pg_activate", dict(marker), timeout=2.0
                )
            except (asyncio.TimeoutError, RuntimeError):
                pass  # member serves primary-only until the next pass

    async def _h_pg_activate(self, conn, p) -> None:
        """The primary finished peering (or drained a backfill target)
        and vouches for this acting set: keep the newest marker. No
        locks taken — validity is re-derived per read from the marker
        plus the mon's interval archive, so racing markers from an old
        reign lose to the history check even if they land last."""
        pg = self._pg_of(p["pgid"])
        mk = pg.replica_marker
        if mk is None or p["les"] >= mk["les"]:
            pg.replica_marker = {
                "les": p["les"],
                "acting": list(p["acting"]),
                "backfill": list(p.get("backfill") or ()),
            }
        self._reply_peer(conn, p["tid"], {"ok": True})

    async def _replica_read_ok(
        self, pg: PG, acting: list[int], primary: int
    ) -> bool:
        """May this acting member serve a read it is not primary for?
        Proof of currency = the primary's activation marker for exactly
        this acting set, with us not a backfill target, cross-checked
        against the mon's interval archive: an interval that STARTED
        after the marker's activation epoch means membership flapped
        since the primary vouched for us (even if the flap's epochs
        never reached us — replicas coalesce map updates), so redirect.
        The archive fetch is one bulk mon query memoized per map epoch
        (_pg_history); steady-state balanced reads stay local."""
        if primary == self.id:
            return pg.active and not pg.self_backfill
        mk = pg.replica_marker
        if (
            mk is None
            or pg.self_backfill
            or self.id in mk["backfill"]
            or list(acting) != list(mk["acting"])
        ):
            return False
        ivs = await self._pg_history(pg)
        if ivs is None:
            return False  # mon unreachable: cannot prove, do not serve
        return not ivs or ivs[-1][0] <= mk["les"]

    async def _serve_balanced_read(
        self, conn, p, pool_id, name, ps, acting, primary
    ) -> bool:
        """Serve a read-only client op as a NON-primary acting member.
        True = a reply went out (data or the same terminal errno the
        primary would give); False sends the caller to the redirect
        path. Served object data is version-checked against our own
        inventory, which sub-op transactions advance atomically with
        the data — with a valid marker every acked write is present, so
        a balanced read can never return bytes a primary read wouldn't."""
        pg = self._pg_of((pool_id, ps))
        if self.codec(pool_id) is not None:
            return False  # EC logical reads decode at the primary
        pool = self.osdmap.pools.get(pool_id)
        if pool is not None and pool.tier_of >= 0:
            return False  # cache-tier promotion is primary-side logic
        if p.get("snapid") is not None or p.get("snapc") is not None:
            return False  # snap resolution walks primary-side state
        if not await self._replica_read_ok(pg, acting, primary):
            return False
        sp = self.tracer.child(
            "balanced_read", tags={"object": f"{pool_id}/{name}"}
        )
        reply_raw = b""
        try:
            if p["op"] == "read":
                entry = pg.latest_objects().get(name)
                if entry is None or entry["kind"] == "delete":
                    raise StoreError(
                        "ENOENT", f"no such object {name!r}"
                    )
                try:
                    data = self.store.read(pg.coll, name)
                    attrs = self.store.getattrs(pg.coll, name)
                except StoreError as e:
                    if e.code == "EIO":
                        self._report_read_error(pg, name, None)
                    return False
                if attrs.get("ver") != entry["obj_ver"]:
                    return False  # copy lags: let the primary serve
                reply_raw = data
                result = {}
            elif p["op"] == "stat":
                result = self._primary_stat(pg, name)
            elif p["op"] == "ops" and not is_mutating(p.get("ops") or ()):
                ops, datas, off = p["ops"], [], 0
                for ln in p.get("data_lens", []):
                    datas.append(p["_raw"][off: off + ln])
                    off += ln
                op_results, reply_raw = await self._primary_ops(
                    pg, acting, name, ops, datas, None
                )
                result = {"results": op_results}
            else:
                return False  # mutations/exotica belong to the primary
            reply = {"tid": p["tid"], "ok": True, **result}
        except (StoreError, ClsError, OpError) as e:
            if isinstance(e, StoreFatalError) or e.code == "EROFS":
                return False  # store fenced: we are about to go down
            # marker-valid state means this terminal errno IS the
            # cluster's answer (every acked create/delete reached us)
            reply = {"tid": p["tid"], "ok": False, "error": str(e),
                     "errno": e.code}
            reply_raw = b""
        except asyncio.CancelledError:
            raise
        # cephlint: disable=error-taxonomy (anything unexpected redirects to the primary)
        except Exception:
            return False
        finally:
            if sp is not None:
                sp.finish()
        self.perf.inc("read_balanced")
        conn.send_message(
            Message(type="osd_op_reply", tid=p["tid"],
                    epoch=self.osdmap.epoch,
                    payload=reply, raw=reply_raw)
        )
        return True

    async def _serve_shard_read(
        self, conn, p, pool_id, name, ps, acting, primary
    ) -> None:
        """EC direct-shard read: return the clipped bytes of OUR data
        shard with the object version, so the client can check that all
        k shards agree and assemble the stripe without a primary gather
        or decode. Every failure mode — wrong home, unproven interval,
        stale or rotten shard — redirects, and the client falls back to
        the primary decode path."""
        pg = self._pg_of((pool_id, ps))

        def _send(payload: dict, raw: bytes = b"") -> None:
            conn.send_message(
                Message(type="osd_op_reply", tid=p["tid"],
                        epoch=self.osdmap.epoch,
                        payload=payload, raw=raw)
            )

        def _redirect(why: str) -> None:
            self.perf.inc("read_redirected")
            mk = pg.replica_marker
            _send(redirect_reply(
                p["tid"], primary, self.osdmap.epoch, why,
                backfill=(mk or {}).get("backfill"),
            ))

        pos = p.get("shard")
        ec = self.codec(pool_id)
        pool = self.osdmap.pools.get(pool_id)
        if (
            ec is None
            or not isinstance(pos, int)
            or (pool is not None and pool.tier_of >= 0)
            or pos >= len(acting)
            or acting[pos] != self.id
        ):
            return _redirect("not this shard's clean home")
        if not await self._replica_read_ok(pg, acting, primary):
            return _redirect("unproven interval")
        entry = pg.latest_objects().get(name)
        if entry is None or entry["kind"] == "delete":
            # the primary serves the authoritative ENOENT on fallback
            return _redirect("no such object")
        sname = shard_name(name, pos)
        try:
            data = self.store.read(pg.coll, sname)
            attrs = self.store.getattrs(pg.coll, sname)
        except StoreError as e:
            if e.code == "EIO":
                self._report_read_error(pg, name, pos)
            return _redirect("shard unreadable")
        if attrs.get("ver") != entry["obj_ver"]:
            return _redirect("shard stale")
        size = attrs.get("size")
        if size is None:
            return _redirect("shard missing size attr")
        # this shard holds data chunk `dpos`: its logical bytes span
        # [dpos*cs, dpos*cs+cs) pre-truncation, clamped by the object
        # size attr (padding never leaves the OSD), then clipped to the
        # client's requested run
        cs = len(data)
        dpos = int(p.get("dpos", 0))
        lo, hi = dpos * cs, min((dpos + 1) * cs, int(size))
        run = p.get("run")
        if run is not None:
            lo = max(lo, int(run[0]))
            hi = min(hi, int(run[0]) + int(run[1]))
        piece = data[lo - dpos * cs: hi - dpos * cs] if hi > lo else b""
        sp = self.tracer.child(
            "shard_read",
            tags={"object": f"{pool_id}/{sname}", "dpos": dpos},
        )
        if sp is not None:
            sp.finish()
        self.perf.inc("read_shard_direct")
        _send({"tid": p["tid"], "ok": True, "ver": entry["obj_ver"],
               "cs": cs, "size": int(size), "lo": lo}, piece)

    def _report_read_error(
        self, pg: PG, name: str, shard: int | None
    ) -> None:
        """A balanced/shard read hit at-rest EIO on our copy: tell the
        primary so it runs the write-back repair now instead of waiting
        for the next scrub (the replica-reported leg of
        rep_repair_primary_object), while we redirect the client."""
        acting, primary = self.acting_of(pg.pool, pg.ps)
        if (
            primary in (-1, _NONE, self.id)
            or self.osdmap.is_down(primary)
        ):
            return

        async def report() -> None:
            try:
                await self._peer_call(
                    primary, "read_error_report",
                    {"pgid": [pg.pool, pg.ps], "name": name,
                     "shard": shard, "reporter": self.id},
                    timeout=5.0,
                )
            except (asyncio.TimeoutError, RuntimeError):
                pass  # scrub remains the backstop

        self._spawn(report())

    async def _h_read_error_report(self, conn, p) -> None:
        # repair takes the fetch/rebuild/push path: run it off the
        # dispatch loop through the per-PG sub-op queue
        self._enqueue_subop(p, self._do_read_error_report, conn)

    async def _do_read_error_report(self, conn, p) -> None:
        """Primary side of a replica-reported read error: rebuild the
        reporter's copy/shard from the survivors and push it back — the
        same write-back _recover_read_error runs for our own EIOs,
        driven by a replica's instead."""
        pg = self._pg_of(p["pgid"])
        name, reporter = p["name"], p["reporter"]
        shard = p.get("shard")
        acting, primary = self.acting_of(pg.pool, pg.ps)
        if (
            primary != self.id
            or not pg.active
            or reporter not in acting
            or (shard is not None
                and (shard >= len(acting) or acting[shard] != reporter))
        ):
            self._reply_peer(conn, p["tid"], {"ok": False})
            return
        entry = pg.latest_objects().get(name)
        if entry is None or entry["kind"] == "delete":
            # deleted since the report: nothing left to heal
            self._reply_peer(conn, p["tid"], {"ok": True})
            return
        got = await self._object_for_push(pg, entry, shard, acting)
        if got is None:
            self._reply_peer(conn, p["tid"], {"ok": False})
            return
        data, attrs = got
        try:
            # force: the reporter's copy is rotten AT the current
            # version, so the push must overwrite an equal-version row
            await self._peer_call(
                reporter, "obj_push",
                {"pgid": [pg.pool, pg.ps], "shard": shard,
                 "entry": entry, "has_data": True, "force": True,
                 "attrs": _attrs_to(attrs)},
                timeout=5.0, raw=data,
            )
        except (asyncio.TimeoutError, RuntimeError):
            self._reply_peer(conn, p["tid"], {"ok": False})
            return
        self.perf.inc("read_error_repaired")
        if (d := self.dlog.dout(0)) is not None:
            d(f"osd.{self.id}: osd.{reporter} reported a read error on "
              f"{pg.coll}/{shard_name(name, shard)}; pushed a rebuilt "
              f"copy (ver {entry['obj_ver']})")
        self._cluster_log(
            "WRN",
            f"osd.{self.id}: read error on "
            f"{pg.coll}/{shard_name(name, shard)} reported by "
            f"osd.{reporter} healed by primary push",
        )
        self._reply_peer(conn, p["tid"], {"ok": True})

    async def _primary_call(
        self, pg: PG, acting: list[int], name: str, p: dict
    ) -> dict:
        """Execute an object-class method server-side (rados exec; the
        PrimaryLogPG CEPH_OSD_OP_CALL path): build the context from the
        object's current content + user xattrs, run the method, and write
        dirty results back through the normal backend fan-out so the
        mutation replicates / EC-encodes like any client write."""
        entry = pg.latest_objects().get(name)
        exists = entry is not None and entry["kind"] != "delete"
        data = None
        user_attrs: dict = {}
        if exists:
            data = await self._primary_read(pg, acting, name)
            local = shard_name(
                name, self._my_shard(pg, acting)
            )
            try:
                blob = self.store.getattrs(pg.coll, local).get("user")
            except StoreError:
                blob = None
            if blob:
                user_attrs = json.loads(blob)
        ec = self.codec(pg.pool)
        ctx = MethodContext(
            data=data,
            user_attrs=user_attrs,
            version=entry["obj_ver"] if exists else 0,
            omap=(
                self.store.omap_get(pg.coll, name) if ec is None else None
            ),
            omap_supported=ec is None,
            # lease arithmetic runs on the primary's clock; the offset
            # knob lets tests advance cls time without sleeping
            now=time.time() + float(self.config.get("cls_clock_offset")),
        )
        result = self.cls.call(p["cls"], p["method"], ctx, p.get("input"))
        if ctx.dirty:
            await self._primary_write(
                pg, acting, name,
                ctx.data if ctx.data is not None else b"",
                user_attrs=ctx.user_attrs,
                omap_delta=ctx.omap_delta(),
            )
        return {"result": result}


    # -- watch / notify (PrimaryLogPG watch/notify, src/osd/Watch.cc) ---------
    #
    # Watchers register on an object at its acting primary; a notify fans
    # the payload to every watcher and completes when all have acked (or
    # the per-notify timeout lapses), returning who acked — the librados
    # coordination primitive rbd's exclusive lock rides. Watches are
    # sessions on THIS primary: a new primary (or a restarted one) starts
    # with no watchers and clients must re-watch, matching the reference's
    # watch timeout + reconnect contract.

    WATCHERS_XATTR = "\x01w"

    async def _persist_watchers(
        self, pg, name: str, remove: tuple | None = None
    ) -> None:
        """Mirror the watcher set into a reserved object xattr
        (obc->watchers persisted in object_info): after a primary change
        the NEW primary knows who SHOULD be watching, so notifies report
        them as missed until they re-watch, instead of silently
        succeeding against an empty table. The persisted set MERGES with
        what a previous primary recorded (minus an explicit unwatch) —
        overwriting with only our live sessions would silently drop
        watchers that have not re-watched here yet."""
        key = (pg.pool, pg.ps, name)
        acting, primary = self.acting_of(pg.pool, pg.ps)
        if primary != self.id:
            return
        live = {
            (w, c) for _conn, w, c in self._watchers.get(key, [])
        }
        merged = live | set(
            self._persisted_watchers(pg, acting, name)
        )
        if remove is not None:
            merged.discard(remove)
        persisted = sorted(f"{w}|{c}" for w, c in merged)
        try:
            async with pg.lock:
                # re-check under the lock: a delete may have committed
                # while we awaited it — the setxattr must not resurrect
                # the object as a ghost
                entry = pg.latest_objects().get(name)
                if entry is None or entry["kind"] == "delete":
                    return
                await self._primary_ops(
                    pg, acting, name,
                    [{"op": "setxattr", "name": self.WATCHERS_XATTR,
                      "value": json.dumps(persisted).encode().hex()}],
                    [], None,
                )
        # cephlint: disable=error-taxonomy (best effort: live sessions still work this interval)
        except Exception:
            pass  # best effort: live sessions still work this interval

    def _persisted_watchers(self, pg, acting, name: str) -> list[tuple]:
        raw = self._head_xattrs(pg, acting, name).get(
            self.WATCHERS_XATTR
        )
        if not raw:
            return []
        return [tuple(s.split("|", 1)) for s in json.loads(raw)]

    async def _h_op_watch(self, pg, conn, p) -> dict:
        key = (pg.pool, pg.ps, p["name"])
        entry = (conn, p.get("watcher", conn.peer_name), p.get("cookie", ""))
        watchers = self._watchers.setdefault(key, [])
        if not any(
            w[1] == entry[1] and w[2] == entry[2] for w in watchers
        ):
            watchers.append(entry)
            await self._persist_watchers(pg, p["name"])
        return {}

    async def _h_op_unwatch(self, pg, conn, p) -> dict:
        key = (pg.pool, pg.ps, p["name"])
        me = (p.get("watcher", conn.peer_name), p.get("cookie", ""))
        self._watchers[key] = [
            w for w in self._watchers.get(key, [])
            if (w[1], w[2]) != me
        ]
        await self._persist_watchers(pg, p["name"], remove=me)
        return {}

    async def _h_op_notify(self, pg, conn, p) -> dict:
        key = (pg.pool, pg.ps, p["name"])
        notify_id = next(self._tids)
        waits = {}
        for wconn, wname, cookie in list(self._watchers.get(key, [])):
            if not wconn.is_connected:
                continue
            fut = asyncio.get_event_loop().create_future()
            self._notify_waiters[(notify_id, wname, cookie)] = fut
            waits[(wname, cookie)] = fut
            wconn.send_message(
                Message(
                    type="watch_notify",
                    payload={"pool": pg.pool, "name": p["name"],
                             "notify_id": notify_id,
                             "cookie": cookie,
                             "payload": p.get("payload", "")},
                )
            )
        timeout = p.get("timeout", 5.0)
        acked, missed = [], []
        if waits:
            # one deadline for the whole fan-out: N silent watchers cost
            # one timeout, not N stacked ones
            await asyncio.wait(waits.values(), timeout=timeout)
        for (wname, cookie), fut in waits.items():
            if fut.done():
                acked.append({"watcher": wname, "cookie": cookie})
            else:
                fut.cancel()
                missed.append({"watcher": wname, "cookie": cookie})
            self._notify_waiters.pop((notify_id, wname, cookie), None)
        # watchers persisted by a previous primary that have not
        # re-established a session here are MISSED, not invisible
        # (handle_watch_timeout semantics after failover)
        acting, _primary = self.acting_of(pg.pool, pg.ps)
        seen = {(a["watcher"], a["cookie"]) for a in acked} | {
            (m["watcher"], m["cookie"]) for m in missed
        }
        for wname, cookie in self._persisted_watchers(
            pg, acting, p["name"]
        ):
            if (wname, cookie) not in seen:
                missed.append({"watcher": wname, "cookie": cookie})
        return {"acked": acked, "missed": missed}

    async def _notify_and_reply(self, pg, conn, p) -> None:
        try:
            result = await self._h_op_notify(pg, conn, p)
            reply = {"tid": p["tid"], "ok": True, **result}
        except Exception as e:
            reply = {"tid": p["tid"], "ok": False, "error": str(e)}
        conn.send_message(
            Message(type="osd_op_reply", tid=p["tid"],
                    epoch=self.osdmap.epoch, payload=reply)
        )

    async def _h_notify_ack(self, conn, p) -> None:
        fut = self._notify_waiters.get(
            (p["notify_id"], p.get("watcher", conn.peer_name),
             p.get("cookie", ""))
        )
        if fut is not None and not fut.done():
            fut.set_result(None)

    # -- admin surface + scrub (admin_socket / `ceph daemon` analogue) --------

    async def _h_osd_admin(self, conn, p) -> None:
        """Daemon admin commands over the wire — the role the per-daemon
        unix admin socket plays for `ceph daemon osd.N <cmd>`."""
        try:
            cmd = p["cmd"]
            if cmd == "perf dump":
                result = self.perf_collection.dump()
            elif cmd == "status":
                result = {
                    "osd": self.id,
                    "epoch": self.osdmap.epoch if self.osdmap else 0,
                    "num_pgs": len(self.pgs),
                    "active_pgs": sum(
                        1 for pg in self.pgs.values() if pg.active
                    ),
                    "collections": len(self.store.list_collections()),
                    "ec_launches": self.encode_service.launches,
                    "ec_objects": self.encode_service.objects,
                }
            elif cmd == "pool_stats":
                # per-pool objects/bytes for PGs this OSD is primary of
                # (the pg_stat_t aggregation the mgr's autoscaler reads)
                stats: dict[int, dict] = {}
                for (pool_id, ps), pg in self.pgs.items():
                    acting, primary = self.acting_of(pool_id, ps)
                    if primary != self.id:
                        continue
                    st = stats.setdefault(
                        pool_id, {"objects": 0, "bytes": 0, "pgs": 0}
                    )
                    st["pgs"] += 1
                    for name, entry in pg.latest_objects().items():
                        if entry["kind"] == "delete":
                            continue
                        st["objects"] += 1
                        try:
                            sname = shard_name(
                                name,
                                self._my_shard(pg, acting),
                            ) if self.codec(pool_id) is not None else name
                            attrs = self.store.getattrs(pg.coll, sname)
                            size = attrs.get("size")
                            if size is None:
                                size = len(self.store.read(pg.coll, sname))
                            st["bytes"] += size
                        except StoreError:
                            pass
                result = {str(k): v for k, v in stats.items()}
                comp = getattr(self.store, "compression_stats", None)
                if comp is not None:
                    # store-wide compressed-length bookkeeping (blob
                    # attribution to pools stays in the store's keyspace)
                    result["compression"] = comp()
            elif cmd == "pg ls":
                # PGLS (the rados `ls` primitive): head objects of this
                # pool's PGs we lead (clones/snapdirs stay internal)
                objects = []
                for (pid, ps), pg in self.pgs.items():
                    if pid != p["pool"]:
                        continue
                    if self.acting_of(pid, ps)[1] != self.id:
                        continue
                    for name, e in pg.latest_objects().items():
                        if e["kind"] != "delete" and "\x1f" not in name:
                            objects.append(name)
                result = {"objects": sorted(objects)}
            elif cmd == "log dump":
                result = {"entries": self.logs.dump_recent()}
            elif cmd == "dump_trace":
                result = {
                    "events": list(
                        self.traces.get(p.get("trace_id", ""), [])
                    )
                }
            elif cmd == "dump_tracing":
                # drain the completed-span ring (client spans reported
                # via trace_report included, so one call returns whole
                # client->messenger->osd->store trees)
                result = self.tracer.dump_tracing(
                    drain=not p.get("keep")
                )
            elif cmd == "dump_ops_in_flight":
                result = self.op_tracker.dump_ops_in_flight()
            elif cmd == "dump_historic_ops":
                result = self.op_tracker.dump_historic_ops()
                # cross-link: a historic op's full span timeline is still
                # retrievable while the flight ring holds the trace
                for o in result.get("slowest", []):
                    tid = o.get("trace_id")
                    if tid:
                        o["in_flight_ring"] = self.tracer.flight_has(tid)
            elif cmd == "injectargs":
                # runtime config overrides (`ceph tell osd.N injectargs`):
                # flips the fault knobs, tracer rates, etc. live — the
                # config observers refresh every cached flag, so no
                # restart is needed to arm/disarm faults mid-run
                applied = {}
                for k, v in (p.get("args") or {}).items():
                    self.config.set(k, v)
                    applied[k] = self.config.get(k)
                result = {"applied": applied}
            elif cmd == "injectdataerr":
                # deterministic per-object read EIO on OUR copy/shard
                # (the reference's `injectdataerr` admin command); heals
                # when the object is rewritten, e.g. by a recovery read
                pool_id = p["pool"]
                ps = self.object_pg(pool_id, p["name"])
                pg = self._pg_of((pool_id, ps))
                acting, _primary = self.acting_of(pool_id, ps)
                shard = self._my_shard(pg, acting)
                sname = shard_name(p["name"], shard)
                inject = getattr(self.store, "inject_data_error", None)
                if inject is None:
                    raise RuntimeError(
                        f"{self.store.KIND} has no device-fault surface "
                        "(osd_objectstore=blockstore required)"
                    )
                inject(pg.coll, sname)
                result = {"injected": sname, "coll": pg.coll}
            elif cmd == "scrub":
                result = await self._scrub(
                    p["pool"], deep=p.get("deep", False)
                )
            elif cmd == "repair":
                result = await self._repair(p["pool"])
            else:
                raise RuntimeError(f"unknown admin command {cmd!r}")
            reply = {"tid": p["tid"], "ok": True, "result": result}
        except Exception as e:
            reply = {"tid": p["tid"], "ok": False, "error": str(e)}
        conn.send_message(
            Message(type="osd_admin_reply", tid=p["tid"],
                    payload=reply)
        )

    async def _h_trace_report(self, conn, p) -> None:
        """Adopt a client's finished spans (the Jaeger agent->collector
        hop): one-way, no reply — tracing must never add an RTT.

        A `promote` section is the tail-sampling relay: the client kept
        its completed trace (slow/errored at any sample rate) — adopt
        its spans into the FLIGHT ring (not the sampled ring: an
        unsampled trace must stay invisible to dump_tracing) and
        promote the same trace locally so our own flight spans ride the
        next mgr report alongside the client's."""
        promote = p.get("promote")
        if promote:
            self.tracer.adopt_flight(p.get("spans") or [])
            self.tracer.promote(
                promote.get("trace_id"),
                reason=promote.get("reason", "relay"),
                root=promote.get("root"),
            )
            return
        self.tracer.adopt(p.get("spans") or [])

    async def _h_mgr_capture(self, conn, p) -> None:
        """The mgr pushed fresh SLO capture predicates down the report
        channel (the metrics->traces loop): while a rule is violated,
        matching ops promote their traces at completion."""
        self.tracer.set_capture_predicates(
            p.get("predicates") or [], p.get("ver") or 0
        )

    async def _scrub_fetch(self, pg, sname: str, osd: int,
                           verify: bool = False):
        """One copy's (data, attrs) or an error string. `verify` reads
        device truth through BlockStore.read_verify so the buffer cache
        can never mask at-rest corruption from a deep scrub."""
        if osd == self.id:
            reader = self.store.read
            if verify:
                reader = getattr(self.store, "read_verify", reader)
            try:
                return (
                    reader(pg.coll, sname),
                    self.store.getattrs(pg.coll, sname),
                )
            except StoreError as e:
                # EIO = at-rest corruption a checksumming store caught
                # (BlockStore); distinct from a copy that is simply gone
                return "read_error" if e.code == "EIO" else "missing"
        try:
            rep = await self._peer_call(
                osd, "obj_read",
                {"coll": pg.coll, "name": sname, "verify": verify},
                timeout=2.0,
            )
        except (asyncio.TimeoutError, RuntimeError):
            return "unreachable"
        if not rep.get("ok"):
            return "read_error" if rep.get("error") == "EIO" else "missing"
        return rep["_raw"], _attrs_from(rep)

    #: deep-scrub findings the primary repairs in place when
    #: osd_scrub_auto_repair is set; "inconsistent" (no safe authority)
    #: and "stale"/"missing" (recovery's job) never auto-repair
    _AUTO_REPAIRABLE = frozenset(
        {"digest_mismatch", "read_error", "hinfo_missing"}
    )

    async def _scrub(
        self, pool_id: int, deep: bool, auto_repair_ok: bool = True
    ) -> dict:
        """Primary-driven consistency check over this OSD's primary PGs in
        `pool_id` (PGBackend::be_scan_list shallow; deep re-reads every
        copy/shard: EC shards verify crc32c against the stored HashInfo
        (ECBackend::be_deep_scrub, ECBackend.cc:2461), replicated copies
        compare data digests and flag the minority, like
        be_select_auth_object's majority rule). With
        `osd_scrub_auto_repair` set, a deep scrub that finds repairable
        damage runs the primary-driven repair in place and reports the
        count as "auto_repaired"."""
        from ceph_tpu.common.crc import ceph_crc32c

        errors: list[dict] = []
        ec = self.codec(pool_id)
        for (pid, ps), pg in sorted(self.pgs.items()):
            if pid != pool_id or not pg.active:
                continue
            acting, primary = self.acting_of(pid, ps)
            if primary != self.id:
                continue
            for name, entry in sorted(pg.latest_objects().items()):
                if entry["kind"] == "delete":
                    continue
                copies: dict[int, tuple] = {}  # pos -> (data, attrs)
                for pos, osd in enumerate(acting):
                    if (osd == _NONE or self.osdmap.is_down(osd)
                            or osd in pg.backfill_targets):
                        continue
                    shard = pos if ec is not None else None
                    got = await self._scrub_fetch(
                        pg, shard_name(name, shard), osd, verify=deep
                    )
                    if isinstance(got, str):
                        errors.append(
                            {"pg": [pid, ps], "name": name,
                             "shard": shard, "osd": osd, "error": got}
                        )
                        continue
                    data, attrs = got
                    if attrs.get("ver") != entry["obj_ver"]:
                        errors.append(
                            {"pg": [pid, ps], "name": name,
                             "shard": shard, "osd": osd,
                             "error": "stale"}
                        )
                        continue
                    copies[pos] = (data, attrs)
                if not deep:
                    continue
                if ec is not None:
                    for pos, (data, attrs) in sorted(copies.items()):
                        hinfo = attrs.get("hinfo")
                        err = None
                        if hinfo is None:
                            err = "hinfo_missing"
                        elif ceph_crc32c(
                            0xFFFFFFFF, data
                        ) != hinfo.get_chunk_hash(pos):
                            err = "digest_mismatch"
                        if err:
                            errors.append(
                                {"pg": [pid, ps], "name": name,
                                 "shard": pos, "osd": acting[pos],
                                 "error": err}
                            )
                elif len(copies) > 1:
                    digests = {
                        pos: ceph_crc32c(0xFFFFFFFF, d)
                        for pos, (d, _a) in copies.items()
                    }
                    counts: dict[int, int] = {}
                    for dg in digests.values():
                        counts[dg] = counts.get(dg, 0) + 1
                    best = max(counts.values())
                    if best * 2 > len(digests):
                        # flag minority copies ONLY under a strict digest
                        # majority; a tie (e.g. 1:1 with a replica down)
                        # has no safe authority — auto-picking one could
                        # make repair overwrite the only good copy, so
                        # ties report "inconsistent" and repair skips them
                        auth = next(
                            dg for dg, c in counts.items() if c == best
                        )
                        for pos, dg in sorted(digests.items()):
                            if dg != auth:
                                errors.append(
                                    {"pg": [pid, ps], "name": name,
                                     "shard": None, "osd": acting[pos],
                                     "error": "digest_mismatch"}
                                )
                    else:
                        for pos in sorted(digests):
                            errors.append(
                                {"pg": [pid, ps], "name": name,
                                 "shard": None, "osd": acting[pos],
                                 "error": "inconsistent"}
                            )
        self.perf.inc("scrub_errors", len(errors))
        if deep:
            # refresh the health feed for every PG this pass scanned
            # (zero clears a previously-flagged PG that came back clean)
            scanned = [
                (pid, ps) for (pid, ps), pg in sorted(self.pgs.items())
                if pid == pool_id and pg.active
                and self.acting_of(pid, ps)[1] == self.id
            ]
            for key in scanned:
                self._scrub_incons[key] = 0
            for err in errors:
                key = tuple(err["pg"])
                if key in self._scrub_incons:
                    self._scrub_incons[key] += 1
        result = {"errors": errors}
        if (
            auto_repair_ok
            and deep
            and self.config.get("osd_scrub_auto_repair")
            and any(e["error"] in self._AUTO_REPAIRABLE for e in errors)
        ):
            n = await self._repair_from_report(pool_id, errors)
            result["auto_repaired"] = n
            if (d := self.dlog.dout(0)) is not None:
                d(f"pool {pool_id}: deep scrub auto-repaired {n} of "
                  f"{len(errors)} inconsistencies")
            self._cluster_log(
                "WRN",
                f"osd.{self.id}: pool {pool_id} deep scrub "
                f"auto-repaired {n} of {len(errors)} inconsistencies",
            )
        return result

    async def _repair(self, pool_id: int) -> dict:
        """Deep-scrub, then overwrite every inconsistent copy with content
        rebuilt from VERIFIED sources only (the `ceph pg repair` flow): EC
        shards decode from hinfo-checked survivors, replicated copies pull
        from a digest-majority member — never from the copy being
        repaired."""
        report = await self._scrub(
            pool_id, deep=True, auto_repair_ok=False
        )
        repaired = await self._repair_from_report(
            pool_id, report["errors"]
        )
        return {"repaired": repaired, "errors": report["errors"]}

    async def _repair_from_report(
        self, pool_id: int, errors: list[dict]
    ) -> int:
        from ceph_tpu.common.crc import ceph_crc32c

        ec = self.codec(pool_id)
        repaired = 0
        for err in errors:
            if err["error"] == "inconsistent":
                continue  # no safe authority: surfaced, never auto-fixed
            pid, ps = err["pg"]
            pg = self.pgs[(pid, ps)]
            acting, _ = self.acting_of(pid, ps)
            entry = pg.latest_objects().get(err["name"])
            if entry is None:
                continue
            shard = err["shard"]
            bad_osd = err["osd"]
            # gather verified sources, excluding the copy under repair
            chunks: dict[int, bytes] = {}
            attrs = None
            data = None
            for pos, osd in enumerate(acting):
                if osd in (_NONE, bad_osd) or self.osdmap.is_down(osd):
                    continue
                spos = pos if ec is not None else None
                # repair sources are verified reads: never rebuild a
                # copy from a peer's (possibly rot-masking) cache
                got = await self._scrub_fetch(
                    pg, shard_name(err["name"], spos), osd, verify=True
                )
                if isinstance(got, str):
                    continue
                d, a = got
                if a.get("ver") != entry["obj_ver"]:
                    continue
                if ec is not None:
                    hinfo = a.get("hinfo")
                    if hinfo is None or ceph_crc32c(
                        0xFFFFFFFF, d
                    ) != hinfo.get_chunk_hash(pos):
                        continue  # never decode from an unverified shard
                    chunks[pos] = d
                    attrs = attrs or a
                    if len(chunks) >= ec.get_data_chunk_count():
                        break
                else:
                    chunks[pos] = d
                    attrs = attrs or a
            if ec is not None:
                if len(chunks) < ec.get_data_chunk_count():
                    continue
                # serial repair loop: direct decode (see _rebuild_shard)
                data = ec.decode({shard}, chunks)[shard]
            elif chunks:
                # replicated: the digest-majority copy wins (ties -> the
                # lowest acting position, like be_select_auth_object)
                counts: dict[bytes, int] = {}
                for d in chunks.values():
                    counts[d] = counts.get(d, 0) + 1
                best = max(counts.values())
                data = next(
                    d for _pos, d in sorted(chunks.items())
                    if counts[d] == best
                )
            if data is None or attrs is None:
                continue
            try:
                if bad_osd == self.id:
                    txn = Transaction()
                    self._write_fetched(
                        txn, pg.coll, shard_name(err["name"], shard),
                        data, attrs,
                    )
                    self.store.queue_transaction(txn)
                else:
                    await self._peer_call(
                        bad_osd, "obj_push",
                        {"pgid": [pid, ps], "shard": shard,
                         "entry": entry, "has_data": True,
                         "attrs": _attrs_to(attrs)},
                        timeout=5.0, raw=data,
                    )
                repaired += 1
            except (asyncio.TimeoutError, RuntimeError):
                continue
        return repaired


def _attrs_to(attrs: dict | None) -> dict:
    if attrs is None:
        return {}
    out = {}
    for k, v in attrs.items():
        if isinstance(v, HashInfo):
            out[k] = {"__hinfo__": [v.total_chunk_size,
                                    v.cumulative_shard_hashes]}
        elif isinstance(v, bytes):
            out[k] = {"__bytes__": v.hex()}
        else:
            out[k] = v
    return out


def _attrs_from(p: dict) -> dict:
    raw = p.get("attrs") or {}
    out = {}
    for k, v in raw.items():
        if isinstance(v, dict) and "__hinfo__" in v:
            out[k] = HashInfo(v["__hinfo__"][0], list(v["__hinfo__"][1]))
        elif isinstance(v, dict) and "__bytes__" in v:
            out[k] = bytes.fromhex(v["__bytes__"])
        else:
            out[k] = v
    return out
