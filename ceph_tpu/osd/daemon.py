"""OSDService: the storage daemon (L6).

One process-per-OSD data plane speaking the messenger, mirroring the
reference's structure (src/osd/OSD.cc boot at ceph_osd.cc:106, fast
dispatch at OSD.cc:6877) at mini scale:

  boot      bind messenger -> MonClient subscribe -> send osd_boot with our
            address -> serve once the committed map shows us up
  ops       clients send "osd_op" to the acting primary; the primary drives
            the backend (PrimaryLogPG::do_op -> PGBackend analogues):
              * replicated: apply locally + fan "rep_write" sub-ops to the
                other acting members, ack to the client when all commit
                (ReplicatedBackend sub-write fan-out)
              * EC: encode on the TPU codec, "ec_sub_write" one shard to
                each acting position, ack when all commit
                (ECBackend::start_rmw -> ECSubWrite, ECBackend.cc:1830);
                reads gather minimum_to_decode shards via "ec_sub_read"
                and decode only when degraded (objects_read_async, 2154)
  fencing   an op whose placement disagrees with our map is bounced with
            the current epoch ("wrong_primary"); the Objecter refreshes its
            map and resends — the reference drops stale-epoch ops the same
            way and relies on client resend (epoch-tagged resend contract)
  peering   on every map epoch whose acting set changed, the primary runs
            GetInfo -> GetLog -> GetMissing -> recover (PeeringState.h
            statechart collapsed to one async pass): collect pg_info from
            acting members, adopt the most advanced log (pull objects it
            names that we lack), then push log + objects/shards every
            laggard is missing; EC shards a member lacks are rebuilt by
            decoding from surviving shards. Every sub-write carries its log
            entry, so replicas' logs advance with their data, exactly like
            ECSubWrite carrying log_entries in the reference
  logs      per-PG log in the pg-meta object's omap ("log/<version>" ->
            entry, PGLog.cc role): the authoritative object inventory that
            peering compares and recovery replays
  failure   periodic pings to peers holding PGs with us; a peer silent past
            osd_heartbeat_grace is reported to the mon (OSD.cc:4547
            handle_osd_ping / heartbeat_check), which commits the down mark

Object naming: a replicated object is stored under its name in collection
"pg_<pool>_<ps>"; EC shard i of an object is "<name>.s<i>" in the same
collection — shard identity in the key, as ECBackend's shard_id_t does.
"""

from __future__ import annotations

import asyncio
import json
import zlib

from ceph_tpu.common.config import Config
from ceph_tpu.common.kv import KeyValueDB
from ceph_tpu.msg import Dispatcher, Message, Messenger, Policy
from ceph_tpu.mon.client import MonClient
from ceph_tpu.osd.cls import ClsError, MethodContext, default_handler
from ceph_tpu.osd.ecutil import HashInfo
from ceph_tpu.osd.objectstore import KStore, StoreError, Transaction
from ceph_tpu.osd.osdmap import CRUSH_ITEM_NONE

_NONE = CRUSH_ITEM_NONE


def pg_coll(pool: int, ps: int) -> str:
    return f"pg_{pool}_{ps}"


def shard_name(name: str, shard: int | None) -> str:
    return name if shard is None else f"{name}.s{shard}"


class PG:
    """Per-PG volatile state; durable state lives in the store."""

    META = ".pgmeta"

    def __init__(self, service: "OSDService", pool: int, ps: int):
        self.service = service
        self.pool = pool
        self.ps = ps
        self.coll = pg_coll(pool, ps)
        self.lock = asyncio.Lock()  # serializes writes + peering
        store = service.store
        if not store.collection_exists(self.coll):
            store.queue_transaction(
                Transaction().create_collection(self.coll).touch(
                    self.coll, self.META
                )
            )
        # in-memory mirror of the persisted log (loaded once, then kept in
        # step by append_log): per-op paths read these instead of scanning
        # + json-decoding the whole omap on every write
        self._last_update = 0
        self._inventory: dict[str, dict] = {}
        for e in self._scan_log():
            self._last_update = max(self._last_update, e["version"])
            self._inventory[e["name"]] = e
        #: a primary serves client IO only once peering for the current
        #: interval finished (PeeringState: Peering -> Active); until then
        #: ops bounce with a retryable error, so a revived primary can
        #: never serve ENOENT for an object it simply hasn't learned yet
        self.active = False
        self.last_acting: list[int] | None = None

    # -- the persisted log ----------------------------------------------------

    @property
    def last_update(self) -> int:
        return self._last_update

    def _scan_log(self, from_version: int = 0) -> list[dict]:
        out = []
        for k, v in sorted(
            self.service.store.omap_get(self.coll, self.META).items()
        ):
            if k.startswith(b"log/"):
                e = json.loads(v)
                if e["version"] > from_version:
                    out.append(e)
        return out

    def log_entries(self, from_version: int = 0) -> list[dict]:
        return self._scan_log(from_version)

    def append_log(self, txn: Transaction, entry: dict) -> None:
        """Record `entry` in the transaction AND the in-memory mirror; the
        caller must queue_transaction(txn) before yielding control (all
        call sites do, under the PG lock)."""
        txn.omap_setkeys(
            self.coll,
            self.META,
            {
                b"log/%016x" % entry["version"]: json.dumps(entry).encode(),
                b"info": json.dumps(
                    {"last_update": entry["version"]}
                ).encode(),
            },
        )
        self._last_update = max(self._last_update, entry["version"])
        cur = self._inventory.get(entry["name"])
        if cur is None or entry["version"] > cur["version"]:
            self._inventory[entry["name"]] = entry

    def latest_objects(self) -> dict[str, dict]:
        """name -> newest log entry (the recovery inventory)."""
        return self._inventory


class OSDService(Dispatcher):
    def __init__(
        self,
        osd_id: int,
        monmap,
        db: KeyValueDB | None = None,
        config: Config | None = None,
        keyring: dict[str, bytes] | None = None,
        crush_location: dict | None = None,
    ):
        self.id = osd_id
        #: e.g. {"host": "host9"} — announced at boot so the mon can place
        #: a brand-new device in the crush hierarchy (cluster expansion)
        self.crush_location = crush_location
        self.name = f"osd.{osd_id}"
        self.config = config if config is not None else Config()
        self.store = KStore(db)
        self.messenger = Messenger(
            self.name, config=self.config, keyring=keyring
        )
        self.messenger.dispatcher = self
        # MonClient chains itself in front of us on the shared messenger
        self.mon = MonClient(
            self.name, monmap, config=self.config,
            messenger=self.messenger,
        )
        self.pgs: dict[tuple[int, int], PG] = {}
        self.cls = default_handler()  # in-OSD object classes (src/cls)
        # per-daemon perf counters, dumped via the admin surface the way
        # `ceph daemon osd.N perf dump` reads the admin socket
        from ceph_tpu.common.perf_counters import PerfCountersCollection

        self.perf_collection = PerfCountersCollection()
        self.perf = self.perf_collection.create(self.name)
        for key, desc in (
            ("op_w", "client writes served as primary"),
            ("op_r", "client reads served as primary"),
            ("op_rw", "client cls calls served as primary"),
            ("subop_w", "replica/shard sub-writes applied"),
            ("recovery_pushes", "objects/shards pushed during recovery"),
            ("recovery_pulls", "objects/shards pulled during peering"),
            ("scrub_errors", "inconsistencies found by scrub"),
            ("heartbeat_failures", "peer failures reported to the mon"),
        ):
            self.perf.add_u64_counter(key, desc)
        self._codecs: dict[int, object] = {}
        self._tids = iter(range(1, 1 << 62))
        self._waiters: dict[int, asyncio.Future] = {}
        self._hb_last: dict[int, float] = {}
        self._reported: set[int] = set()
        #: (pool, ps, name) -> [(conn, watcher, cookie)] watch sessions
        self._watchers: dict[tuple, list] = {}
        self._notify_waiters: dict[tuple, asyncio.Future] = {}
        # per-op event timeline ("slow request" reporting, TrackedOp.h)
        from ceph_tpu.common.admin import OpTracker

        self.op_tracker = OpTracker()
        # dout-style subsystem logging with the always-on recent ring
        # (src/log/Log.cc); dumped via the `log dump` admin command
        from ceph_tpu.common.log import LogRegistry

        self.logs = LogRegistry(self.config)
        self.dlog = self.logs.get_logger("osd")
        # sharded weighted op queue (ShardedOpWQ): workers start in start()
        from ceph_tpu.common.op_queue import WeightedPriorityQueue

        class _OpShard:
            def __init__(self):
                self.queue = WeightedPriorityQueue()
                self.kick = asyncio.Event()

        self._op_shards = [_OpShard() for _ in range(4)]
        self._tasks: list[asyncio.Task] = []
        self._ephemeral: set[asyncio.Task] = set()
        self._stopped = False
        self.mon.on_map_change(self._note_map)
        self._map_dirty = asyncio.Event()

    # -- lifecycle ------------------------------------------------------------

    @property
    def osdmap(self):
        return self.mon.osdmap

    async def start(self) -> None:
        await self.messenger.bind()
        self.mon.subscribe()
        await self.mon.wait_for_map()
        # serve once the quorum-committed map says we're up at our address;
        # the boot report is re-sent until then (it can race an election
        # or ride a session that dies — one-way messages need the retry)
        loop = asyncio.get_event_loop()
        end = loop.time() + 30
        next_boot = 0.0
        while loop.time() < end:
            m = self.osdmap
            if (
                self.id < m.max_osd
                and m.osd_up[self.id]
                and m.osd_addrs.get(self.id)
                == tuple(self.messenger.my_addr)
            ):
                break
            if loop.time() >= next_boot:
                self.mon.send_boot(
                    self.id, tuple(self.messenger.my_addr),
                    location=self.crush_location,
                )
                next_boot = loop.time() + 1.0
            await asyncio.sleep(0.02)
        if (d := self.dlog.dout(1)) is not None:
            d(f"osd.{self.id} booted at {self.messenger.my_addr}, "
              f"epoch {self.osdmap.epoch}")
        self._tasks.append(asyncio.create_task(self._heartbeat_loop()))
        self._tasks.append(asyncio.create_task(self._peering_loop()))
        for shard in self._op_shards:
            self._tasks.append(
                asyncio.create_task(self._op_shard_worker(shard))
            )
        self._note_map(self.osdmap)

    def _spawn(self, coro) -> None:
        """Short-lived task that prunes itself on completion (notifies,
        peering nudges): `_tasks` must not grow with daemon lifetime."""
        task = asyncio.create_task(coro)
        self._ephemeral.add(task)
        task.add_done_callback(self._ephemeral.discard)

    async def stop(self) -> None:
        self._stopped = True
        for t in list(self._tasks) + list(self._ephemeral):
            t.cancel()
        for t in list(self._tasks) + list(self._ephemeral):
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        await self.messenger.shutdown()

    # -- placement helpers ----------------------------------------------------

    def codec(self, pool_id: int):
        if pool_id not in self._codecs:
            pool = self.osdmap.pools[pool_id]
            if not pool.is_erasure():
                self._codecs[pool_id] = None
            else:
                from ceph_tpu.ec.registry import factory

                profile = dict(
                    self.osdmap.erasure_code_profiles[
                        pool.erasure_code_profile
                    ]
                )
                plugin = profile.pop("plugin", "tpu")
                self._codecs[pool_id] = factory(plugin, profile)
        return self._codecs[pool_id]

    def acting_of(self, pool_id: int, ps: int) -> tuple[list[int], int]:
        _up, _upp, acting, primary = self.osdmap.pg_to_up_acting_osds(
            pool_id, ps
        )
        return acting, primary

    def object_pg(self, pool_id: int, name: str) -> int:
        from ceph_tpu.common.hash import ceph_str_hash_rjenkins

        pool = self.osdmap.pools[pool_id]
        return pool.raw_pg_to_pg(ceph_str_hash_rjenkins(name))

    def _osd_conn(self, osd: int):
        addr = self.osdmap.osd_addrs.get(osd)
        if addr is None:
            raise RuntimeError(f"no address for osd.{osd}")
        return self.messenger.connect(tuple(addr), Policy.lossless_client())

    async def _peer_call(
        self, osd: int, msg_type: str, payload: dict, timeout: float = 10.0
    ) -> dict:
        """Request/response to a peer OSD (sub-op + ack)."""
        tid = next(self._tids)
        payload = dict(payload)
        payload["tid"] = tid
        payload["reply_to"] = self.id
        fut = asyncio.get_event_loop().create_future()
        self._waiters[tid] = fut
        try:
            self._osd_conn(osd).send_message(
                Message(type=msg_type, tid=tid,
                        epoch=self.osdmap.epoch,
                        data=json.dumps(payload).encode())
            )
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._waiters.pop(tid, None)

    def _reply_peer(self, conn, tid: int, payload: dict) -> None:
        payload = dict(payload)
        payload["tid"] = tid
        conn.send_message(
            Message(type="sub_reply", tid=tid,
                    epoch=self.osdmap.epoch,
                    data=json.dumps(payload).encode())
        )

    # -- dispatch -------------------------------------------------------------

    async def ms_dispatch(self, conn, msg: Message) -> None:
        p = json.loads(msg.data) if msg.data else {}
        if msg.type == "sub_reply":
            fut = self._waiters.get(p.get("tid"))
            if fut is not None and not fut.done():
                fut.set_result(p)
            return
        handler = getattr(self, f"_h_{msg.type}", None)
        if handler is not None:
            await handler(conn, p)

    # -- heartbeats + failure detection ---------------------------------------

    def _hb_peers(self) -> set[int]:
        """OSDs sharing at least one PG with us (the heartbeat peer set)."""
        peers: set[int] = set()
        for (pool, ps) in self.pgs:
            acting, _ = self.acting_of(pool, ps)
            peers.update(o for o in acting if o != _NONE and o != self.id)
        return peers

    async def _heartbeat_loop(self) -> None:
        interval = self.config.get("osd_heartbeat_interval")
        grace = self.config.get("osd_heartbeat_grace")
        loop = asyncio.get_event_loop()
        while not self._stopped:
            for peer in self._hb_peers():
                if self.osdmap.is_down(peer):
                    self._hb_last.pop(peer, None)
                    self._reported.discard(peer)
                    continue
                self._hb_last.setdefault(peer, loop.time())
                try:
                    await self._peer_call(
                        peer, "osd_ping", {}, timeout=interval
                    )
                    self._hb_last[peer] = loop.time()
                    self._reported.discard(peer)
                except (asyncio.TimeoutError, RuntimeError):
                    silent = loop.time() - self._hb_last.get(
                        peer, loop.time()
                    )
                    if silent > grace and peer not in self._reported:
                        if (d := self.dlog.dout(1)) is not None:
                            d(f"peer osd.{peer} silent {silent:.1f}s: "
                              f"reporting failure")
                        self.mon.report_failure(peer)
                        self._reported.add(peer)
                        self.perf.inc("heartbeat_failures")
            await asyncio.sleep(interval)

    async def _h_osd_ping(self, conn, p) -> None:
        self._reply_peer(conn, p["tid"], {"ok": True})

    # -- map handling + peering -----------------------------------------------

    def _note_map(self, _osdmap) -> None:
        self._map_dirty.set()

    async def _peering_loop(self) -> None:
        """Re-evaluate PG responsibility on every map change."""
        while not self._stopped:
            await self._map_dirty.wait()
            self._map_dirty.clear()
            try:
                await self._handle_map_change()
            except asyncio.CancelledError:
                raise
            except Exception:
                pass  # next epoch retries

    async def _handle_map_change(self) -> None:
        m = self.osdmap
        mine: set[tuple[int, int]] = set()
        for pool_id, pool in m.pools.items():
            for ps in range(pool.pg_num):
                acting, primary = self.acting_of(pool_id, ps)
                if self.id in [o for o in acting if o != _NONE]:
                    mine.add((pool_id, ps))
        for key in mine:
            if key not in self.pgs:
                self.pgs[key] = PG(self, *key)
        # primaries drive recovery for their PGs; the interval's acting set
        # is the peering trigger (PastIntervals role): unchanged acting on
        # an already-active PG needs no new pass
        retry_needed = False
        for (pool_id, ps) in sorted(mine):
            acting, primary = self.acting_of(pool_id, ps)
            pg = self.pgs[(pool_id, ps)]
            if primary != self.id:
                pg.active = False
                pg.last_acting = None
                continue
            if pg.active and pg.last_acting == acting:
                continue
            pg.active = False
            try:
                async with pg.lock:
                    complete = await self._peer_and_recover(pg, acting)
                if complete:
                    pg.active = True
                    pg.last_acting = list(acting)
                    if (d := self.dlog.dout(5)) is not None:
                        d(f"pg {pool_id}.{ps} active, acting {acting}")
                else:
                    retry_needed = True  # partial recovery: stay peering
            except asyncio.CancelledError:
                raise
            except Exception:
                retry_needed = True  # transient peer trouble: try again
        if retry_needed and not self._stopped:
            async def nudge():
                await asyncio.sleep(0.3)
                self._map_dirty.set()

            self._spawn(nudge())

    async def _peer_and_recover(self, pg: PG, acting: list[int]) -> bool:
        """GetInfo -> GetLog -> GetMissing -> push, one pass. True only
        when the PG is known complete (safe to go active).

        Info is collected from acting members AND every other up OSD: a
        remap (cluster expansion, failed host) can hand the whole acting
        set to newcomers, leaving the authoritative log only on strays."""
        members = [o for o in acting if o != _NONE and o != self.id]
        infos: dict[int, int] = {self.id: pg.last_update}
        for osd in set(members) | set(self._up_peers()):
            try:
                rep = await self._peer_call(
                    osd, "pg_info", {"pgid": [pg.pool, pg.ps]},
                    timeout=2.0,
                )
                infos[osd] = rep["last_update"]
            except (asyncio.TimeoutError, RuntimeError):
                continue
        best_osd = max(infos, key=lambda o: (infos[o], o == self.id))
        ok = True
        if infos[best_osd] > pg.last_update:
            ok = await self._pull_log_and_objects(pg, best_osd, acting)
        member_infos = {
            o: v for o, v in infos.items() if o in members or o == self.id
        }
        pushed = await self._push_missing(pg, acting, member_infos)
        return ok and pushed

    async def _pull_log_and_objects(
        self, pg: PG, source: int, acting: list[int]
    ) -> bool:
        """Adopt a more advanced holder's log (GetLog + pull). Aborts at
        the first entry whose data is unreachable: appending later entries
        past a gap would advance last_update and silently orphan the
        skipped one forever."""
        rep = await self._peer_call(
            source, "pg_log", {"pgid": [pg.pool, pg.ps],
                               "from": pg.last_update},
        )
        my_shard = self._my_shard(pg, acting)
        inventory: dict[str, dict] = {}
        for e in rep["entries"]:
            inventory[e["name"]] = e
        for e in rep["entries"]:
            txn = Transaction()
            if e["kind"] == "delete":
                txn.remove(pg.coll, shard_name(e["name"], my_shard))
            elif inventory[e["name"]]["version"] != e["version"]:
                pass  # superseded within this pull: newest entry has it
            else:
                want = shard_name(e["name"], my_shard)
                got = await self._pull_object(
                    pg, e["name"], my_shard, acting, e
                )
                if got is None:
                    return False  # retry the whole tail next pass
                data, attrs = got
                txn.write(pg.coll, want, data, attrs=attrs)
            pg.append_log(txn, e)
            self.store.queue_transaction(txn)
            self.perf.inc("recovery_pulls")
        return True

    def _my_shard(self, pg: PG, acting: list[int]) -> int | None:
        if self.codec(pg.pool) is None:
            return None
        try:
            return acting.index(self.id)
        except ValueError:
            return None

    def _up_peers(self) -> list[int]:
        m = self.osdmap
        return [
            o for o in sorted(m.osd_addrs)
            if o != self.id and o < m.max_osd and not m.is_down(o)
        ]

    def _holders_for(self, acting: list[int], pos: int | None) -> list[int]:
        """Candidate holders of a copy/shard: the acting home first, then
        every other up OSD — after a remap the surviving data lives on
        previous-interval STRAYS, which is exactly what the reference's
        MissingLoc tracks (src/osd/MissingLoc.cc). Includes self (local
        store) since we may hold stray shards of other positions."""
        out = []
        if pos is not None and pos < len(acting):
            home = acting[pos]
            if home != _NONE and not self.osdmap.is_down(home):
                out.append(home)
        if self.id not in out:
            out.append(self.id)
        acting_set = set(acting)
        out.extend(
            o for o in self._up_peers()
            if o not in acting_set and o not in out
        )
        # remaining acting members too (replicated: any member has a copy)
        out.extend(
            o for o in acting
            if o not in (_NONE, *out) and not self.osdmap.is_down(o)
        )
        return out

    async def _fetch_copy(self, pg: PG, sname: str, ver: int, candidates):
        """First current-version (data, attrs) among candidates, or None."""
        for osd in candidates:
            if osd == self.id:
                try:
                    data = self.store.read(pg.coll, sname)
                    attrs = self.store.getattrs(pg.coll, sname)
                except StoreError:
                    continue
                if attrs.get("ver") == ver:
                    return data, attrs
                continue
            try:
                rep = await self._peer_call(
                    osd, "obj_read",
                    {"coll": pg.coll, "name": sname, "ver": ver},
                    timeout=2.0,
                )
            except (asyncio.TimeoutError, RuntimeError):
                continue
            if rep.get("ok"):
                return bytes.fromhex(rep["data"]), _attrs_from(rep)
        return None

    async def _rebuild_shard(
        self, pg: PG, name: str, shard: int, acting: list[int], ver: int,
        exclude: int | None = None,
    ):
        """Decode shard `shard` from current-version source shards found at
        acting homes or strays (RecoveryOp READING with MissingLoc)."""
        ec = self.codec(pg.pool)
        chunks: dict[int, bytes] = {}
        attrs = None
        for pos in range(len(acting)):
            if pos == shard:
                continue
            cands = [
                o for o in self._holders_for(acting, pos) if o != exclude
            ]
            got = await self._fetch_copy(
                pg, shard_name(name, pos), ver, cands
            )
            if got is not None:
                chunks[pos] = got[0]
                attrs = attrs or got[1]
            if len(chunks) >= ec.get_data_chunk_count():
                break
        if len(chunks) < ec.get_data_chunk_count():
            return None
        return ec.decode({shard}, chunks)[shard], attrs

    async def _pull_object(
        self, pg: PG, name: str, shard: int | None, acting: list[int], entry
    ):
        """Fetch our copy/shard: direct from any holder (acting or stray),
        else (EC) rebuild by decoding (RecoveryOp READING)."""
        cands = [
            o for o in self._holders_for(acting, shard) if o != self.id
        ]
        got = await self._fetch_copy(
            pg, shard_name(name, shard), entry["obj_ver"], cands
        )
        if got is not None:
            return got
        ec = self.codec(pg.pool)
        if ec is None or shard is None:
            return None
        return await self._rebuild_shard(
            pg, name, shard, acting, entry["obj_ver"]
        )

    async def _push_missing(
        self, pg: PG, acting: list[int], infos: dict[int, int]
    ) -> bool:
        """Push log entries + object data to every laggard member; True
        only when every member is known complete — the PG must not go
        active on a partial recovery."""
        inventory = pg.latest_objects()
        ec = self.codec(pg.pool)
        complete = True
        for pos, osd in enumerate(acting):
            if osd in (self.id, _NONE) or self.osdmap.is_down(osd):
                continue
            since = infos.get(osd)
            if since is None:
                complete = False  # unreachable member: state unknown
                continue
            if since >= pg.last_update:
                continue
            shard = pos if ec is not None else None
            for e in pg.log_entries(since):
                latest = inventory.get(e["name"])
                if latest is None or latest["version"] != e["version"]:
                    # superseded entry: the newest one will carry the data
                    payload = {"entry": e, "data": None}
                elif e["kind"] == "delete":
                    payload = {"entry": e, "data": None}
                else:
                    got = await self._object_for_push(
                        pg, e, shard, acting
                    )
                    if got is None:
                        complete = False  # sources unavailable right now
                        continue
                    data, attrs = got
                    payload = {
                        "entry": e,
                        "data": data.hex(),
                        "attrs": _attrs_to(attrs),
                    }
                try:
                    await self._peer_call(
                        osd, "obj_push",
                        {"pgid": [pg.pool, pg.ps],
                         "shard": shard, **payload},
                        timeout=5.0,
                    )
                    self.perf.inc("recovery_pushes")
                except (asyncio.TimeoutError, RuntimeError):
                    complete = False
                    break  # next pass retries this member
        return complete

    async def _object_for_push(
        self, pg: PG, entry: dict, shard: int | None, acting: list[int]
    ):
        """Data for the target's copy/shard: our own copy when we hold it
        at the right version, else fetched/rebuilt from acting + stray
        holders."""
        ver = entry["obj_ver"]
        my = self._my_shard(pg, acting)
        ec = self.codec(pg.pool)
        if ec is None or shard == my:
            sname = shard_name(entry["name"], my if ec is not None else None)
            got = await self._fetch_copy(
                pg, sname, ver, self._holders_for(acting, my)
            )
            return got
        return await self._rebuild_shard(
            pg, entry["name"], shard, acting, ver
        )

    # -- peer sub-op servers --------------------------------------------------

    async def _h_pg_info(self, conn, p) -> None:
        pg = self._pg_of(p["pgid"])
        self._reply_peer(
            conn, p["tid"], {"last_update": pg.last_update}
        )

    async def _h_pg_log(self, conn, p) -> None:
        pg = self._pg_of(p["pgid"])
        self._reply_peer(
            conn, p["tid"],
            {"entries": pg.log_entries(p.get("from", 0))},
        )

    async def _h_obj_read(self, conn, p) -> None:
        """handle_sub_read: local read (+ version check when asked)."""
        try:
            data = self.store.read(p["coll"], p["name"])
            attrs = self.store.getattrs(p["coll"], p["name"])
        except StoreError:
            self._reply_peer(conn, p["tid"], {"ok": False})
            return
        if p.get("ver") is not None and attrs.get("ver") != p["ver"]:
            self._reply_peer(conn, p["tid"], {"ok": False, "stale": True})
            return
        self._reply_peer(
            conn, p["tid"],
            {"ok": True, "data": data.hex(), "attrs": _attrs_to(attrs)},
        )

    async def _h_obj_push(self, conn, p) -> None:
        """Recovery push: store the object/shard + its log entry."""
        pg = self._pg_of(p["pgid"])
        e = p["entry"]
        txn = Transaction()
        if e["version"] > pg.last_update:
            pg.append_log(txn, e)
        if p.get("data") is not None:
            txn.write(
                pg.coll,
                shard_name(e["name"], p.get("shard")),
                bytes.fromhex(p["data"]),
                attrs=_attrs_from(p),
            )
        elif e["kind"] == "delete":
            txn.remove(pg.coll, shard_name(e["name"], p.get("shard")))
        self.store.queue_transaction(txn)
        self._reply_peer(conn, p["tid"], {"ok": True})

    async def _h_rep_write(self, conn, p) -> None:
        """ReplicatedBackend sub-write: apply locally, ack; idempotent on
        resend (the entry version gate)."""
        pg = self._pg_of(p["pgid"])
        e = p["entry"]
        async with pg.lock:
            if e["version"] > pg.last_update:
                txn = Transaction()
                if e["kind"] == "delete":
                    txn.remove(pg.coll, e["name"])
                else:
                    txn.write(
                        pg.coll, e["name"], bytes.fromhex(p["data"]),
                        attrs=_attrs_from(p),
                    )
                pg.append_log(txn, e)
                self.store.queue_transaction(txn)
                self.perf.inc("subop_w")
        self._reply_peer(conn, p["tid"], {"ok": True})

    async def _h_ec_sub_write(self, conn, p) -> None:
        """ECBackend::handle_sub_write for our shard."""
        pg = self._pg_of(p["pgid"])
        e = p["entry"]
        async with pg.lock:
            if e["version"] > pg.last_update:
                txn = Transaction()
                if e["kind"] == "delete":
                    txn.remove(
                        pg.coll, shard_name(e["name"], p["shard"])
                    )
                else:
                    txn.write(
                        pg.coll,
                        shard_name(e["name"], p["shard"]),
                        bytes.fromhex(p["data"]),
                        attrs=_attrs_from(p),
                    )
                pg.append_log(txn, e)
                self.store.queue_transaction(txn)
                self.perf.inc("subop_w")
        self._reply_peer(conn, p["tid"], {"ok": True})

    def _pg_of(self, pgid) -> PG:
        key = (pgid[0], pgid[1])
        if key not in self.pgs:
            self.pgs[key] = PG(self, *key)
        return self.pgs[key]

    # -- client ops (the primary path) ----------------------------------------

    async def _h_osd_op(self, conn, p) -> None:
        """Client ops ride the sharded weighted op queue (ShardedOpWQ,
        OSD.cc:9490 enqueue_op -> dequeue_op): the shard is picked by
        object name so same-object ops keep their arrival order, and
        within a shard the WPQ's deficit round-robin over client klasses
        fair-shares service by op cost."""
        shard = self._op_shards[
            zlib.crc32(p["name"].encode()) % len(self._op_shards)
        ]
        shard.queue.enqueue(
            63,  # osd_client_op_priority
            max(1, len(p.get("data", "")) // 8192),
            (conn, p),
            klass=conn.peer_name,
        )
        shard.kick.set()

    async def _op_shard_worker(self, shard) -> None:
        while not self._stopped:
            item = shard.queue.dequeue()
            if item is None:
                shard.kick.clear()
                await shard.kick.wait()
                continue
            conn, p = item
            pool_id = p["pool"]
            name = p["name"]
            with self.op_tracker.track(
                f"osd_op({p.get('op')} {pool_id}/{name} "
                f"from {conn.peer_name})"
            ) as tracked:
                await self._do_osd_op(conn, p, pool_id, name, tracked)

    async def _do_osd_op(self, conn, p, pool_id, name, tracked) -> None:
        try:
            if pool_id not in self.osdmap.pools:
                raise RuntimeError(f"no pool {pool_id}")
            ps = self.object_pg(pool_id, name)
            acting, primary = self.acting_of(pool_id, ps)
            tracked.mark_event("placed")
            if primary != self.id:
                conn.send_message(
                    Message(
                        type="osd_op_reply", tid=p["tid"],
                        epoch=self.osdmap.epoch,
                        data=json.dumps(
                            {"tid": p["tid"], "ok": False,
                             "wrong_primary": True,
                             "epoch": self.osdmap.epoch}
                        ).encode(),
                    )
                )
                return
            pg = self._pg_of((pool_id, ps))
            if not pg.active:
                raise RuntimeError(
                    f"pg {pool_id}.{ps} is peering"
                )  # retryable: no errno, the client resends
            if p["op"] == "write":
                async with pg.lock:
                    await self._primary_write(
                        pg, acting, name, bytes.fromhex(p["data"])
                    )
                self.perf.inc("op_w")
                result = {}
            elif p["op"] == "delete":
                async with pg.lock:
                    await self._primary_delete(pg, acting, name)
                result = {}
            elif p["op"] == "read":
                result = {
                    "data": (
                        await self._primary_read(pg, acting, name)
                    ).hex()
                }
                self.perf.inc("op_r")
            elif p["op"] == "stat":
                result = self._primary_stat(pg, name)
            elif p["op"] == "call":
                async with pg.lock:
                    result = await self._primary_call(pg, acting, name, p)
                self.perf.inc("op_rw")
            elif p["op"] == "watch":
                result = await self._h_op_watch(pg, conn, p)
            elif p["op"] == "unwatch":
                result = await self._h_op_unwatch(pg, conn, p)
            elif p["op"] == "notify":
                # replied by a task: waiting for acks inline would wedge
                # this conn's dispatch loop, and the notifier may well be
                # one of the watchers being notified on this very conn
                self._spawn(self._notify_and_reply(pg, conn, p))
                return
            else:
                raise RuntimeError(f"unknown op {p['op']!r}")
            reply = {"tid": p["tid"], "ok": True, **result}
        except (StoreError, ClsError) as e:
            # permanent, client-visible errno (ENOENT/EBUSY/...): the
            # client surfaces these instead of retrying
            reply = {"tid": p["tid"], "ok": False, "error": str(e),
                     "errno": e.code}
        except Exception as e:
            reply = {"tid": p["tid"], "ok": False, "error": str(e)}
        conn.send_message(
            Message(type="osd_op_reply", tid=p["tid"],
                    epoch=self.osdmap.epoch,
                    data=json.dumps(reply).encode())
        )

    def _obj_version(self, pg: PG, name: str) -> int:
        e = pg.latest_objects().get(name)
        return 0 if e is None else e["obj_ver"]

    def _check_min_size(self, pg: PG, acting: list[int]) -> None:
        """The reference blocks IO below pool min_size: acking a write
        that landed on fewer than min_size members risks silently losing
        it if the lone holder then fails and stale replicas re-peer. The
        error is retryable (no errno) so the client resends once the
        cluster heals."""
        pool = self.osdmap.pools[pg.pool]
        alive = sum(
            1 for o in acting
            if o != _NONE and not self.osdmap.is_down(o)
        )
        if alive < pool.min_size:
            raise RuntimeError(
                f"pg {pg.pool}.{pg.ps} has {alive} acting members, "
                f"below min_size {pool.min_size}"
            )

    async def _primary_write(
        self, pg: PG, acting: list[int], name: str, data: bytes,
        user_attrs: dict | None = None,
    ) -> None:
        """Full-object write fan-out. `user_attrs` (cls xattrs) ride along
        as a json blob on every replica/shard; a plain client write_full
        resets them, matching its replace-the-object semantics."""
        entry = {
            "version": pg.last_update + 1,
            "name": name,
            "obj_ver": self._obj_version(pg, name) + 1,
            "kind": "modify",
        }
        user_blob = (
            json.dumps(user_attrs, sort_keys=True).encode()
            if user_attrs else None
        )
        self._check_min_size(pg, acting)
        ec = self.codec(pg.pool)
        if ec is None:
            attrs = {"ver": entry["obj_ver"]}
            if user_blob is not None:
                attrs["user"] = user_blob
            txn = Transaction().write(pg.coll, name, data, attrs=attrs)
            pg.append_log(txn, entry)
            self.store.queue_transaction(txn)
            waits = [
                self._peer_call(
                    osd, "rep_write",
                    {"pgid": [pg.pool, pg.ps], "entry": entry,
                     "data": data.hex(), "attrs": _attrs_to(attrs)},
                )
                for osd in acting
                if osd not in (self.id, _NONE)
                and not self.osdmap.is_down(osd)
            ]
            if waits:
                await asyncio.gather(*waits)
            return
        encoded = ec.encode(range(ec.get_chunk_count()), data)
        hinfo = HashInfo.from_shards(encoded, ec.get_chunk_count())
        attrs = {"ver": entry["obj_ver"], "hinfo": hinfo,
                 "size": len(data)}
        if user_blob is not None:
            attrs["user"] = user_blob
        waits = []
        for pos, osd in enumerate(acting):
            if osd == _NONE or self.osdmap.is_down(osd):
                continue  # degraded write: that shard stays missing
            if osd == self.id:
                txn = Transaction().write(
                    pg.coll, shard_name(name, pos), encoded[pos],
                    attrs=attrs,
                )
                pg.append_log(txn, entry)
                self.store.queue_transaction(txn)
                continue
            waits.append(
                self._peer_call(
                    osd, "ec_sub_write",
                    {"pgid": [pg.pool, pg.ps], "shard": pos,
                     "entry": entry, "data": encoded[pos].hex(),
                     "attrs": _attrs_to(attrs)},
                )
            )
        if waits:
            await asyncio.gather(*waits)

    async def _primary_delete(
        self, pg: PG, acting: list[int], name: str
    ) -> None:
        entry = {
            "version": pg.last_update + 1,
            "name": name,
            "obj_ver": self._obj_version(pg, name) + 1,
            "kind": "delete",
        }
        self._check_min_size(pg, acting)
        ec = self.codec(pg.pool)
        waits = []
        for pos, osd in enumerate(acting):
            if osd == _NONE or self.osdmap.is_down(osd):
                continue
            shard = pos if ec is not None else None
            if osd == self.id:
                txn = Transaction().remove(
                    pg.coll, shard_name(name, shard)
                )
                pg.append_log(txn, entry)
                self.store.queue_transaction(txn)
                continue
            mtype = "ec_sub_write" if ec is not None else "rep_write"
            waits.append(
                self._peer_call(
                    osd, mtype,
                    {"pgid": [pg.pool, pg.ps], "shard": shard,
                     "entry": entry, "data": None},
                )
            )
        if waits:
            await asyncio.gather(*waits)

    async def _primary_read(
        self, pg: PG, acting: list[int], name: str
    ) -> bytes:
        entry = pg.latest_objects().get(name)
        if entry is None or entry["kind"] == "delete":
            raise StoreError("ENOENT", f"no such object {name!r}")
        ec = self.codec(pg.pool)
        if ec is None:
            data = self.store.read(pg.coll, name)
            attrs = self.store.getattrs(pg.coll, name)
            if attrs.get("ver") != entry["obj_ver"]:
                raise RuntimeError(f"local replica of {name!r} is stale")
            return data

        # EC: probe current-version shard availability at acting homes
        available: dict[int, int] = {}
        chunks: dict[int, bytes] = {}
        size = None
        for pos, osd in enumerate(acting):
            if osd == _NONE or self.osdmap.is_down(osd):
                continue
            if osd == self.id:
                try:
                    data = self.store.read(pg.coll, shard_name(name, pos))
                    attrs = self.store.getattrs(
                        pg.coll, shard_name(name, pos)
                    )
                except StoreError:
                    continue
                if attrs.get("ver") == entry["obj_ver"]:
                    available[pos] = osd
                    chunks[pos] = data
                    size = attrs.get("size", size)
            else:
                available[pos] = osd
        want = {ec.chunk_index(i)
                for i in range(ec.get_data_chunk_count())}
        while True:
            minimum = ec.minimum_to_decode(want, set(available))
            fetch = [s for s in minimum if s not in chunks]
            failed = None
            for s in fetch:
                try:
                    rep = await self._peer_call(
                        available[s], "obj_read",
                        {"coll": pg.coll, "name": shard_name(name, s),
                         "ver": entry["obj_ver"]},
                        timeout=2.0,
                    )
                except (asyncio.TimeoutError, RuntimeError):
                    rep = {"ok": False}
                if not rep.get("ok"):
                    # acting home lacks the shard (mid-recovery interval):
                    # previous-interval strays may still hold it
                    stray = await self._fetch_copy(
                        pg, shard_name(name, s), entry["obj_ver"],
                        [o for o in self._up_peers()
                         if o not in set(acting)],
                    )
                    if stray is not None:
                        chunks[s] = stray[0]
                        if size is None:
                            size = stray[1].get("size")
                        continue
                    failed = s
                    break
                chunks[s] = bytes.fromhex(rep["data"])
                if size is None:
                    size = _attrs_from(rep).get("size")
            if failed is None:
                break
            del available[failed]
            chunks.pop(failed, None)
        decoded = ec.decode(want, {s: chunks[s] for s in minimum})
        out = b"".join(
            decoded[ec.chunk_index(i)]
            for i in range(ec.get_data_chunk_count())
        )
        return out[:size] if size is not None else out

    def _primary_stat(self, pg: PG, name: str) -> dict:
        entry = pg.latest_objects().get(name)
        if entry is None or entry["kind"] == "delete":
            raise StoreError("ENOENT", f"no such object {name!r}")
        return {"obj_ver": entry["obj_ver"], "pg_version": entry["version"]}

    async def _primary_call(
        self, pg: PG, acting: list[int], name: str, p: dict
    ) -> dict:
        """Execute an object-class method server-side (rados exec; the
        PrimaryLogPG CEPH_OSD_OP_CALL path): build the context from the
        object's current content + user xattrs, run the method, and write
        dirty results back through the normal backend fan-out so the
        mutation replicates / EC-encodes like any client write."""
        entry = pg.latest_objects().get(name)
        exists = entry is not None and entry["kind"] != "delete"
        data = None
        user_attrs: dict = {}
        if exists:
            data = await self._primary_read(pg, acting, name)
            local = shard_name(
                name, self._my_shard(pg, acting)
            )
            try:
                blob = self.store.getattrs(pg.coll, local).get("user")
            except StoreError:
                blob = None
            if blob:
                user_attrs = json.loads(blob)
        ctx = MethodContext(
            data=data,
            user_attrs=user_attrs,
            version=entry["obj_ver"] if exists else 0,
        )
        result = self.cls.call(p["cls"], p["method"], ctx, p.get("input"))
        if ctx.dirty:
            await self._primary_write(
                pg, acting, name,
                ctx.data if ctx.data is not None else b"",
                user_attrs=ctx.user_attrs,
            )
        return {"result": result}


    # -- watch / notify (PrimaryLogPG watch/notify, src/osd/Watch.cc) ---------
    #
    # Watchers register on an object at its acting primary; a notify fans
    # the payload to every watcher and completes when all have acked (or
    # the per-notify timeout lapses), returning who acked — the librados
    # coordination primitive rbd's exclusive lock rides. Watches are
    # sessions on THIS primary: a new primary (or a restarted one) starts
    # with no watchers and clients must re-watch, matching the reference's
    # watch timeout + reconnect contract.

    async def _h_op_watch(self, pg, conn, p) -> dict:
        key = (pg.pool, pg.ps, p["name"])
        entry = (conn, p.get("watcher", conn.peer_name), p.get("cookie", ""))
        watchers = self._watchers.setdefault(key, [])
        if not any(
            w[1] == entry[1] and w[2] == entry[2] for w in watchers
        ):
            watchers.append(entry)
        return {}

    async def _h_op_unwatch(self, pg, conn, p) -> dict:
        key = (pg.pool, pg.ps, p["name"])
        me = (p.get("watcher", conn.peer_name), p.get("cookie", ""))
        self._watchers[key] = [
            w for w in self._watchers.get(key, [])
            if (w[1], w[2]) != me
        ]
        return {}

    async def _h_op_notify(self, pg, conn, p) -> dict:
        key = (pg.pool, pg.ps, p["name"])
        notify_id = next(self._tids)
        waits = {}
        for wconn, wname, cookie in list(self._watchers.get(key, [])):
            if not wconn.is_connected:
                continue
            fut = asyncio.get_event_loop().create_future()
            self._notify_waiters[(notify_id, wname, cookie)] = fut
            waits[(wname, cookie)] = fut
            wconn.send_message(
                Message(
                    type="watch_notify",
                    data=json.dumps(
                        {"pool": pg.pool, "name": p["name"],
                         "notify_id": notify_id,
                         "cookie": cookie,
                         "payload": p.get("payload", "")}
                    ).encode(),
                )
            )
        timeout = p.get("timeout", 5.0)
        acked, missed = [], []
        if waits:
            # one deadline for the whole fan-out: N silent watchers cost
            # one timeout, not N stacked ones
            await asyncio.wait(waits.values(), timeout=timeout)
        for (wname, cookie), fut in waits.items():
            if fut.done():
                acked.append({"watcher": wname, "cookie": cookie})
            else:
                fut.cancel()
                missed.append({"watcher": wname, "cookie": cookie})
            self._notify_waiters.pop((notify_id, wname, cookie), None)
        return {"acked": acked, "missed": missed}

    async def _notify_and_reply(self, pg, conn, p) -> None:
        try:
            result = await self._h_op_notify(pg, conn, p)
            reply = {"tid": p["tid"], "ok": True, **result}
        except Exception as e:
            reply = {"tid": p["tid"], "ok": False, "error": str(e)}
        conn.send_message(
            Message(type="osd_op_reply", tid=p["tid"],
                    epoch=self.osdmap.epoch,
                    data=json.dumps(reply).encode())
        )

    async def _h_notify_ack(self, conn, p) -> None:
        fut = self._notify_waiters.get(
            (p["notify_id"], p.get("watcher", conn.peer_name),
             p.get("cookie", ""))
        )
        if fut is not None and not fut.done():
            fut.set_result(None)

    # -- admin surface + scrub (admin_socket / `ceph daemon` analogue) --------

    async def _h_osd_admin(self, conn, p) -> None:
        """Daemon admin commands over the wire — the role the per-daemon
        unix admin socket plays for `ceph daemon osd.N <cmd>`."""
        try:
            cmd = p["cmd"]
            if cmd == "perf dump":
                result = self.perf_collection.dump()
            elif cmd == "status":
                result = {
                    "osd": self.id,
                    "epoch": self.osdmap.epoch if self.osdmap else 0,
                    "num_pgs": len(self.pgs),
                    "active_pgs": sum(
                        1 for pg in self.pgs.values() if pg.active
                    ),
                    "collections": len(self.store.list_collections()),
                }
            elif cmd == "log dump":
                result = {"entries": self.logs.dump_recent()}
            elif cmd == "dump_ops_in_flight":
                result = self.op_tracker.dump_ops_in_flight()
            elif cmd == "dump_historic_ops":
                result = self.op_tracker.dump_historic_ops()
            elif cmd == "scrub":
                result = await self._scrub(
                    p["pool"], deep=p.get("deep", False)
                )
            elif cmd == "repair":
                result = await self._repair(p["pool"])
            else:
                raise RuntimeError(f"unknown admin command {cmd!r}")
            reply = {"tid": p["tid"], "ok": True, "result": result}
        except Exception as e:
            reply = {"tid": p["tid"], "ok": False, "error": str(e)}
        conn.send_message(
            Message(type="osd_admin_reply", tid=p["tid"],
                    data=json.dumps(reply).encode())
        )

    async def _scrub_fetch(self, pg, sname: str, osd: int):
        """One copy's (data, attrs) or an error string."""
        if osd == self.id:
            try:
                return (
                    self.store.read(pg.coll, sname),
                    self.store.getattrs(pg.coll, sname),
                )
            except StoreError:
                return "missing"
        try:
            rep = await self._peer_call(
                osd, "obj_read", {"coll": pg.coll, "name": sname},
                timeout=2.0,
            )
        except (asyncio.TimeoutError, RuntimeError):
            return "unreachable"
        if not rep.get("ok"):
            return "missing"
        return bytes.fromhex(rep["data"]), _attrs_from(rep)

    async def _scrub(self, pool_id: int, deep: bool) -> dict:
        """Primary-driven consistency check over this OSD's primary PGs in
        `pool_id` (PGBackend::be_scan_list shallow; deep re-reads every
        copy/shard: EC shards verify crc32c against the stored HashInfo
        (ECBackend::be_deep_scrub, ECBackend.cc:2461), replicated copies
        compare data digests and flag the minority, like
        be_select_auth_object's majority rule)."""
        from ceph_tpu.common.crc import ceph_crc32c

        errors: list[dict] = []
        ec = self.codec(pool_id)
        for (pid, ps), pg in sorted(self.pgs.items()):
            if pid != pool_id or not pg.active:
                continue
            acting, primary = self.acting_of(pid, ps)
            if primary != self.id:
                continue
            for name, entry in sorted(pg.latest_objects().items()):
                if entry["kind"] == "delete":
                    continue
                copies: dict[int, tuple] = {}  # pos -> (data, attrs)
                for pos, osd in enumerate(acting):
                    if osd == _NONE or self.osdmap.is_down(osd):
                        continue
                    shard = pos if ec is not None else None
                    got = await self._scrub_fetch(
                        pg, shard_name(name, shard), osd
                    )
                    if isinstance(got, str):
                        errors.append(
                            {"pg": [pid, ps], "name": name,
                             "shard": shard, "osd": osd, "error": got}
                        )
                        continue
                    data, attrs = got
                    if attrs.get("ver") != entry["obj_ver"]:
                        errors.append(
                            {"pg": [pid, ps], "name": name,
                             "shard": shard, "osd": osd,
                             "error": "stale"}
                        )
                        continue
                    copies[pos] = (data, attrs)
                if not deep:
                    continue
                if ec is not None:
                    for pos, (data, attrs) in sorted(copies.items()):
                        hinfo = attrs.get("hinfo")
                        err = None
                        if hinfo is None:
                            err = "hinfo_missing"
                        elif ceph_crc32c(
                            0xFFFFFFFF, data
                        ) != hinfo.get_chunk_hash(pos):
                            err = "digest_mismatch"
                        if err:
                            errors.append(
                                {"pg": [pid, ps], "name": name,
                                 "shard": pos, "osd": acting[pos],
                                 "error": err}
                            )
                elif len(copies) > 1:
                    digests = {
                        pos: ceph_crc32c(0xFFFFFFFF, d)
                        for pos, (d, _a) in copies.items()
                    }
                    counts: dict[int, int] = {}
                    for dg in digests.values():
                        counts[dg] = counts.get(dg, 0) + 1
                    best = max(counts.values())
                    if best * 2 > len(digests):
                        # flag minority copies ONLY under a strict digest
                        # majority; a tie (e.g. 1:1 with a replica down)
                        # has no safe authority — auto-picking one could
                        # make repair overwrite the only good copy, so
                        # ties report "inconsistent" and repair skips them
                        auth = next(
                            dg for dg, c in counts.items() if c == best
                        )
                        for pos, dg in sorted(digests.items()):
                            if dg != auth:
                                errors.append(
                                    {"pg": [pid, ps], "name": name,
                                     "shard": None, "osd": acting[pos],
                                     "error": "digest_mismatch"}
                                )
                    else:
                        for pos in sorted(digests):
                            errors.append(
                                {"pg": [pid, ps], "name": name,
                                 "shard": None, "osd": acting[pos],
                                 "error": "inconsistent"}
                            )
        self.perf.inc("scrub_errors", len(errors))
        return {"errors": errors}

    async def _repair(self, pool_id: int) -> dict:
        """Deep-scrub, then overwrite every inconsistent copy with content
        rebuilt from VERIFIED sources only (the `ceph pg repair` flow): EC
        shards decode from hinfo-checked survivors, replicated copies pull
        from a digest-majority member — never from the copy being
        repaired."""
        from ceph_tpu.common.crc import ceph_crc32c

        report = await self._scrub(pool_id, deep=True)
        ec = self.codec(pool_id)
        repaired = 0
        for err in report["errors"]:
            if err["error"] == "inconsistent":
                continue  # no safe authority: surfaced, never auto-fixed
            pid, ps = err["pg"]
            pg = self.pgs[(pid, ps)]
            acting, _ = self.acting_of(pid, ps)
            entry = pg.latest_objects().get(err["name"])
            if entry is None:
                continue
            shard = err["shard"]
            bad_osd = err["osd"]
            # gather verified sources, excluding the copy under repair
            chunks: dict[int, bytes] = {}
            attrs = None
            data = None
            for pos, osd in enumerate(acting):
                if osd in (_NONE, bad_osd) or self.osdmap.is_down(osd):
                    continue
                spos = pos if ec is not None else None
                got = await self._scrub_fetch(
                    pg, shard_name(err["name"], spos), osd
                )
                if isinstance(got, str):
                    continue
                d, a = got
                if a.get("ver") != entry["obj_ver"]:
                    continue
                if ec is not None:
                    hinfo = a.get("hinfo")
                    if hinfo is None or ceph_crc32c(
                        0xFFFFFFFF, d
                    ) != hinfo.get_chunk_hash(pos):
                        continue  # never decode from an unverified shard
                    chunks[pos] = d
                    attrs = attrs or a
                    if len(chunks) >= ec.get_data_chunk_count():
                        break
                else:
                    chunks[pos] = d
                    attrs = attrs or a
            if ec is not None:
                if len(chunks) < ec.get_data_chunk_count():
                    continue
                data = ec.decode({shard}, chunks)[shard]
            elif chunks:
                # replicated: the digest-majority copy wins (ties -> the
                # lowest acting position, like be_select_auth_object)
                counts: dict[bytes, int] = {}
                for d in chunks.values():
                    counts[d] = counts.get(d, 0) + 1
                best = max(counts.values())
                data = next(
                    d for _pos, d in sorted(chunks.items())
                    if counts[d] == best
                )
            if data is None or attrs is None:
                continue
            try:
                if bad_osd == self.id:
                    txn = Transaction().write(
                        pg.coll, shard_name(err["name"], shard), data,
                        attrs=attrs,
                    )
                    self.store.queue_transaction(txn)
                else:
                    await self._peer_call(
                        bad_osd, "obj_push",
                        {"pgid": [pid, ps], "shard": shard,
                         "entry": entry, "data": data.hex(),
                         "attrs": _attrs_to(attrs)},
                        timeout=5.0,
                    )
                repaired += 1
            except (asyncio.TimeoutError, RuntimeError):
                continue
        return {"repaired": repaired, "errors": report["errors"]}


def _attrs_to(attrs: dict | None) -> dict:
    if attrs is None:
        return {}
    out = {}
    for k, v in attrs.items():
        if isinstance(v, HashInfo):
            out[k] = {"__hinfo__": [v.total_chunk_size,
                                    v.cumulative_shard_hashes]}
        elif isinstance(v, bytes):
            out[k] = {"__bytes__": v.hex()}
        else:
            out[k] = v
    return out


def _attrs_from(p: dict) -> dict:
    raw = p.get("attrs") or {}
    out = {}
    for k, v in raw.items():
        if isinstance(v, dict) and "__hinfo__" in v:
            out[k] = HashInfo(v["__hinfo__"][0], list(v["__hinfo__"][1]))
        elif isinstance(v, dict) and "__bytes__" in v:
            out[k] = bytes.fromhex(v["__bytes__"])
        else:
            out[k] = v
    return out
