"""Image: create/open/read/write/resize/snapshots on a striped layout.

Layout parity with the reference (src/librbd/ImageCtx + ObjectMap):

  header   "rbd_header.<name>"   json {size, order, snaps} — metadata
  data     "rbd_data.<name>.<objectno:016x>" — 2^order bytes each, sparse

`read` returns zeros for unwritten ranges (the reference reads an absent
object as a hole via the object map / ENOENT); `write` loads, patches, and
rewrites only the touched objects; `resize` truncates or extends, removing
data objects wholly beyond the new size (ObjectMap-guided trim,
librbd::Operations::resize).

Snapshots ride RADOS self-managed snaps (librbd::Operations::snap_create,
src/librbd/Operations.cc): the image allocates a pool snap id, records it
in the header, and every data write carries the snap context, so object
clones happen server-side on first-write-after-snap. `snap_rollback`
copies each object's at-snap state back over the head.
"""

from __future__ import annotations

import json

from ceph_tpu.rados.client import IoCtx, ObjectNotFound, RadosError

DEFAULT_ORDER = 22  # 4 MiB objects, the reference default (rbd_default_order)


class ImageNotFound(RadosError):
    pass


class Image:
    def __init__(self, ioctx: IoCtx, name: str, size: int, order: int,
                 snaps: dict | None = None):
        # a private IoCtx: the snap context is per-image state and must
        # not leak onto other users of the caller's pool handle
        self.ioctx = IoCtx(ioctx.objecter, ioctx.pool_id)
        self.name = name
        self.size = size
        self.order = order
        #: snap name -> {"id": snapid, "size": image size at snap}
        self.snaps: dict = snaps or {}
        self._apply_snapc()

    def _apply_snapc(self) -> None:
        ids = sorted((s["id"] for s in self.snaps.values()), reverse=True)
        if ids:
            self.ioctx.set_selfmanaged_snap_context(ids[0], ids)
        else:
            self.ioctx.snapc = None

    # -- lifecycle ------------------------------------------------------------

    @staticmethod
    def _header_name(name: str) -> str:
        return f"rbd_header.{name}"

    def _data_name(self, objectno: int) -> str:
        return f"rbd_data.{self.name}.{objectno:016x}"

    @classmethod
    async def create(
        cls, ioctx: IoCtx, name: str, size: int,
        order: int = DEFAULT_ORDER,
    ) -> "Image":
        try:
            await ioctx.stat(cls._header_name(name))
            raise RadosError(f"image {name!r} exists")
        except ObjectNotFound:
            pass
        await ioctx.write_full(
            cls._header_name(name),
            json.dumps({"size": size, "order": order}).encode(),
        )
        return cls(ioctx, name, size, order)

    @classmethod
    async def open(cls, ioctx: IoCtx, name: str) -> "Image":
        try:
            header = json.loads(await ioctx.read(cls._header_name(name)))
        except ObjectNotFound as e:
            raise ImageNotFound(f"no image {name!r}") from e
        return cls(ioctx, name, header["size"], header["order"],
                   snaps=header.get("snaps"))

    async def _save_header(self) -> None:
        # the header itself is never snapshotted: strip the snapc
        saved, self.ioctx.snapc = self.ioctx.snapc, None
        try:
            await self.ioctx.write_full(
                self._header_name(self.name),
                json.dumps({"size": self.size, "order": self.order,
                            "snaps": self.snaps}).encode(),
            )
        finally:
            self.ioctx.snapc = saved

    async def remove(self) -> None:
        objsize = 1 << self.order
        for objectno in range((self.size + objsize - 1) // objsize):
            try:
                await self.ioctx.remove(self._data_name(objectno))
            except ObjectNotFound:
                pass
        await self.ioctx.remove(self._header_name(self.name))

    # -- extent algebra (Striper::file_to_extents for the simple layout) ------

    def _extents(self, off: int, length: int):
        """Yield (objectno, obj_off, obj_len, buf_off) covering the span."""
        objsize = 1 << self.order
        buf_off = 0
        while length > 0:
            objectno = off >> self.order
            obj_off = off & (objsize - 1)
            obj_len = min(objsize - obj_off, length)
            yield objectno, obj_off, obj_len, buf_off
            off += obj_len
            buf_off += obj_len
            length -= obj_len

    # -- IO -------------------------------------------------------------------

    def _check_span(self, off: int, length: int) -> None:
        if off < 0 or length < 0 or off + length > self.size:
            raise RadosError(
                f"span [{off}, {off + length}) outside image of size "
                f"{self.size}"
            )

    async def read(
        self, off: int, length: int, snap_name: str | None = None
    ) -> bytes:
        snapid = None
        size = self.size
        if snap_name is not None:
            meta = self.snaps.get(snap_name)
            if meta is None:
                raise RadosError(f"no snap {snap_name!r}")
            snapid = meta["id"]
            size = meta["size"]
        if off < 0 or length < 0 or off + length > size:
            raise RadosError(
                f"span [{off}, {off + length}) outside image of size "
                f"{size}"
            )
        out = bytearray(length)
        objsize = 1 << self.order
        for objectno, obj_off, obj_len, buf_off in self._extents(
            off, length
        ):
            try:
                data = await self.ioctx.read(
                    self._data_name(objectno), snapid=snapid
                )
            except ObjectNotFound:
                continue  # hole: stays zero
            if len(data) < objsize:
                data = data + b"\0" * (objsize - len(data))
            out[buf_off: buf_off + obj_len] = data[
                obj_off: obj_off + obj_len
            ]
        return bytes(out)

    # -- snapshots (librbd::Operations::snap_* family) ------------------------

    async def snap_create(self, snap_name: str) -> int:
        if snap_name in self.snaps:
            raise RadosError(f"snap {snap_name!r} exists")
        snapid = await self.ioctx.selfmanaged_snap_create()
        self.snaps[snap_name] = {"id": snapid, "size": self.size}
        self._apply_snapc()
        await self._save_header()
        return snapid

    async def snap_remove(self, snap_name: str) -> None:
        meta = self.snaps.pop(snap_name, None)
        if meta is None:
            raise RadosError(f"no snap {snap_name!r}")
        self._apply_snapc()
        await self._save_header()
        # pool-level removal queues the OSD-side clone trim
        await self.ioctx.selfmanaged_snap_remove(meta["id"])

    async def snap_rollback(self, snap_name: str) -> None:
        """Copy every object's at-snap state back over the head
        (Operations.cc snap_rollback); the rollback writes carry the
        current snap context so they are themselves snapshottable."""
        meta = self.snaps.get(snap_name)
        if meta is None:
            raise RadosError(f"no snap {snap_name!r}")
        snapid, snap_size = meta["id"], meta["size"]
        objsize = 1 << self.order
        cur_objects = (self.size + objsize - 1) // objsize
        snap_objects = (snap_size + objsize - 1) // objsize
        for objectno in range(max(cur_objects, snap_objects)):
            try:
                data = await self.ioctx.read(
                    self._data_name(objectno), snapid=snapid
                )
                await self.ioctx.write_full(
                    self._data_name(objectno), data
                )
            except ObjectNotFound:
                # hole (or did not exist) at snap time: drop the head
                try:
                    await self.ioctx.remove(self._data_name(objectno))
                except ObjectNotFound:
                    pass
        self.size = snap_size
        await self._save_header()

    def snap_list(self) -> dict:
        return dict(self.snaps)

    async def write(self, off: int, data: bytes) -> None:
        self._check_span(off, len(data))
        objsize = 1 << self.order
        for objectno, obj_off, obj_len, buf_off in self._extents(
            off, len(data)
        ):
            piece = data[buf_off: buf_off + obj_len]
            if obj_off == 0 and obj_len == objsize:
                await self.ioctx.write_full(
                    self._data_name(objectno), piece
                )
                continue
            # partial object: client-side read-modify-write
            try:
                cur = await self.ioctx.read(self._data_name(objectno))
            except ObjectNotFound:
                cur = b""
            buf = bytearray(max(len(cur), obj_off + obj_len))
            buf[: len(cur)] = cur
            buf[obj_off: obj_off + obj_len] = piece
            await self.ioctx.write_full(
                self._data_name(objectno), bytes(buf)
            )

    async def resize(self, new_size: int) -> None:
        objsize = 1 << self.order
        old_objects = (self.size + objsize - 1) // objsize
        new_objects = (new_size + objsize - 1) // objsize
        for objectno in range(new_objects, old_objects):
            try:
                await self.ioctx.remove(self._data_name(objectno))
            except ObjectNotFound:
                pass
        if new_size < self.size and new_size & (objsize - 1):
            # shrink: truncate the partial boundary object too, or a later
            # grow would re-expose stale bytes where zeros are expected
            # (the reference truncates the boundary object on shrink)
            boundary = new_size >> self.order
            keep = new_size & (objsize - 1)
            try:
                cur = await self.ioctx.read(self._data_name(boundary))
                if len(cur) > keep:
                    await self.ioctx.write_full(
                        self._data_name(boundary), cur[:keep]
                    )
            except ObjectNotFound:
                pass
        self.size = new_size
        await self._save_header()
