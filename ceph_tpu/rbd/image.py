"""Image: create/open/read/write/resize/snapshots/clones on a striped
layout, with an object-map accelerating existence checks.

Layout parity with the reference (src/librbd/ImageCtx + ObjectMap +
CloneRequest/CopyupRequest):

  header   "rbd_header.<name>"   json {size, order, snaps, parent,
           protected, children} — metadata
  data     "rbd_data.<name>.<objectno:016x>" — 2^order bytes each, sparse
  map      "rbd_object_map.<name>[.<snapid:x>]" — one bit per object
           (exists); snapshots freeze a copy, like the reference's
           per-snap object maps

`read` returns zeros for unwritten ranges (holes); for a CLONE, a hole in
the child reads through to the parent's protected snapshot within the
overlap (librbd's parent read-through). `write` to an absent child object
first copies the parent's content up (CopyupRequest) so the child object
carries full data from then on. `flatten` copies every still-inherited
object up and severs the parent link (Operations::flatten); the parent
tracks a child count so `snap_unprotect` refuses while clones exist
(the rbd_children registry role).

Snapshots ride RADOS self-managed snaps (librbd::Operations::snap_create):
the image allocates a pool snap id, records it in the header, and every
data write carries the snap context, so object clones happen server-side
on first-write-after-snap. `snap_rollback` copies each object's at-snap
state back over the head.

The object map is consulted on reads (an absent bit skips the RADOS read
entirely — the fast-diff/existence role, src/librbd/ObjectMap.cc), kept
exact on write/remove/resize/rollback/flatten, and rebuildable from a
full stat sweep (`object_map_rebuild`, the `rbd object-map rebuild`
CLI role).
"""

from __future__ import annotations

import asyncio
import json

from ceph_tpu.rados.client import IoCtx, ObjectNotFound, RadosError

DEFAULT_ORDER = 22  # 4 MiB objects, the reference default (rbd_default_order)

RBD_LOCK_NAME = "rbd_lock"  # the reference's RBD_LOCK_NAME


class _ClsHeaderLock:
    """Cluster-side image lock on the header object via cls_lock (the
    librbd ManagedLock/ExclusiveLock role, src/librbd/ManagedLock.h:28).

    Replaces round-4's in-process `_header_locks` dict: exclusion now
    lives IN the cluster (an atomic cls op on the header at its primary
    OSD), so two clients in different processes — the deployment that
    exists since the vstart work — serialize clone/flatten/unprotect
    header RMWs and open-for-write ownership correctly.

    Owner identity is "entity/nonce" (this messenger instance), which is
    exactly the OSDMap blocklist's per-instance key: `break_lock`
    blocklists the dead holder BEFORE removing its lock, so its delayed
    writes are refused at every OSD (blacklist_on_break_lock).
    """

    def __init__(self, ioctx: IoCtx, header_name: str):
        self.ioctx = ioctx
        self.header = header_name
        m = ioctx.objecter.messenger
        self.owner = f"{ioctx.objecter.name}/{m.instance_nonce}"
        # cookie = client id: `rbd lock ls` equivalents show WHICH
        # client holds the image, not just which messenger instance
        self.cookie = ioctx.objecter.name

    async def acquire(self, timeout: float = 10.0) -> None:
        """Bounded-retry exclusive acquire (maintenance ops hold the
        lock briefly; open-for-write holders keep it until release)."""
        loop = asyncio.get_event_loop()
        end = loop.time() + timeout
        while True:
            try:
                await self.ioctx.exec(
                    self.header, "lock", "lock",
                    {"name": RBD_LOCK_NAME, "type": "exclusive",
                     "owner": self.owner, "cookie": self.cookie},
                )
                return
            except RadosError as e:
                if "EBUSY" not in str(e) or loop.time() > end:
                    raise
                await asyncio.sleep(0.05)

    async def release(self) -> None:
        try:
            await self.ioctx.exec(
                self.header, "lock", "unlock",
                {"name": RBD_LOCK_NAME, "owner": self.owner,
                 "cookie": self.cookie},
            )
        except RadosError:
            pass  # already broken/expired: release is best-effort

    async def holders(self) -> list:
        info = await self.ioctx.exec(
            self.header, "lock", "get_info", {"name": RBD_LOCK_NAME}
        )
        return info.get("holders", [])

    async def break_lock(
        self, owner: str, blocklist: bool = True,
        blocklist_expire: float = 3600.0,
    ) -> None:
        """Take a dead holder's lock away: blocklist its messenger
        instance in the OSDMap FIRST (its in-flight writes die at every
        OSD), then remove the holder entry."""
        if blocklist:
            await self.ioctx.objecter.mon.command(
                "osd blocklist",
                {"op": "add", "entity": owner,
                 "expire": blocklist_expire},
            )
        await self.ioctx.exec(
            self.header, "lock", "break_lock",
            {"name": RBD_LOCK_NAME, "owner": owner},
        )
        try:
            self.ioctx.objecter.mon.cluster_log(
                "WRN", f"lock broken: {self.header}/{RBD_LOCK_NAME} "
                       f"holder {owner!r} by {self.owner!r}"
            )
        # cephlint: disable=error-taxonomy (break-lock already succeeded; the WRN line is best-effort)
        except Exception:  # noqa: BLE001
            pass


class _HeaderLockCtx:
    """`async with` sugar for a brief maintenance hold."""

    def __init__(self, ioctx: IoCtx, header_name: str):
        self.lock = _ClsHeaderLock(ioctx, header_name)

    async def __aenter__(self):
        await self.lock.acquire()
        return self.lock

    async def __aexit__(self, *exc):
        await self.lock.release()


def _header_lock(ioctx: IoCtx, image_name: str) -> _HeaderLockCtx:
    return _HeaderLockCtx(ioctx, f"rbd_header.{image_name}")


class ImageNotFound(RadosError):
    pass


class Image:
    def __init__(self, ioctx: IoCtx, name: str, size: int, order: int,
                 snaps: dict | None = None, parent: dict | None = None,
                 protected: list | None = None, children: int = 0,
                 migration: dict | None = None):
        # a private IoCtx: the snap context is per-image state and must
        # not leak onto other users of the caller's pool handle
        self.ioctx = IoCtx(ioctx.objecter, ioctx.pool_id)
        self.name = name
        self.size = size
        self.order = order
        #: snap name -> {"id": snapid, "size": image size at snap}
        self.snaps: dict = snaps or {}
        #: {"pool": id, "image": name, "snap": name, "snapid": id,
        #:  "overlap": bytes} for a clone, else None
        self.parent: dict | None = parent
        #: snap names protected against removal (clone prerequisites)
        self.protected: list = list(protected or [])
        #: number of clones whose parent is a snap of this image
        self.children = children
        #: {"pool": id, "image": name} while this image is the TARGET of
        #: a live migration: holes read through to the source's head and
        #: writes copy up, exactly the clone machinery minus the snap
        #: (librbd/api/Migration.cc role)
        self.migration: dict | None = migration
        self._parent_image: "Image | None" = None
        self._migration_src: "Image | None" = None
        #: head object map bits (1 = object exists); loaded lazily
        self._omap_bits: bytearray | None = None
        #: fast-diff clean bits (unchanged since the latest snap)
        self._clean_bits: bytearray | None = None
        self._apply_snapc()

    def _apply_snapc(self) -> None:
        ids = sorted((s["id"] for s in self.snaps.values()), reverse=True)
        if ids:
            self.ioctx.set_selfmanaged_snap_context(ids[0], ids)
        else:
            self.ioctx.snapc = None

    # -- lifecycle ------------------------------------------------------------

    @staticmethod
    def _header_name(name: str) -> str:
        return f"rbd_header.{name}"

    def _data_name(self, objectno: int) -> str:
        return f"rbd_data.{self.name}.{objectno:016x}"

    def _map_name(self, snapid: int | None = None) -> str:
        base = f"rbd_object_map.{self.name}"
        return base if snapid is None else f"{base}.{snapid:x}"

    @classmethod
    async def create(
        cls, ioctx: IoCtx, name: str, size: int,
        order: int = DEFAULT_ORDER,
    ) -> "Image":
        try:
            await ioctx.stat(cls._header_name(name))
            raise RadosError(f"image {name!r} exists")
        except ObjectNotFound:
            pass
        await ioctx.write_full(
            cls._header_name(name),
            json.dumps({"size": size, "order": order}).encode(),
        )
        return cls(ioctx, name, size, order)

    @classmethod
    async def open(
        cls, ioctx: IoCtx, name: str, exclusive: bool = False,
        force: bool = False,
    ) -> "Image":
        """`exclusive=True` = open-for-write under the cluster-side
        exclusive lock (librbd's exclusive-lock feature): held until
        `close()`/`lock_release()`, visible to every other client via
        `lock_holders()`, breakable with `break_lock` when the holder
        died (which blocklists it first). A second writer fails with
        EBUSY immediately; `force=True` is the `rbd lock rm`-style
        operator override — break every current holder (blocklisting
        their instances) and take the lock."""
        try:
            header = json.loads(await ioctx.read(cls._header_name(name)))
        except ObjectNotFound as e:
            raise ImageNotFound(f"no image {name!r}") from e
        img = cls(ioctx, name, header["size"], header["order"],
                  snaps=header.get("snaps"),
                  parent=header.get("parent"),
                  protected=header.get("protected"),
                  children=header.get("children", 0),
                  migration=header.get("migration"))
        if exclusive:
            try:
                await img.lock_acquire(timeout=0.0)
            except RadosError as e:
                if "EBUSY" not in str(e) or not force:
                    raise
                for h in await img.lock_holders():
                    await img.break_lock(h["owner"])
                await img.lock_acquire(timeout=0.0)
        return img

    # -- the exclusive lock (ManagedLock.h:28 surface) -------------------------

    @property
    def _lock(self) -> _ClsHeaderLock:
        return _ClsHeaderLock(self.ioctx, self._header_name(self.name))

    async def lock_acquire(self, timeout: float = 10.0) -> None:
        await self._lock.acquire(timeout=timeout)
        self._lock_held = True

    async def lock_release(self) -> None:
        await self._lock.release()
        self._lock_held = False

    async def lock_holders(self) -> list:
        return await self._lock.holders()

    async def break_lock(self, owner: str, blocklist: bool = True) -> None:
        await self._lock.break_lock(owner, blocklist=blocklist)

    async def close(self) -> None:
        # release only what THIS handle acquired: a read-only handle's
        # close must not strip the exclusive lock a sibling handle of
        # the same client (same owner/cookie at the cls) still relies on
        if getattr(self, "_lock_held", False):
            await self.lock_release()

    async def _save_header(self) -> None:
        # the header itself is never snapshotted: strip the snapc
        saved, self.ioctx.snapc = self.ioctx.snapc, None
        try:
            await self.ioctx.write_full(
                self._header_name(self.name),
                json.dumps({"size": self.size, "order": self.order,
                            "snaps": self.snaps,
                            "parent": self.parent,
                            "protected": self.protected,
                            "children": self.children,
                            "migration": self.migration}).encode(),
            )
        finally:
            self.ioctx.snapc = saved

    async def remove(self) -> None:
        await self._refresh()
        if self.children:
            raise RadosError(
                f"image {self.name!r} has {self.children} clone(s)"
            )
        bits = await self._load_map()
        objsize = 1 << self.order
        for objectno in range((self.size + objsize - 1) // objsize):
            if not self._map_get(bits, objectno):
                continue  # object-map fast path: known-absent
            try:
                await self.ioctx.remove(self._data_name(objectno))
            except ObjectNotFound:
                pass
        for snap in self.snaps.values():
            try:
                await self.ioctx.remove(self._map_name(snap["id"]))
            except ObjectNotFound:
                pass
        for oname in (
            self._map_name(), self._map_name() + ".clean",
            self._header_name(self.name),
        ):
            try:
                await self.ioctx.remove(oname)
            except ObjectNotFound:
                pass
        if self.parent is not None:
            await self._detach_parent()

    # -- object map (src/librbd/ObjectMap.cc role) -----------------------------

    def _map_get(self, bits: bytearray, objectno: int) -> bool:
        byte = objectno >> 3
        return byte < len(bits) and bool(
            bits[byte] & (1 << (objectno & 7))
        )

    async def _load_map(self) -> bytearray:
        if self._omap_bits is None:
            saved, self.ioctx.snapc = self.ioctx.snapc, None
            try:
                self._omap_bits = bytearray(
                    await self.ioctx.read(self._map_name())
                )
            except ObjectNotFound:
                # no map yet (older image or fresh create): rebuild from
                # a stat sweep so existing images upgrade transparently
                self._omap_bits = await self._stat_sweep()
                await self._persist_map()
            finally:
                self.ioctx.snapc = saved
        return self._omap_bits

    async def _stat_sweep(self) -> bytearray:
        objsize = 1 << self.order
        n = (self.size + objsize - 1) // objsize
        bits = bytearray((n + 7) // 8)
        for objectno in range(n):
            try:
                await self.ioctx.stat(self._data_name(objectno))
            except ObjectNotFound:
                continue
            bits[objectno >> 3] |= 1 << (objectno & 7)
        return bits

    async def _persist_map(self) -> None:
        saved, self.ioctx.snapc = self.ioctx.snapc, None
        try:
            await self.ioctx.write_full(
                self._map_name(), bytes(self._omap_bits)
            )
        finally:
            self.ioctx.snapc = saved

    @staticmethod
    def _set_bit(bits: bytearray, objectno: int, exists: bool) -> None:
        byte = objectno >> 3
        if byte >= len(bits):
            bits.extend(b"\x00" * (byte + 1 - len(bits)))
        if exists:
            bits[byte] |= 1 << (objectno & 7)
        else:
            bits[byte] &= ~(1 << (objectno & 7)) & 0xFF

    async def _map_set(self, objectno: int, exists: bool) -> None:
        bits = await self._load_map()
        self._set_bit(bits, objectno, exists)
        await self._persist_map()

    async def object_map_rebuild(self) -> None:
        """`rbd object-map rebuild`: recompute from a full stat sweep."""
        self._omap_bits = await self._stat_sweep()
        await self._persist_map()

    async def _load_clean(self) -> bytearray:
        """Bits for objects UNCHANGED since the latest snap_create (the
        fast-diff EXISTS_CLEAN state); absent map = nothing known clean,
        which only ever makes diff pessimistic, never wrong."""
        if self._clean_bits is None:
            saved, self.ioctx.snapc = self.ioctx.snapc, None
            try:
                self._clean_bits = bytearray(
                    await self.ioctx.read(self._map_name() + ".clean")
                )
            except ObjectNotFound:
                self._clean_bits = bytearray()
            finally:
                self.ioctx.snapc = saved
        return self._clean_bits

    async def _persist_clean(self) -> None:
        saved, self.ioctx.snapc = self.ioctx.snapc, None
        try:
            await self.ioctx.write_full(
                self._map_name() + ".clean", bytes(self._clean_bits)
            )
        finally:
            self.ioctx.snapc = saved

    async def diff(self, from_snap: str) -> list[int]:
        """Object numbers that changed between `from_snap` and the head
        (rbd diff --whole-object, the fast-diff contract): computed from
        the frozen per-snap exists-bitmap, the head's, and the
        clean-bitmap the head maintains since its latest snap — no data
        object is read. Against an older snap the clean bits only say
        "changed since the LATEST snap", so anything not provably clean
        is reported — pessimistic, never missing a change."""
        meta = self.snaps.get(from_snap)
        if meta is None:
            raise RadosError(f"no snap {from_snap!r}")
        saved, self.ioctx.snapc = self.ioctx.snapc, None
        try:
            try:
                snap_bits = bytearray(
                    await self.ioctx.read(self._map_name(meta["id"]))
                )
            except ObjectNotFound:
                snap_bits = bytearray()
        finally:
            self.ioctx.snapc = saved
        head_bits = await self._load_map()
        clean = await self._load_clean()
        latest = max(
            self.snaps.values(), key=lambda m: m["id"]
        )["id"] == meta["id"]
        objsize = 1 << self.order
        n = (self.size + objsize - 1) // objsize
        changed = []
        for objectno in range(n):
            was = self._map_get(snap_bits, objectno)
            now = self._map_get(head_bits, objectno)
            if was != now:
                changed.append(objectno)
            elif now and not (
                latest and self._map_get(clean, objectno)
            ):
                changed.append(objectno)
        return changed

    async def object_map_check(self) -> list[int]:
        """Objects whose map bit disagrees with reality (diagnostic;
        the `rbd object-map check` role). Empty list = consistent."""
        bits = await self._load_map()
        actual = await self._stat_sweep()
        objsize = 1 << self.order
        n = (self.size + objsize - 1) // objsize
        return [
            i for i in range(n)
            if self._map_get(bits, i) != self._map_get(actual, i)
        ]

    # -- clones (librbd CloneRequest / CopyupRequest / flatten) ---------------

    async def _refresh(self) -> None:
        """Reload header state another handle may have changed (clone
        counts, protection) — the ImageCtx refresh librbd runs before
        maintenance operations."""
        fresh = await Image.open(self.ioctx, self.name)
        self.size = fresh.size
        self.snaps = fresh.snaps
        self.parent = fresh.parent
        self.protected = fresh.protected
        self.children = fresh.children
        self._apply_snapc()

    async def snap_protect(self, snap_name: str) -> None:
        await self._refresh()
        if snap_name not in self.snaps:
            raise RadosError(f"no snap {snap_name!r}")
        if snap_name not in self.protected:
            self.protected.append(snap_name)
            await self._save_header()

    async def snap_unprotect(self, snap_name: str) -> None:
        async with _header_lock(self.ioctx, self.name):
            await self._refresh()
            if self.children:
                raise RadosError(
                    f"snap {snap_name!r} has {self.children} clone(s)"
                )
            if snap_name in self.protected:
                self.protected.remove(snap_name)
                await self._save_header()

    @classmethod
    async def clone(
        cls, parent_ioctx: IoCtx, parent_name: str, snap_name: str,
        child_ioctx: IoCtx, child_name: str,
    ) -> "Image":
        """Snapshot-backed copy-on-write child (librbd::CloneRequest):
        the child starts with NO data objects; reads fall through to the
        parent's protected snap within the overlap, writes copy-up."""
        async with _header_lock(parent_ioctx, parent_name):
            parent = await cls.open(parent_ioctx, parent_name)
            meta = parent.snaps.get(snap_name)
            if meta is None:
                raise RadosError(f"no snap {snap_name!r}")
            if snap_name not in parent.protected:
                raise RadosError(f"snap {snap_name!r} is not protected")
            try:
                await child_ioctx.stat(cls._header_name(child_name))
                raise RadosError(f"image {child_name!r} exists")
            except ObjectNotFound:
                pass
            parent.children += 1
            await parent._save_header()
        child = cls(
            child_ioctx, child_name, meta["size"], parent.order,
            parent={"pool": parent_ioctx.pool_id,
                    "image": parent_name, "snap": snap_name,
                    "snapid": meta["id"], "overlap": meta["size"]},
        )
        await child._save_header()
        return child

    async def _open_parent(self) -> "Image":
        if self._parent_image is None:
            pioctx = IoCtx(self.ioctx.objecter, self.parent["pool"])
            self._parent_image = await Image.open(
                pioctx, self.parent["image"]
            )
        return self._parent_image

    async def _migration_object(self, objectno: int) -> bytes | None:
        """Read-through to a migration SOURCE's head (Migration.cc's
        deep-copy read path): same shape as the clone fall-through but
        at the live head, clipped to the source size."""
        if self.migration is None:
            return None
        if self._migration_src is None:
            sioctx = IoCtx(self.ioctx.objecter, self.migration["pool"])
            self._migration_src = await Image.open(
                sioctx, self.migration["image"]
            )
        src = self._migration_src
        objsize = 1 << self.order
        poff = objectno * objsize
        if poff >= src.size:
            return None
        length = min(objsize, src.size - poff)
        return await src.read(poff, length)

    async def _parent_object(self, objectno: int) -> bytes | None:
        """The child object's content as inherited from the parent snap
        (clipped to the overlap), or None when outside it — or from a
        migration source's head while a migration is in flight."""
        if self.parent is None:
            return await self._migration_object(objectno)
        objsize = 1 << self.order
        poff = objectno * objsize
        overlap = self.parent["overlap"]
        if poff >= overlap:
            return None
        length = min(objsize, overlap - poff)
        parent = await self._open_parent()
        return await parent.read(poff, length, self.parent["snap"])

    async def _copy_up(self, objectno: int) -> bytes:
        """CopyupRequest: materialize an absent child object from the
        parent before the first write touches it."""
        inherited = await self._parent_object(objectno)
        return inherited if inherited is not None else b""

    async def _detach_parent(self) -> None:
        pioctx = IoCtx(self.ioctx.objecter, self.parent["pool"])
        async with _header_lock(pioctx, self.parent["image"]):
            parent = await self._open_parent()
            await parent._refresh()
            parent.children = max(0, parent.children - 1)
            await parent._save_header()
        self.parent = None
        self._parent_image = None

    async def flatten(self) -> None:
        """Copy every still-inherited object up, then sever the parent
        link (librbd::Operations::flatten)."""
        if self.parent is None:
            return
        objsize = 1 << self.order
        overlap = min(self.parent["overlap"], self.size)
        bits = await self._load_map()
        for objectno in range((overlap + objsize - 1) // objsize):
            if self._map_get(bits, objectno):
                continue  # child already owns it
            data = await self._copy_up(objectno)
            await self.ioctx.write_full(
                self._data_name(objectno), data
            )
            self._set_bit(bits, objectno, True)
        await self._persist_map()
        await self._detach_parent()
        await self._save_header()

    # -- live migration (librbd/api/Migration.cc, mini) -----------------------

    @classmethod
    async def migration_prepare(
        cls, src_ioctx: IoCtx, src_name: str,
        dst_ioctx: IoCtx, dst_name: str,
    ) -> "Image":
        """Stage 1 (`rbd migration prepare`): create the TARGET image
        linked to the source; clients switch to the target immediately —
        holes read through to the source, writes copy up. The source is
        fenced for the whole migration by a cluster-side lock owned by
        the migration itself (the reference hides the source image)."""
        src = await cls.open(src_ioctx, src_name)
        if src.snaps:
            raise RadosError(
                "cannot migrate an image with snapshots (flatten its "
                "history first)"
            )
        if src.parent is not None:
            raise RadosError("flatten the clone before migrating")
        try:
            await dst_ioctx.stat(cls._header_name(dst_name))
            raise RadosError(f"image {dst_name!r} exists")
        except ObjectNotFound:
            pass
        fence = _ClsHeaderLock(src_ioctx, cls._header_name(src_name))
        fence.owner = f"migration/{dst_ioctx.pool_id}/{dst_name}"
        await fence.acquire()
        dst = cls(
            dst_ioctx, dst_name, src.size, src.order,
            migration={"pool": src_ioctx.pool_id, "image": src_name},
        )
        await dst._save_header()
        return dst

    def _migration_fence(self) -> _ClsHeaderLock:
        sioctx = IoCtx(self.ioctx.objecter, self.migration["pool"])
        fence = _ClsHeaderLock(
            sioctx, self._header_name(self.migration["image"])
        )
        fence.owner = (
            f"migration/{self.ioctx.pool_id}/{self.name}"
        )
        return fence

    async def migration_execute(self) -> int:
        """Stage 2: deep-copy every still-inherited object into the
        target (the image stays fully usable throughout). Returns the
        number of objects copied."""
        if self.migration is None:
            return 0
        objsize = 1 << self.order
        bits = await self._load_map()
        copied = 0
        for objectno in range((self.size + objsize - 1) // objsize):
            if self._map_get(bits, objectno):
                continue  # target already owns it
            data = await self._migration_object(objectno)
            if data is None:
                continue  # source hole stays a hole
            await self.ioctx.write_full(
                self._data_name(objectno), data
            )
            self._set_bit(bits, objectno, True)
            copied += 1
        await self._persist_map()
        return copied

    async def migration_commit(self) -> None:
        """Stage 3: finish any remaining copy, remove the SOURCE, and
        sever the link — the target is standalone from here."""
        if self.migration is None:
            return
        await self.migration_execute()
        fence = self._migration_fence()
        sioctx = IoCtx(self.ioctx.objecter, self.migration["pool"])
        src = await Image.open(sioctx, self.migration["image"])
        await fence.release()
        await src.remove()
        self.migration = None
        self._migration_src = None
        await self._save_header()

    async def migration_abort(self) -> None:
        """Back out: drop the target, unfence the source (clients
        switch back)."""
        if self.migration is None:
            return
        fence = self._migration_fence()
        self.migration = None
        await self.remove()
        await fence.release()

    # -- extent algebra (Striper::file_to_extents for the simple layout) ------

    def _extents(self, off: int, length: int):
        """Yield (objectno, obj_off, obj_len, buf_off) covering the span."""
        objsize = 1 << self.order
        buf_off = 0
        while length > 0:
            objectno = off >> self.order
            obj_off = off & (objsize - 1)
            obj_len = min(objsize - obj_off, length)
            yield objectno, obj_off, obj_len, buf_off
            off += obj_len
            buf_off += obj_len
            length -= obj_len

    # -- IO -------------------------------------------------------------------

    def _check_span(self, off: int, length: int) -> None:
        if off < 0 or length < 0 or off + length > self.size:
            raise RadosError(
                f"span [{off}, {off + length}) outside image of size "
                f"{self.size}"
            )

    async def read(
        self, off: int, length: int, snap_name: str | None = None
    ) -> bytes:
        snapid = None
        size = self.size
        if snap_name is not None:
            meta = self.snaps.get(snap_name)
            if meta is None:
                raise RadosError(f"no snap {snap_name!r}")
            snapid = meta["id"]
            size = meta["size"]
        if off < 0 or length < 0 or off + length > size:
            raise RadosError(
                f"span [{off}, {off + length}) outside image of size "
                f"{size}"
            )
        out = bytearray(length)
        objsize = 1 << self.order
        head_bits = (
            await self._load_map() if snapid is None else None
        )
        for objectno, obj_off, obj_len, buf_off in self._extents(
            off, length
        ):
            data = None
            if head_bits is not None and not self._map_get(
                head_bits, objectno
            ):
                # object-map fast path: no child object — inherit from
                # the parent snap (clone) or stay a hole
                data = await self._parent_object(objectno)
            else:
                try:
                    data = await self.ioctx.read(
                        self._data_name(objectno), snapid=snapid
                    )
                except ObjectNotFound:
                    data = await self._parent_object(objectno)
            if data is None:
                continue  # hole: stays zero
            if len(data) < objsize:
                data = data + b"\0" * (objsize - len(data))
            out[buf_off: buf_off + obj_len] = data[
                obj_off: obj_off + obj_len
            ]
        return bytes(out)

    # -- snapshots (librbd::Operations::snap_* family) ------------------------

    async def snap_create(self, snap_name: str) -> int:
        if snap_name in self.snaps:
            raise RadosError(f"snap {snap_name!r} exists")
        snapid = await self.ioctx.selfmanaged_snap_create()
        self.snaps[snap_name] = {"id": snapid, "size": self.size}
        self._apply_snapc()
        # freeze the object map alongside the data (per-snap maps);
        # everything existing right now is CLEAN relative to this snap
        bits = await self._load_map()
        self._clean_bits = bytearray(bits)
        await self._persist_clean()
        saved, self.ioctx.snapc = self.ioctx.snapc, None
        try:
            await self.ioctx.write_full(
                self._map_name(snapid), bytes(bits)
            )
        finally:
            self.ioctx.snapc = saved
        await self._save_header()
        return snapid

    async def snap_remove(self, snap_name: str) -> None:
        await self._refresh()
        if snap_name in self.protected:
            raise RadosError(f"snap {snap_name!r} is protected")
        meta = self.snaps.pop(snap_name, None)
        if meta is None:
            raise RadosError(f"no snap {snap_name!r}")
        self._apply_snapc()
        # clean bits were computed relative to the latest snap — if that
        # reference point goes away they would falsely exonerate changed
        # objects in diff(); void them (pessimistic, never wrong)
        self._clean_bits = bytearray()
        await self._persist_clean()
        await self._save_header()
        try:
            await self.ioctx.remove(self._map_name(meta["id"]))
        except ObjectNotFound:
            pass
        # pool-level removal queues the OSD-side clone trim
        await self.ioctx.selfmanaged_snap_remove(meta["id"])

    async def snap_rollback(self, snap_name: str) -> None:
        """Copy every object's at-snap state back over the head
        (Operations.cc snap_rollback); the rollback writes carry the
        current snap context so they are themselves snapshottable."""
        meta = self.snaps.get(snap_name)
        if meta is None:
            raise RadosError(f"no snap {snap_name!r}")
        snapid, snap_size = meta["id"], meta["size"]
        objsize = 1 << self.order
        cur_objects = (self.size + objsize - 1) // objsize
        snap_objects = (snap_size + objsize - 1) // objsize
        bits = await self._load_map()
        for objectno in range(max(cur_objects, snap_objects)):
            try:
                data = await self.ioctx.read(
                    self._data_name(objectno), snapid=snapid
                )
                await self.ioctx.write_full(
                    self._data_name(objectno), data
                )
                self._set_bit(bits, objectno, True)
            except ObjectNotFound:
                # hole (or did not exist) at snap time: drop the head
                try:
                    await self.ioctx.remove(self._data_name(objectno))
                except ObjectNotFound:
                    pass
                self._set_bit(bits, objectno, False)
        await self._persist_map()  # one batched map write for the sweep
        self._clean_bits = bytearray()  # rollback voids fast-diff state
        await self._persist_clean()
        self.size = snap_size
        await self._save_header()

    def snap_list(self) -> dict:
        return dict(self.snaps)

    async def write(self, off: int, data: bytes) -> None:
        self._check_span(off, len(data))
        objsize = 1 << self.order
        bits = await self._load_map()
        dirty = clean_dirty = False
        for objectno, obj_off, obj_len, buf_off in self._extents(
            off, len(data)
        ):
            piece = data[buf_off: buf_off + obj_len]
            exists = self._map_get(bits, objectno)
            if (
                obj_off == 0 and obj_len == objsize
                and (self.parent is None or exists)
            ):
                await self.ioctx.write_full(
                    self._data_name(objectno), piece
                )
            else:
                # partial object (or first clone write): read-modify-
                # write, seeding from the parent via copy-up when the
                # child object doesn't exist yet
                if exists:
                    try:
                        cur = await self.ioctx.read(
                            self._data_name(objectno)
                        )
                    except ObjectNotFound:
                        cur = await self._copy_up(objectno)
                else:
                    cur = await self._copy_up(objectno)
                buf = bytearray(max(len(cur), obj_off + obj_len))
                buf[: len(cur)] = cur
                buf[obj_off: obj_off + obj_len] = piece
                await self.ioctx.write_full(
                    self._data_name(objectno), bytes(buf)
                )
            if not exists:
                self._set_bit(bits, objectno, True)
                dirty = True
            clean = await self._load_clean()
            if self._map_get(clean, objectno):
                self._set_bit(clean, objectno, False)
                clean_dirty = True
        if dirty:
            await self._persist_map()  # one map write per span
        if clean_dirty:
            await self._persist_clean()

    async def resize(self, new_size: int) -> None:
        objsize = 1 << self.order
        old_objects = (self.size + objsize - 1) // objsize
        new_objects = (new_size + objsize - 1) // objsize
        bits = await self._load_map()
        trimmed = False
        for objectno in range(new_objects, old_objects):
            if self._map_get(bits, objectno):
                try:
                    await self.ioctx.remove(self._data_name(objectno))
                except ObjectNotFound:
                    pass
                self._set_bit(bits, objectno, False)
                trimmed = True
        if trimmed:
            await self._persist_map()
        if new_size < self.size and new_size & (objsize - 1):
            # shrink: truncate the partial boundary object too, or a later
            # grow would re-expose stale bytes where zeros are expected
            # (the reference truncates the boundary object on shrink)
            boundary = new_size >> self.order
            keep = new_size & (objsize - 1)
            try:
                cur = await self.ioctx.read(self._data_name(boundary))
                if len(cur) > keep:
                    await self.ioctx.write_full(
                        self._data_name(boundary), cur[:keep]
                    )
            except ObjectNotFound:
                pass
        if new_size < self.size and self.parent is not None:
            # shrinking below the parent overlap reduces what a clone
            # can ever inherit (librbd shrinks the overlap too)
            self.parent["overlap"] = min(
                self.parent["overlap"], new_size
            )
        self.size = new_size
        await self._save_header()
