"""rbd: block images over RADOS objects (L9, librbd-lite).

The reference's librbd (src/librbd, 73k LoC) presents a virtual block
device as a sequence of 2^order-byte RADOS objects named
rbd_data.<id>.<objectno>, with a header object for metadata and an object
map tracking which objects exist. The mini equivalent here keeps that
layout: `Image` slices byte extents onto data objects (Striper-style
offset algebra), reads absent objects as zeros (sparse semantics — the
object map role is played by ENOENT), and does client-side
read-modify-write for partial-object updates since the mini OSD op set is
whole-object. Works unchanged on replicated and EC pools — EC images get
TPU-encoded object shards for free.
"""

from ceph_tpu.rbd.image import Image, ImageNotFound

__all__ = ["Image", "ImageNotFound"]
