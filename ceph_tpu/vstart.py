"""vstart: boot a REAL multi-process cluster.

This is the role of the reference's ``src/vstart.sh`` (1357 lines of shell
whose only job is to start N ceph-mon + M ceph-osd + mds/rgw as separate OS
processes on one machine) together with the daemon ``main()``s it execs
(``src/ceph_osd.cc:106``, ``src/ceph_mon.cc``).  Every daemon here is a real
``fork+exec``'d Python interpreter running exactly one Monitor / OSDService /
MDS / RGW on its own event loop; they find each other over the TCP messenger
through a shared **cluster spec** file — the monmap + config the reference
distributes via ``ceph.conf`` + the monmap file.

Layout of a run directory (``--run-dir``):

    cluster_spec.json      monmap + n_osds + config overrides
    mon.0.kv / osd.3.kv    per-daemon FileDB stores (WAL, crash-safe)
    osd.3.kv/block         raw block file when osd_objectstore=blockstore
    mon.0.log / osd.3.log  daemon stdout+stderr

The spec is deterministic: every mon builds the identical initial OSDMap
from it (the reference's ``monmaptool --create`` + ``osdmaptool
--createsimple`` seed), so independently-booted mons agree on epoch 1
without talking.

Why this exists: through round 4 every "live" test hosted all daemons in ONE
interpreter on one loop — fine for correctness, but a single GIL serialised
the whole data path (~27 MB/s).  Real processes give each OSD its own
interpreter, so daemon-path throughput can scale with the process count;
``tools/daemon_bench.py --multiprocess`` measures exactly that and
``tests/test_multiprocess.py`` proves kill/revive correctness across real
PIDs (SIGKILL, not cooperative ``stop()``).
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field


# ---------------------------------------------------------------------------
# Cluster spec


@dataclass
class ClusterSpec:
    """Everything a daemon needs to boot: the monmap + deterministic seed.

    The reference splits this across ceph.conf, the monmap file, and the
    mon store's initial osdmap; one JSON file carries all three here.
    """

    mon_addrs: list  # [[host, port], ...] — rank r binds mon_addrs[r]
    n_osds: int
    run_dir: str
    config: dict = field(default_factory=dict)
    keyring: dict = field(default_factory=dict)  # entity -> hex secret
    #: launcher-only knobs outside the typed Config schema (pool ids
    #: for mds/rgw daemons, rgw user database, ...)
    extras: dict = field(default_factory=dict)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(
                {
                    "mon_addrs": [list(a) for a in self.mon_addrs],
                    "n_osds": self.n_osds,
                    "run_dir": self.run_dir,
                    "config": self.config,
                    "keyring": self.keyring,
                    "extras": self.extras,
                },
                f,
                indent=1,
            )

    @classmethod
    def load(cls, path: str) -> "ClusterSpec":
        with open(path) as f:
            d = json.load(f)
        return cls(
            mon_addrs=[tuple(a) for a in d["mon_addrs"]],
            n_osds=d["n_osds"],
            run_dir=d["run_dir"],
            config=d.get("config", {}),
            keyring=d.get("keyring", {}),
            extras=d.get("extras", {}),
        )

    # -- deterministic seeds --------------------------------------------------

    def monmap(self):
        from ceph_tpu.mon import MonMap

        # deterministic uds:// endpoints derived from run_dir: every
        # daemon and client rebuilds the same monmap from the spec, so
        # co-located peers can dial the mon's Unix socket directly. The
        # messenger falls back to TCP whenever the socket is absent (a
        # remote run_dir) or the path exceeds the AF_UNIX limit.
        local = [
            f"uds://{os.path.join(self.run_dir, f'mon.{r}.sock')}"
            for r in range(len(self.mon_addrs))
        ]
        return MonMap(
            addrs=[tuple(a) for a in self.mon_addrs],
            local_addrs=local,
        )

    def build_config(self):
        from ceph_tpu.common.config import Config

        cfg = Config()
        for k, v in self.config.items():
            cfg.set(k, v)
        return cfg

    def initial_osdmap(self):
        return initial_osdmap(self.n_osds)

    def bytes_keyring(self) -> dict | None:
        if not self.keyring:
            return None
        return {k: bytes.fromhex(v) for k, v in self.keyring.items()}


def initial_osdmap(n_osds: int):
    """THE deterministic epoch-1 seed: one host per OSD (failures cross
    failure domains), straw2 root, rule 0 = indep (EC), rule 1 = firstn
    (replicated). Every mon of a cluster must build this identically from
    the spec alone, and the in-process live tier + daemon bench import it
    too, so single-process and multi-process behavior stay comparable."""
    from ceph_tpu.crush import builder as cb
    from ceph_tpu.crush.types import BucketAlg, CrushMap, Tunables
    from ceph_tpu.osd import OSDMap

    cmap = CrushMap(tunables=Tunables.jewel())
    host_ids, host_ws = [], []
    for h in range(n_osds):
        b = cb.make_bucket(
            cmap, -(h + 2), BucketAlg.STRAW2, 1, [h], [0x10000]
        )
        host_ids.append(b.id)
        host_ws.append(b.weight)
    cb.make_bucket(cmap, -1, BucketAlg.STRAW2, 10, host_ids, host_ws)
    cb.make_simple_rule(cmap, 0, -1, 1, "indep", 0)
    cb.make_simple_rule(cmap, 1, -1, 1, "firstn", 0)
    return OSDMap(crush=cmap, max_osd=n_osds)


def pick_ports(n: int) -> list[int]:
    """Reserve n distinct kernel-assigned loopback ports.

    All sockets stay open until every port is collected so the kernel can't
    hand the same port out twice; the (tiny, loopback-only) close->bind race
    is accepted, as vstart.sh accepts it with its fixed port ranges.
    """
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            socks.append(s)
    finally:
        for s in socks:
            s.close()
    return ports


# ---------------------------------------------------------------------------
# Daemon mains (exec'd via python -m ceph_tpu.mon / ceph_tpu.osd / ...)


#: in-flight SIGTERM stop tasks: referenced here so the interpreter can
#: never garbage-collect one mid-stop (cephlint task-leak rule)
_TERM_TASKS: set = set()


def _install_term_handler(loop, stopper) -> None:
    """SIGTERM -> clean daemon stop (the reference's handle_osd_signal);
    SIGKILL needs no handler — that's the crash path tests exercise."""

    def _term():
        task = asyncio.ensure_future(stopper())
        _TERM_TASKS.add(task)
        task.add_done_callback(_TERM_TASKS.discard)

    loop.add_signal_handler(signal.SIGTERM, _term)


async def _run_forever(stop_evt: asyncio.Event) -> None:
    await stop_evt.wait()


def daemon_main(kind: str, ident: int, spec_path: str) -> None:
    """Shared entry point behind ``python -m ceph_tpu.{mon,osd}``."""
    # The axon TPU plugin ignores JAX_PLATFORMS; the platform must be forced
    # through jax.config before the backend initializes.  Test/bench parents
    # ask their daemon children for CPU this way (a single real TPU chip
    # can't be opened by N daemon processes at once anyway).
    plat = os.environ.get("CEPH_TPU_JAX_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    spec = ClusterSpec.load(spec_path)
    from ceph_tpu.common.kv import FileDB

    async def amain() -> None:
        loop = asyncio.get_event_loop()
        stop_evt = asyncio.Event()
        cfg = spec.build_config()
        keyring = spec.bytes_keyring()
        db = None
        if kind in ("mon", "osd"):
            if (
                kind == "osd"
                and cfg.get("osd_objectstore") == "memstore"
            ):
                from ceph_tpu.common.kv import MemDB

                db = MemDB()
            else:
                # kstore-file AND blockstore both persist through this
                # FileDB; a blockstore OSD adds its block file inside
                # the same per-daemon dir (OSDService builds the store
                # from osd_objectstore)
                db = FileDB(
                    os.path.join(spec.run_dir, f"{kind}.{ident}.kv")
                )
        if kind == "mon":
            from ceph_tpu.mon import Monitor

            mon = Monitor(
                ident,
                spec.monmap(),
                spec.initial_osdmap(),
                db=db,
                config=cfg,
                keyring=keyring,
            )
            await mon.start()

            async def _stop():
                await mon.stop()
                stop_evt.set()

            _install_term_handler(loop, _stop)
            print(f"mon.{ident} up at {spec.mon_addrs[ident]}", flush=True)
        elif kind == "osd":
            from ceph_tpu.osd.daemon import OSDService

            osd = OSDService(
                ident, spec.monmap(), db=db, config=cfg, keyring=keyring
            )
            # the reference OSD dlopens every cls plugin at boot; a
            # daemon-main OSD registers all built-in class families so
            # MDS/RGW/journal consumers work against any process
            from ceph_tpu.cephfs.fs import register_fs_classes
            from ceph_tpu.journal.journal import (
                register_journal_classes,
            )
            from ceph_tpu.rgw.gateway import register_rgw_classes

            register_fs_classes(osd)
            register_journal_classes(osd)
            register_rgw_classes(osd)
            await osd.start()

            async def _stop():
                await osd.stop()
                stop_evt.set()

            _install_term_handler(loop, _stop)
            print(f"osd.{ident} up at {osd.messenger.my_addr}", flush=True)
        elif kind == "mds":
            from ceph_tpu.cephfs.mds import MDSService

            mds = MDSService(
                f"mds.{ident}", spec.monmap(),
                int(spec.extras.get("mds_data_pool", 1)),
                config=cfg, keyring=keyring,
            )
            await mds.start()

            async def _stop():
                await mds.stop()
                stop_evt.set()

            _install_term_handler(loop, _stop)
            print(f"mds.{ident} up at {mds.addr}", flush=True)
        elif kind == "rgw":
            from ceph_tpu.rados.client import IoCtx, Rados
            from ceph_tpu.rgw import ObjectGateway, S3Frontend

            rados = Rados(
                f"client.rgw{ident}", spec.monmap(), config=cfg,
                keyring=keyring,
            )
            await rados.connect()
            gw = ObjectGateway(
                IoCtx(rados.objecter,
                      int(spec.extras.get("rgw_data_pool", 2))),
                index_ioctx=IoCtx(
                    rados.objecter,
                    int(spec.extras.get("rgw_index_pool", 1)),
                ),
            )
            users = dict(spec.extras.get("rgw_users") or {})
            front = S3Frontend(gw, users=users)
            port = await front.start()
            # the kernel-assigned port is published for the launcher
            # (vstart.sh writes the same kind of run files); one tiny
            # write at boot, before any IO is served
            with open(  # cephlint: disable=async-blocking
                os.path.join(spec.run_dir, f"rgw.{ident}.port"), "w"
            ) as f:
                f.write(str(port))

            async def _stop():
                await front.stop()
                await rados.shutdown()
                stop_evt.set()

            _install_term_handler(loop, _stop)
            print(f"rgw.{ident} serving S3 on :{port}", flush=True)
        elif kind == "mgr":
            from ceph_tpu.mgr.daemon import MgrService

            mgr = MgrService(
                f"mgr.{ident}", spec.monmap(), config=cfg,
                keyring=keyring,
            )
            await mgr.start()
            port = await mgr.serve_http()
            # boot-time run-file write, before any IO is served
            with open(  # cephlint: disable=async-blocking
                os.path.join(spec.run_dir, f"mgr.{ident}.port"), "w"
            ) as f:
                f.write(str(port))

            async def _stop():
                await mgr.stop()
                stop_evt.set()

            _install_term_handler(loop, _stop)
            print(f"mgr.{ident} http on :{port}", flush=True)
        else:  # pragma: no cover - guarded by argparse choices
            raise SystemExit(f"unknown daemon kind {kind!r}")
        await _run_forever(stop_evt)

    if os.environ.get("CEPH_TPU_PROFILE"):
        import cProfile

        prof = cProfile.Profile()
        prof.enable()
        try:
            asyncio.run(amain())
        finally:
            prof.disable()
            prof.dump_stats(
                os.path.join(spec.run_dir, f"{kind}.{ident}.prof")
            )
    else:
        asyncio.run(amain())


# ---------------------------------------------------------------------------
# The launcher


class VStart:
    """Boot + manage a multi-process cluster from the test/bench process.

    ``start()`` spawns one interpreter per daemon; ``kill_osd`` delivers a
    real signal (default SIGKILL — the crash the thrasher wants);
    ``start_osd`` boots a fresh process for an id over the daemon's
    surviving FileDB, which is the reference's restart-with-intact-store
    path.
    """

    def __init__(
        self,
        run_dir: str,
        n_mons: int = 3,
        n_osds: int = 4,
        config: dict | None = None,
        env: dict | None = None,
    ):
        os.makedirs(run_dir, exist_ok=True)
        cfg = {
            "mon_lease": 0.25,
            "mon_election_timeout": 1.0,
            "osd_heartbeat_interval": 0.25,
            # daemons no longer share a loop: grace can be much tighter
            # than the in-process tier's jit-compile-absorbing 2s
            "osd_heartbeat_grace": 3,
            # keep every daemon's Unix sockets + ring files inside the
            # cluster's run_dir so teardown removes them with the dir
            "ms_uds_dir": run_dir,
        }
        cfg.update(config or {})
        ports = pick_ports(n_mons)
        self.spec = ClusterSpec(
            mon_addrs=[("127.0.0.1", p) for p in ports],
            n_osds=n_osds,
            run_dir=run_dir,
            config=cfg,
        )
        self.spec_path = os.path.join(run_dir, "cluster_spec.json")
        self.spec.save(self.spec_path)
        self.env = dict(os.environ)
        self.env.update(env or {})
        self.mons: dict[int, subprocess.Popen] = {}
        self.osds: dict[int, subprocess.Popen] = {}
        self.extra: dict[tuple, subprocess.Popen] = {}
        self._logs: list = []

    # -- process management ---------------------------------------------------

    #: daemon kind -> python module hosting its __main__
    _KIND_MODULE = {
        "mon": "ceph_tpu.mon",
        "osd": "ceph_tpu.osd",
        "mds": "ceph_tpu.cephfs",
        "rgw": "ceph_tpu.rgw",
        "mgr": "ceph_tpu.mgr",
    }

    def _spawn(self, kind: str, ident: int) -> subprocess.Popen:
        log = open(
            os.path.join(self.spec.run_dir, f"{kind}.{ident}.log"), "ab"
        )
        self._logs.append(log)
        return subprocess.Popen(
            [
                sys.executable,
                "-m",
                self._KIND_MODULE[kind],
                "--id",
                str(ident),
                "--spec",
                self.spec_path,
            ],
            stdout=log,
            stderr=subprocess.STDOUT,
            env=self.env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )

    def start(self) -> None:
        for r in range(len(self.spec.mon_addrs)):
            self.mons[r] = self._spawn("mon", r)
        for i in range(self.spec.n_osds):
            self.osds[i] = self._spawn("osd", i)

    def start_osd(self, osd_id: int) -> None:
        self.osds[osd_id] = self._spawn("osd", osd_id)

    def start_daemon(self, kind: str, ident: int) -> None:
        """Spawn an mds/rgw/mgr process (their pools must exist first —
        the vstart.sh ordering). Pool bindings/users ride spec.extras."""
        self.extra[(kind, ident)] = self._spawn(kind, ident)

    def daemon_port(self, kind: str, ident: int,
                    timeout: float = 60.0) -> int:
        """Kernel-assigned port an rgw/mgr daemon published in its run
        file (vstart.sh's out-dir convention)."""
        path = os.path.join(
            self.spec.run_dir, f"{kind}.{ident}.port"
        )
        end = time.time() + timeout
        while time.time() < end:
            try:
                with open(path) as f:
                    raw = f.read().strip()
                if raw:
                    return int(raw)
            except FileNotFoundError:
                pass
            time.sleep(0.2)
        raise TimeoutError(f"{kind}.{ident} never published a port")

    def kill_osd(self, osd_id: int, sig: int = signal.SIGKILL) -> None:
        p = self.osds.pop(osd_id)
        p.send_signal(sig)
        p.wait(timeout=30)

    def kill_mon(self, rank: int, sig: int = signal.SIGKILL) -> None:
        p = self.mons.pop(rank)
        p.send_signal(sig)
        p.wait(timeout=30)

    def stop(self) -> None:
        procs = (
            list(self.mons.values()) + list(self.osds.values())
            + list(self.extra.values())
        )
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:  # pragma: no cover
                p.kill()
        for log in self._logs:
            log.close()
        self.mons.clear()
        self.osds.clear()
        self.extra.clear()

    # -- client-side helpers --------------------------------------------------

    def client(self, name: str = "client.admin"):
        from ceph_tpu.rados.client import Rados

        return Rados(name, self.spec.monmap(), config=self.spec.build_config())

    async def wait_healthy(
        self, rados=None, osds: set | None = None, timeout: float = 60.0
    ):
        """Wait until the committed osdmap shows every expected OSD up."""
        own = rados is None
        if own:
            rados = self.client()
            await rados.connect()
        want = osds if osds is not None else set(range(self.spec.n_osds))
        loop = asyncio.get_event_loop()
        end = loop.time() + timeout
        try:
            while True:
                m = rados.objecter.osdmap
                if m is not None and all(
                    i < m.max_osd and m.osd_up[i] for i in want
                ):
                    return m
                if loop.time() > end:
                    raise TimeoutError(
                        f"osds {want} not up; map={None if m is None else m.epoch}"
                    )
                await asyncio.sleep(0.1)
        finally:
            if own:
                await rados.shutdown()


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(
        description="boot a multi-process cluster (vstart.sh role)"
    )
    ap.add_argument("--run-dir", default="./vstart-run")
    ap.add_argument("--mons", type=int, default=3)
    ap.add_argument("--osds", type=int, default=4)
    args = ap.parse_args(argv)
    v = VStart(args.run_dir, n_mons=args.mons, n_osds=args.osds)
    v.start()
    print(f"spec: {v.spec_path}")
    print(f"mons: {[p.pid for p in v.mons.values()]}")
    print(f"osds: {[p.pid for p in v.osds.values()]}")
    try:
        asyncio.run(v.wait_healthy())
        print("HEALTH_OK: all osds up — ^C to tear down")
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        v.stop()


if __name__ == "__main__":
    main()
