"""`python -m ceph_tpu.lint` — see ceph_tpu.lint.cli."""

import sys

from ceph_tpu.lint.cli import main

sys.exit(main())
