"""cephlint — project-invariant static analysis for the ceph_tpu tree.

The reference ships correctness tooling next to the code (clang-tidy
wiring, denc round-trip checks in src/test/, the kernel-compat rules on
the CRUSH core); cephlint plays that role here.  PRs 1-10 accreted a set
of unwritten invariants — sleep-free tier-1 tests, `MethodContext.now`
instead of wall clocks inside cls methods, declared-knob-only config
reads, perf counters declared before incremented, no blocking IO on the
OSD event loop, every `asyncio.create_task` tracked — and cephlint turns
each into an AST check that fails the build instead of a review comment.

Layout:

  * `core`      — Finding/check registry, `# cephlint: disable=` comment
                  suppressions, fingerprinted baseline file, runner;
  * `checks`    — the project checks (async-blocking, task-leak,
                  clock-discipline, knob-registry, perf-counter,
                  error-taxonomy);
  * `cli`       — `python -m ceph_tpu.lint` / `tools/lint.py` front end
                  (non-zero exit on new findings, `--baseline-update`,
                  `--json` summary counts);
  * `racecheck` — the RUNTIME half: opt-in (`CEPH_TPU_RACECHECK=1`)
                  asyncio instrumentation that detects lock-order
                  inversions, tasks garbage-collected while pending, and
                  locks held across network IO awaits.
"""

from ceph_tpu.lint.core import (  # noqa: F401
    Finding,
    LintReport,
    load_baseline,
    run_lint,
    write_baseline,
)
from ceph_tpu.lint import checks  # noqa: F401  (registers the checks)

__all__ = [
    "Finding",
    "LintReport",
    "load_baseline",
    "run_lint",
    "write_baseline",
]
