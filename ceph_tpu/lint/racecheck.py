"""Runtime asyncio race/leak detector (CEPH_TPU_RACECHECK=1).

The static side of cephlint proves structural invariants; this module
watches the two failure classes that only exist at runtime:

  * **lock-order inversions** — lockdep-style: every ``asyncio.Lock`` is
    assigned a *lock class* by its creation site (file:line), every
    acquisition while other locks are held adds ``held -> acquiring``
    edges to a global order graph, and a new edge that closes a cycle is
    an inversion: two tasks taking the same pair of lock classes in
    opposite orders can deadlock even on a single-threaded event loop,
    because the loop interleaves at every await.
  * **unawaited-task leaks** — a Task garbage-collected while still
    pending had no live reference: nothing could ever await it, and its
    exception (if any) was silently dropped.  This is the runtime twin
    of the static ``task-leak`` check.

It also *reports* (but does not assert on) locks held across messenger
network awaits: coordination leases held over RADOS IO are by design
(e.g. the checkpoint committer lock spans the save), so
``assert_clean()`` covers only inversions and leaks.

Install with :func:`install` (idempotent); the tier-1 conftest does so
for every test session when ``CEPH_TPU_RACECHECK=1`` and calls
:func:`assert_clean` at teardown.  ``coord.lock.Lock`` participates in
the same order graph via :func:`note_acquire`/:func:`note_release`.
"""

from __future__ import annotations

import asyncio
import asyncio.base_events
import os
import sys
import weakref

ENV = "CEPH_TPU_RACECHECK"

_installed = False
_orig_lock = None
_orig_loop_create_task = None

#: lock-class order graph: class -> set of classes acquired while it was
#: held; edge examples carry one (holder_site, acquirer_site) witness
_order: dict[str, set[str]] = {}
_edge_witness: dict[tuple[str, str], str] = {}
#: per-task held lock classes, keyed by id(task) (stable for its lifetime)
_held: dict[int, list[str]] = {}
#: pending tasks by id -> creation site; removed when the task completes
_pending: dict[int, str] = {}

inversions: list[dict] = []
leaks: list[dict] = []
io_under_lock: list[dict] = []
_seen_inversions: set[tuple[str, str]] = set()
_seen_io: set[tuple[str, ...]] = set()


def wanted() -> bool:
    """True when the environment asks for the race detector."""
    return os.environ.get(ENV, "") not in ("", "0")


def active() -> bool:
    return _installed


_THIS_FILE = os.path.abspath(__file__)
#: filename -> (is_foreign, display_name) memo: _site() runs on EVERY
#: create_task, so the per-frame path normalization must be O(dict hit)
_site_fn_cache: dict[str, tuple[bool, str]] = {}


def _site_fn(fn: str) -> tuple[bool, str]:
    got = _site_fn_cache.get(fn)
    if got is None:
        foreign = (os.path.abspath(fn) != _THIS_FILE
                   and f"{os.sep}asyncio{os.sep}" not in fn)
        display = fn if fn.startswith("<") else os.path.relpath(fn)
        got = _site_fn_cache[fn] = (foreign, display)
    return got


def _site(skip_prefixes: tuple[str, ...] = ()) -> str:
    """file:line of the nearest caller outside this module and asyncio."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        foreign, display = _site_fn(fn)
        if foreign and not fn.startswith(skip_prefixes):
            return f"{display}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _task_key() -> int | None:
    try:
        t = asyncio.current_task()
    except RuntimeError:
        return None
    return None if t is None else id(t)


def _path_exists(src: str, dst: str) -> list[str] | None:
    """DFS: a held-before path src -> ... -> dst in the order graph."""
    stack = [(src, [src])]
    visited = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _order.get(node, ()):
            if nxt not in visited:
                visited.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def note_acquire(lock_class: str, *, blocking: bool = True) -> None:
    """Record that the current task now holds `lock_class`; detect any
    order-graph cycle the new held->acquiring edges introduce.

    Lockdep semantics: only a BLOCKING acquisition adds held->acquiring
    edges — a trylock (coord ``block=False``) fails fast instead of
    waiting, so it cannot complete a deadlock cycle as the acquirer.
    Either way the lock joins the held set: HOLDING it while someone
    else blocks is still half of an inversion."""
    key = _task_key()
    if key is None:
        return
    held = _held.setdefault(key, [])
    if not blocking:
        held.append(lock_class)
        return
    for h in held:
        if h == lock_class:
            continue
        # would h -> lock_class close a cycle? (a path the OTHER way
        # already exists: lock_class held before h somewhere else)
        if (h, lock_class) not in _edge_witness:
            back = _path_exists(lock_class, h)
            if back is not None:
                pair = tuple(sorted((h, lock_class)))
                if pair not in _seen_inversions:
                    _seen_inversions.add(pair)
                    inversions.append({
                        "classes": [h, lock_class],
                        "path_back": back,
                        "witness": _edge_witness.get(
                            (back[0], back[1]), "?"),
                        "at": _site(),
                    })
            _order.setdefault(h, set()).add(lock_class)
            _edge_witness[(h, lock_class)] = _site()
    held.append(lock_class)


def note_release(lock_class: str) -> None:
    key = _task_key()
    if key is None:
        return
    held = _held.get(key)
    if held and lock_class in held:
        # remove the most recent acquisition of that class
        for i in range(len(held) - 1, -1, -1):
            if held[i] == lock_class:
                del held[i]
                break
        if not held:
            _held.pop(key, None)


def note_io(kind: str = "net") -> None:
    """Called from the messenger's socket-write path: report (never
    assert) locks held across a network await."""
    if not _installed:
        return
    key = _task_key()
    if key is None:
        return
    held = _held.get(key)
    if held:
        sig = (kind, *sorted(set(held)))
        if sig not in _seen_io:
            _seen_io.add(sig)
            io_under_lock.append({
                "kind": kind, "held": sorted(set(held)), "at": _site(),
            })


class _TrackedLock(asyncio.Lock):
    """asyncio.Lock that reports acquisition order by creation site."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._rc_class = f"asyncio.Lock@{_site()}"

    async def acquire(self):
        ok = await super().acquire()
        note_acquire(self._rc_class)
        return ok

    def release(self):
        super().release()
        note_release(self._rc_class)


def _track_task(task: asyncio.Task, site: str) -> None:
    key = id(task)
    _pending[key] = site

    def _done(t, _key=key):
        _pending.pop(_key, None)
        _held.pop(_key, None)

    task.add_done_callback(_done)

    def _finalized(_ref, _key=key, _site=site):
        # the weakref died: if the entry is still pending the task was
        # garbage-collected before ever completing — nothing held a
        # reference, nothing could await it
        _task_refs.discard(_ref)
        if _pending.pop(_key, None) is not None:
            leaks.append({"task": _site, "gc": "collected while pending"})

    # keep the ref alive via the registry so the callback can fire
    _task_refs.add(weakref.ref(task, _finalized))


_task_refs: set = set()


def install() -> None:
    """Patch asyncio.Lock and loop.create_task (idempotent)."""
    global _installed, _orig_lock, _orig_loop_create_task
    if _installed:
        return
    _orig_lock = asyncio.Lock
    asyncio.Lock = _TrackedLock
    asyncio.locks.Lock = _TrackedLock

    _orig_loop_create_task = asyncio.base_events.BaseEventLoop.create_task

    def create_task(self, coro, **kw):
        task = _orig_loop_create_task(self, coro, **kw)
        _track_task(task, _site())
        return task

    asyncio.base_events.BaseEventLoop.create_task = create_task
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    asyncio.Lock = _orig_lock
    asyncio.locks.Lock = _orig_lock
    asyncio.base_events.BaseEventLoop.create_task = _orig_loop_create_task
    _installed = False


def reset() -> None:
    """Drop accumulated state (between tests / sessions)."""
    _order.clear()
    _edge_witness.clear()
    _held.clear()
    _pending.clear()
    _task_refs.clear()
    inversions.clear()
    leaks.clear()
    io_under_lock.clear()
    _seen_inversions.clear()
    _seen_io.clear()


def report() -> dict:
    return {
        "inversions": list(inversions),
        "leaks": list(leaks),
        "io_under_lock": list(io_under_lock),
        "lock_classes": len(_order),
    }


def assert_clean() -> None:
    """Raise on inversions or unawaited-task leaks.  io_under_lock is
    informational only (coord leases legitimately span RADOS IO)."""
    import gc
    gc.collect()  # flush pending-task finalizers before judging
    problems = []
    for inv in inversions:
        problems.append(
            f"lock-order inversion between {inv['classes'][0]} and "
            f"{inv['classes'][1]} (reverse path {inv['path_back']}, "
            f"detected at {inv['at']})"
        )
    for leak in leaks:
        problems.append(
            f"task created at {leak['task']} was garbage-collected while "
            "still pending — keep a reference and await it (the OSD._spawn "
            "idiom)"
        )
    if problems:
        raise AssertionError(
            "racecheck: " + "; ".join(problems)
        )
