"""The cephlint checks — PRs 1-10's unwritten invariants, as AST passes.

Every check encodes a rule this tree already lives by:

  * `async-blocking`   — no blocking calls on the event loop (the OSD is
                         single-loop; one `time.sleep` stalls every PG);
  * `task-leak`        — every `asyncio.create_task` result is stored,
                         awaited, or registered with a tracked-task
                         helper (a discarded task is GC-bait: Python may
                         collect it mid-flight and its exceptions vanish);
  * `clock-discipline` — cls methods judge time via `MethodContext.now`
                         (the primary's clock + cls_clock_offset), never
                         the wall clock, and non-slow tier-1 tests are
                         sleep-free (time travel via config, not sleep);
  * `knob-registry`    — config keys read anywhere must be declared in
                         common/config.py's SCHEMA, and every declared
                         knob must be documented (COMPONENTS.md/README)
                         and actually read somewhere (dead knobs rot);
  * `perf-counter`     — counter names bumped on the hot path must be
                         declared in the owning make_*_perf/add_* block
                         (an undeclared name KeyErrors at runtime, but
                         only when that path finally executes);
  * `error-taxonomy`   — `except Exception`/bare except inside ceph_tpu/
                         must re-raise, dout-log, or carry an explicit
                         suppression; `StoreFatalError` (fail-stop by
                         contract, objectstore.py) may never be swallowed.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from ceph_tpu.lint.core import (
    FileContext,
    Finding,
    ProjectContext,
    file_check,
    project_check,
)

# -- shared AST helpers -------------------------------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """`a.b.c` for Name/Attribute chains; None for anything dynamic."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def receiver_tail(func: ast.AST) -> str | None:
    """For a call `X.Y.meth(...)`, the terminal receiver name `Y`."""
    if not isinstance(func, ast.Attribute):
        return None
    value = func.value
    if isinstance(value, ast.Name):
        return value.id
    if isinstance(value, ast.Attribute):
        return value.attr
    return None


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _walk_same_func(body: Iterable[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/lambda/class
    scopes (those run in a different execution context)."""
    stack: list[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue  # a nested scope appearing as a direct statement
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


# -- async-blocking -----------------------------------------------------------

#: dotted calls that block the event loop (unless routed through an
#: executor wrapper — calls inside lambdas/def bodies handed to
#: run_in_executor/to_thread live in another scope and are not walked)
BLOCKING_CALLS = {
    "time.sleep": "blocks the loop; await asyncio.sleep() instead",
    "os.fsync": "blocking device flush; route through run_in_executor",
    "os.fdatasync": "blocking device flush; route through run_in_executor",
    "os.system": "spawns + waits synchronously; use asyncio.create_subprocess_*",
    "subprocess.run": "blocks until the child exits; use "
                      "asyncio.create_subprocess_exec",
    "subprocess.call": "blocks until the child exits",
    "subprocess.check_call": "blocks until the child exits",
    "subprocess.check_output": "blocks until the child exits",
    "socket.create_connection": "synchronous connect; use asyncio streams",
    "socket.getaddrinfo": "synchronous resolve; use loop.getaddrinfo",
}
#: method names that are blocking when called on a raw socket
BLOCKING_SOCKET_METHODS = {"recv", "send", "sendall", "accept", "connect"}


@file_check("async-blocking")
def check_async_blocking(ctx: FileContext) -> Iterator[Finding]:
    if not ctx.path.startswith("ceph_tpu/"):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        for sub in _walk_same_func(node.body):
            if not isinstance(sub, ast.Call):
                continue
            name = dotted_name(sub.func)
            if name in BLOCKING_CALLS:
                yield Finding(
                    "async-blocking", ctx.path, sub.lineno, sub.col_offset,
                    f"{name}() inside `async def {node.name}`: "
                    f"{BLOCKING_CALLS[name]}",
                )
                continue
            if name == "open" or (
                isinstance(sub.func, ast.Name) and sub.func.id == "open"
            ):
                yield Finding(
                    "async-blocking", ctx.path, sub.lineno, sub.col_offset,
                    f"open() inside `async def {node.name}`: file IO "
                    "blocks the loop; route through run_in_executor",
                )
                continue
            tail = receiver_tail(sub.func)
            if (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in BLOCKING_SOCKET_METHODS
                    and tail is not None
                    and (tail == "sock" or tail.endswith("socket"))):
                yield Finding(
                    "async-blocking", ctx.path, sub.lineno, sub.col_offset,
                    f"synchronous socket op {tail}.{sub.func.attr}() inside "
                    f"`async def {node.name}`; use asyncio streams",
                )


# -- task-leak ----------------------------------------------------------------

@file_check("task-leak")
def check_task_leak(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Expr):
            continue
        call = node.value
        if not isinstance(call, ast.Call):
            continue
        name = dotted_name(call.func) or ""
        if name.endswith("create_task") or name.endswith("ensure_future"):
            yield Finding(
                "task-leak", ctx.path, call.lineno, call.col_offset,
                f"{name}(...) result discarded: the task can be "
                "garbage-collected mid-flight and its exception is lost — "
                "store it, await it, or use a tracked-task helper "
                "(OSD._spawn / Messenger._track style)",
            )


# -- clock-discipline ---------------------------------------------------------

def _decorator_is_slow(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Call):
        dec = dec.func
    name = dotted_name(dec) or ""
    return name.split(".")[-1] == "slow"


def _module_is_slow(tree: ast.AST) -> bool:
    for node in getattr(tree, "body", []):
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
            if "pytestmark" in targets and "slow" in ast.dump(node.value):
                return True
    return False


@file_check("clock-discipline")
def check_clock_discipline(ctx: FileContext) -> Iterator[Finding]:
    # rule 1: cls method bodies never read the wall clock — lease/lock
    # arithmetic must use MethodContext.now (cls_clock_offset time travel)
    if ctx.path.endswith("osd/cls.py"):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name in ("time.time", "time.monotonic",
                            "time.perf_counter"):
                    yield Finding(
                        "clock-discipline", ctx.path, node.lineno,
                        node.col_offset,
                        f"{name}() inside osd/cls.py: cls methods must "
                        "judge time via MethodContext.now (the primary's "
                        "clock + cls_clock_offset), never the wall clock",
                    )
        return
    # rule 2: non-slow tier-1 tests are sleep-free (PR 10's rule: leases
    # time-travel via cls_clock_offset, never wall-clock waits)
    if not ctx.path.startswith("tests/"):
        return
    if _module_is_slow(ctx.tree):
        return

    def visit(body, slow: bool):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                here = slow or any(_decorator_is_slow(d)
                                   for d in node.decorator_list)
                yield from visit(node.body, here)
                continue
            if slow:
                continue
            for sub in _walk_same_func([node]):
                if not isinstance(sub, ast.Call):
                    continue
                name = dotted_name(sub.func)
                if name == "time.sleep":
                    yield Finding(
                        "clock-discipline", ctx.path, sub.lineno,
                        sub.col_offset,
                        "time.sleep() in a non-slow test: tier-1 is "
                        "sleep-free — advance time via cls_clock_offset "
                        "or mark the test @pytest.mark.slow",
                    )
                elif name == "asyncio.sleep" and sub.args:
                    arg = sub.args[0]
                    if (isinstance(arg, ast.Constant)
                            and isinstance(arg.value, (int, float))
                            and arg.value > 0):
                        yield Finding(
                            "clock-discipline", ctx.path, sub.lineno,
                            sub.col_offset,
                            f"asyncio.sleep({arg.value}) in a non-slow "
                            "test: tier-1 is sleep-free — sleep(0) "
                            "yield-points are fine, timed waits are not",
                        )

    yield from visit(ctx.tree.body, slow=False)


# -- dispatch-blocking --------------------------------------------------------

#: dispatcher entry points: the messenger awaits these inline on the
#: connection's read loop, so anything they await on stalls EVERY later
#: message on that connection (and holds dispatch-throttle bytes)
_HANDLER_PREFIXES = ("ms_handle_", "_h_")

#: receivers whose awaited methods are client-side RADOS round trips —
#: a dispatch handler awaiting one parks this connection's stream on
#: another daemon's reply (deadlock-bait when that daemon is also
#: waiting on us)
_RADOS_IO_RECEIVERS = {"rados", "objecter", "ioctx"}


def _dispatch_handlers(tree: ast.AST) -> Iterator[ast.AsyncFunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef) and (
            node.name == "ms_dispatch"
            or node.name.startswith(_HANDLER_PREFIXES)
        ):
            yield node


@file_check("dispatch-blocking")
def check_dispatch_blocking(ctx: FileContext) -> Iterator[Finding]:
    """No lock waits or client-side RADOS IO inline in dispatch.

    `ms_dispatch` / `ms_handle_*` / `_h_*` handlers run on the
    connection's single read loop. An `await lock.acquire()` (or
    `async with lock:`) there stalls every queued message behind the
    lock holder; awaiting a RADOS round trip parks the stream on a
    peer's reply. Either belongs in a tracked task the handler spawns.
    """
    if not ctx.path.startswith("ceph_tpu/"):
        return
    for fn in _dispatch_handlers(ctx.tree):
        for sub in _walk_same_func(fn.body):
            if isinstance(sub, ast.AsyncWith):
                for item in sub.items:
                    name = dotted_name(item.context_expr) or ""
                    tail = name.split(".")[-1].lower()
                    if "lock" in tail or "mutex" in tail:
                        yield Finding(
                            "dispatch-blocking", ctx.path,
                            sub.lineno, sub.col_offset,
                            f"`async with {name}` inside dispatch handler "
                            f"`{fn.name}`: every later message on this "
                            "connection queues behind the lock holder — "
                            "move the guarded work to a tracked task",
                        )
                continue
            if not isinstance(sub, ast.Await):
                continue
            call = sub.value
            if not isinstance(call, ast.Call):
                continue
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr == "acquire"):
                name = dotted_name(call.func) or "lock.acquire"
                yield Finding(
                    "dispatch-blocking", ctx.path, sub.lineno,
                    sub.col_offset,
                    f"`await {name}()` inside dispatch handler "
                    f"`{fn.name}`: the connection's read loop blocks "
                    "until the lock frees — move the guarded work to a "
                    "tracked task",
                )
                continue
            tail = receiver_tail(call.func)
            if (isinstance(call.func, ast.Attribute)
                    and tail in _RADOS_IO_RECEIVERS):
                yield Finding(
                    "dispatch-blocking", ctx.path, sub.lineno,
                    sub.col_offset,
                    f"client RADOS IO `await {tail}."
                    f"{call.func.attr}(...)` inside dispatch handler "
                    f"`{fn.name}`: the stream parks on another daemon's "
                    "reply while this connection's messages queue — "
                    "spawn it as a tracked task instead",
                )


# -- knob-registry ------------------------------------------------------------

_CONFIG_RECEIVERS = ("config", "cfg", "conf")


def _is_config_receiver(tail: str | None) -> bool:
    if tail is None:
        return False
    tail = tail.lstrip("_")
    return tail in _CONFIG_RECEIVERS or tail.endswith("config") \
        or tail.endswith("cfg")


_schema_cache: dict[str, tuple[set[str], set[str]] | None] = {}


def _schema_names(ctx: FileContext) -> tuple[set[str], set[str]] | None:
    """(exact names, family prefixes) declared by the project's own
    common/config.py — parsed from ITS root (so scratch corpora under a
    tmp root see their own stub schema, not the installed one), or None
    when that root has no schema to enforce against."""
    import os
    root = (ctx.abspath[:-len(ctx.path)]
            if ctx.abspath.endswith(ctx.path) else "")
    if root in _schema_cache:
        return _schema_cache[root]
    exact: set[str] = set()
    prefixes: set[str] = set()
    cfg = os.path.join(root, "ceph_tpu", "common", "config.py")
    try:
        with open(cfg, encoding="utf-8", errors="replace") as fp:
            tree = ast.parse(fp.read())
    except (OSError, SyntaxError):
        tree = None
    if tree is not None:
        for node in ast.walk(tree):
            # _opt("name", ...) declarations; f-string first args are
            # templated families (debug_<subsys>, tracer_sample_rate_<op>)
            if (isinstance(node, ast.Call)
                    and dotted_name(node.func) == "_opt" and node.args):
                s = str_const(node.args[0])
                if s is not None:
                    exact.add(s)
                elif (isinstance(node.args[0], ast.JoinedStr)
                      and node.args[0].values):
                    head = str_const(node.args[0].values[0])
                    if head:
                        prefixes.add(head)
            # SCHEMA = {"name": ...} literal (corpus stubs)
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Dict):
                if any(isinstance(t, ast.Name) and t.id == "SCHEMA"
                       for t in node.targets):
                    for k in node.value.keys:
                        s = str_const(k)
                        if s is not None:
                            exact.add(s)
    result = (exact, prefixes) if (exact or prefixes) else None
    _schema_cache[root] = result
    return result


@file_check("knob-registry")
def check_knob_reads(ctx: FileContext) -> Iterator[Finding]:
    if ctx.path.endswith("common/config.py"):
        return
    schema = _schema_names(ctx)
    if schema is None:
        return  # no SCHEMA at this root: nothing to enforce against
    exact, families = schema
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or not isinstance(node.func,
                                                            ast.Attribute):
            continue
        meth = node.func.attr
        if meth in ("get", "source_of", "rm"):
            want_args = 1
        elif meth in ("set", "observe"):
            want_args = 2
        else:
            continue
        if len(node.args) != want_args or node.keywords:
            continue  # dict.get(k, default) etc — not the Config API
        if not _is_config_receiver(receiver_tail(node.func)):
            continue
        key = str_const(node.args[0])
        if (key is not None and key not in exact
                and not any(key.startswith(p) for p in families)):
            yield Finding(
                "knob-registry", ctx.path, node.lineno, node.col_offset,
                f"config key {key!r} is not declared in "
                "common/config.py SCHEMA — declare the knob (with "
                "type/level/default/description) before reading it",
            )


@project_check("knob-registry")
def check_knob_inventory(project: ProjectContext) -> Iterator[Finding]:
    """Declared knobs must be documented AND read somewhere (dead or
    undocumented knobs are reported at their SCHEMA declaration line)."""
    try:
        import ceph_tpu
        from ceph_tpu.common.config import SCHEMA
    except ImportError:
        return
    import os
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(ceph_tpu.__file__)))
    if os.path.realpath(project.root) != os.path.realpath(pkg_root):
        return  # scratch corpus root: its stub schema is not importable
    config_ctx = None
    for f in project.files:
        if f.path.endswith("common/config.py"):
            config_ctx = f
            break
    if config_ctx is None or config_ctx.tree is None:
        return  # config.py not under lint — nothing to anchor to

    # where is each knob declared? exact literals + f-string families
    anchors: dict[str, int] = {}
    family_anchors: list[tuple[str, int]] = []  # (literal prefix, line)
    for node in ast.walk(config_ctx.tree):
        s = str_const(node)
        if s is not None and s in SCHEMA:
            anchors.setdefault(s, node.lineno)
        if isinstance(node, ast.JoinedStr) and node.values:
            head = str_const(node.values[0])
            if head:
                family_anchors.append((head, node.lineno))

    def anchor(name: str) -> int:
        if name in anchors:
            return anchors[name]
        best = 1
        for prefix, line in family_anchors:
            if name.startswith(prefix):
                best = line
        return best

    # everything the rest of the tree mentions: exact string literals,
    # f-string constant fragments (templated families like
    # f"tracer_sample_rate_{op}"), and CEPH_TPU_<NAME> env spellings
    exact: set[str] = set()
    prefixes: set[str] = set()

    def harvest(tree: ast.AST) -> None:
        for node in ast.walk(tree):
            s = str_const(node)
            if s is not None:
                exact.add(s)
                if s.startswith("CEPH_TPU_"):
                    exact.add(s[len("CEPH_TPU_"):].lower())
            elif isinstance(node, ast.JoinedStr):
                for part in node.values:
                    ps = str_const(part)
                    if ps and len(ps) >= 4:
                        prefixes.add(ps)

    seen_paths = set()
    for f in project.files:
        seen_paths.add(f.abspath)
        if f.tree is None or f.path.endswith("common/config.py"):
            continue
        harvest(f.tree)

    # a knob read only by the benchmark/tooling layer is still live, even
    # when the lint invocation targets just ceph_tpu/ + tests/
    import glob
    import os
    for aux in (glob.glob(os.path.join(project.root, "tools", "*.py"))
                + [os.path.join(project.root, "bench.py")]):
        if os.path.abspath(aux) in seen_paths or not os.path.isfile(aux):
            continue
        try:
            with open(aux, encoding="utf-8", errors="replace") as fp:
                harvest(ast.parse(fp.read()))
        except (OSError, SyntaxError):
            continue

    docs = ""
    for doc in ("COMPONENTS.md", "README.md"):
        p = f"{project.root}/{doc}"
        try:
            with open(p, encoding="utf-8", errors="replace") as fp:
                docs += fp.read()
        except OSError:
            pass
    # docs may describe templated families as `prefix_<placeholder>`
    import re
    doc_families = {m.group(1) for m in
                    re.finditer(r"([a-z0-9_]+_)<[a-zA-Z]+>", docs)}

    for name in sorted(SCHEMA):
        documented = name in docs or any(name.startswith(p)
                                         for p in doc_families)
        live = name in exact or any(name.startswith(p) for p in prefixes)
        if not documented:
            yield Finding(
                "knob-registry", "ceph_tpu/common/config.py", anchor(name), 0,
                f"declared knob {name!r} is undocumented — mention it in "
                "COMPONENTS.md or README.md (families may be documented "
                "as `prefix_<placeholder>`)",
            )
        if not live:
            yield Finding(
                "knob-registry", "ceph_tpu/common/config.py", anchor(name), 0,
                f"declared knob {name!r} is never read anywhere under "
                "lint — dead knob: delete it or wire it up",
            )


# -- perf-counter -------------------------------------------------------------

_DECLARE_METHODS = {"add_u64", "add_u64_counter", "add_time_avg",
                    "add_histogram"}
_BUMP_METHODS = {"inc", "dec", "set", "set_max", "tinc", "hinc", "time"}


def _is_perf_receiver(tail: str | None) -> bool:
    if tail is None:
        return False
    tail = tail.lstrip("_")
    return tail == "perf" or tail.endswith("perf") or tail == "counters"


def _declared_counter_names(tree: ast.AST) -> Iterator[str]:
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _DECLARE_METHODS and node.args):
            key = str_const(node.args[0])
            if key is not None:
                yield key
        # the loop-declaration idiom: `for key, desc in ((...), ...):
        # perf.add_u64_counter(key, desc)` — harvest the iterated names
        if isinstance(node, ast.For):
            has_decl = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in _DECLARE_METHODS
                for n in ast.walk(node)
            )
            if not has_decl:
                continue
            for elt in ast.walk(node.iter):
                if isinstance(elt, (ast.Tuple, ast.List)) and elt.elts:
                    first = str_const(elt.elts[0])
                    if first is not None:
                        yield first


@project_check("perf-counter")
def check_perf_counters(project: ProjectContext) -> Iterator[Finding]:
    declared: set[str] = set()
    for f in project.files:
        if f.tree is None:
            continue
        declared.update(_declared_counter_names(f.tree))
    if not declared:
        return  # corpus without any perf blocks: nothing to enforce
    for f in project.files:
        if f.tree is None or f.path.endswith("common/perf_counters.py"):
            continue
        for node in ast.walk(f.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _BUMP_METHODS and node.args):
                continue
            if not _is_perf_receiver(receiver_tail(node.func)):
                continue
            key = str_const(node.args[0])
            if key is not None and key not in declared:
                yield Finding(
                    "perf-counter", f.path, node.lineno, node.col_offset,
                    f"counter {key!r} bumped via .{node.func.attr}() but "
                    "never declared in any make_*_perf/add_* block — this "
                    "KeyErrors the first time the path executes",
                )


# -- error-taxonomy -----------------------------------------------------------

#: call names inside a handler that count as "the error was reported"
_LOG_CALL_NAMES = {"dout", "cluster_log", "warning", "error", "exception",
                   "critical", "print_exc", "format_exc", "set_exception"}


def _handler_catches(handler: ast.ExceptHandler, names: set[str]) -> bool:
    t = handler.type
    if t is None:
        return "BARE" in names
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        tail = (dotted_name(e) or "").split(".")[-1]
        if tail in names:
            return True
    return False


def _handler_reports(handler: ast.ExceptHandler) -> bool:
    """True when the handler deals with the error rather than dropping
    it: re-raise, a log/report call, an error-counter bump, or any real
    use of the bound exception (stashing it, appending it to an error
    list, folding it into a reply)."""
    for node in _walk_same_func(handler.body):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = (dotted_name(node.func) or "").split(".")[-1]
            if name in _LOG_CALL_NAMES:
                return True
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "inc"
                    and _is_perf_receiver(receiver_tail(node.func))):
                return True
        if (handler.name and isinstance(node, ast.Name)
                and node.id == handler.name
                and isinstance(node.ctx, ast.Load)):
            return True
    return False


@file_check("error-taxonomy")
def check_error_taxonomy(ctx: FileContext) -> Iterator[Finding]:
    if not ctx.path.startswith("ceph_tpu/"):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        # StoreFatalError is fail-stop by contract: never swallowed, even
        # with logging — the handler must re-raise (fencing happens at the
        # raise site; see osd/objectstore.py's error taxonomy)
        if _handler_catches(node, {"StoreFatalError", "BARE", "Exception",
                                   "BaseException"}):
            fatal = _handler_catches(node, {"StoreFatalError"})
            has_raise = any(isinstance(n, ast.Raise)
                            for n in _walk_same_func(node.body))
            if fatal and not has_raise:
                yield Finding(
                    "error-taxonomy", ctx.path, node.lineno, node.col_offset,
                    "StoreFatalError caught without re-raise: fatal store "
                    "errors are fail-stop by contract (objectstore.py) and "
                    "may never be swallowed",
                )
                continue
            if fatal:
                continue
            # the shutdown-drain idiom: `except (asyncio.CancelledError,
            # Exception): pass` while awaiting a task being torn down.
            # Naming CancelledError (a BaseException) NEXT TO Exception is
            # deliberate — the task's outcome is irrelevant by then — and
            # is this codebase's marker for "drain, don't report"
            if _handler_catches(node, {"CancelledError"}):
                continue
            if not _handler_reports(node):
                what = "bare except" if node.type is None else \
                    "except Exception"
                yield Finding(
                    "error-taxonomy", ctx.path, node.lineno, node.col_offset,
                    f"{what} swallows the error: re-raise, log via dout/"
                    "cluster_log, or add `# cephlint: disable=error-"
                    "taxonomy` with a comment saying why",
                )
