"""cephlint CLI — `python -m ceph_tpu.lint` / `tools/lint.py`.

Exit status is the contract: 0 when no NEW findings (everything is either
clean, comment-suppressed, or grandfathered in the baseline), 1 when new
findings exist, 2 on usage errors.  `--baseline-update` rewrites the
baseline to the current finding set (pruning stale entries), which is the
only sanctioned way to grow it.  `--json` emits the summary counters
(checks run, findings, suppressions, baseline size) as one JSON object so
suppression-debt can be tracked across PRs like a bench metric.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from ceph_tpu.lint.core import (
    all_check_names,
    load_baseline,
    run_lint,
    write_baseline,
)

DEFAULT_PATHS = ["ceph_tpu", "tests"]
DEFAULT_BASELINE = "tools/lint_baseline.json"


def find_repo_root(start: str | None = None) -> str:
    """Nearest ancestor that contains the ceph_tpu package."""
    d = os.path.abspath(start or os.getcwd())
    while True:
        if os.path.isdir(os.path.join(d, "ceph_tpu")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            return os.path.abspath(start or os.getcwd())
        d = parent


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="cephlint",
        description="project-invariant static analysis for the ceph_tpu "
                    "tree (see COMPONENTS.md 'Static analysis & "
                    "invariants')",
    )
    parser.add_argument("paths", nargs="*", default=None,
                        help=f"files/dirs to lint (default: "
                             f"{' '.join(DEFAULT_PATHS)})")
    parser.add_argument("--root", default=None,
                        help="repo root (default: auto-detected)")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline file (default: {DEFAULT_BASELINE} "
                             "under the root)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: report every finding "
                        "as new")
    parser.add_argument("--baseline-update", action="store_true",
                        help="rewrite the baseline to the current finding "
                        "set (prunes stale entries) and exit 0")
    parser.add_argument("--check", action="append", default=None,
                        metavar="NAME",
                        help="run only this check (repeatable)")
    parser.add_argument("--list-checks", action="store_true",
                        help="list registered checks and exit")
    parser.add_argument("--json", action="store_true",
                        help="emit the summary as one JSON object on "
                        "stdout (findings go to stderr)")
    args = parser.parse_args(argv)

    if args.list_checks:
        for name in all_check_names():
            print(name)
        return 0

    root = os.path.abspath(args.root) if args.root else find_repo_root()
    paths = args.paths or DEFAULT_PATHS
    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    baseline = [] if args.no_baseline else load_baseline(baseline_path)

    t0 = time.perf_counter()
    report = run_lint(paths, root=root, baseline=baseline, only=args.check)
    seconds = time.perf_counter() - t0

    if args.baseline_update:
        write_baseline(baseline_path, report.findings)
        print(f"cephlint: baseline rewritten with "
              f"{len(report.findings)} finding(s) -> {baseline_path}")
        return 0

    out = sys.stderr if args.json else sys.stdout
    for f in report.new:
        print(f.render(), file=out)
    if report.stale_baseline:
        print(f"cephlint: {len(report.stale_baseline)} stale baseline "
              "entr(ies) no longer fire — run --baseline-update to shrink "
              "the baseline", file=out)

    summary = report.summary()
    summary["seconds"] = round(seconds, 3)
    summary["baseline_size"] = len(baseline)
    if args.json:
        print(json.dumps(summary, sort_keys=True))
    else:
        print(f"cephlint: {report.files} files, "
              f"{len(report.checks)} checks, "
              f"{len(report.new)} new / {len(report.baselined)} baselined "
              f"/ {report.suppressed} suppressed finding(s) "
              f"in {seconds:.2f}s")
    return 1 if report.new else 0


if __name__ == "__main__":
    sys.exit(main())
