"""cephlint core — findings, suppressions, baseline, check registry, runner.

The shape mirrors the tooling the reference wires around its tree
(clang-tidy with NOLINT comments and a warnings baseline): every check is
a small function over a parsed file (or over the whole project), findings
are suppressable in place with `# cephlint: disable=<check>` comments, and
a committed baseline file grandfathers pre-existing findings so the CLI
can gate on NEW findings only while the debt is paid down.

Suppression syntax (comment anywhere on the offending line, or on a
comment-only line directly above it):

    time.sleep(0.1)  # cephlint: disable=async-blocking
    # cephlint: disable=task-leak
    asyncio.create_task(fire_and_forget())

File-level (usually in the module docstring area):

    # cephlint: disable-file=clock-discipline

`disable=all` disables every check for that line/file.

Baseline entries are matched by content fingerprint — a hash of
(check, path, normalized source line, occurrence index) — so findings
survive unrelated line-number drift but die with the code they flag.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

#: suppression comment: `# cephlint: disable=check-a,check-b`
_SUPPRESS_RE = re.compile(
    r"#\s*cephlint:\s*(disable|disable-file)\s*=\s*([a-zA-Z0-9_,\- ]+)"
)

SKIP_DIRS = {"__pycache__", ".git", ".claude", "node_modules"}


@dataclass
class Finding:
    check: str
    path: str        # repo-relative, forward slashes
    line: int        # 1-based
    col: int
    message: str
    fingerprint: str = ""

    def key(self) -> tuple:
        return (self.path, self.line, self.col, self.check)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.check}] {self.message}"

    def as_dict(self) -> dict[str, Any]:
        return {
            "check": self.check,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


@dataclass
class FileContext:
    """One parsed source file as the checks see it."""

    path: str                 # repo-relative
    abspath: str
    source: str
    tree: ast.AST | None
    lines: list[str] = field(default_factory=list)
    #: line -> set of disabled check names (line-level suppressions)
    line_disables: dict[int, set[str]] = field(default_factory=dict)
    #: whole-file disabled check names
    file_disables: set[str] = field(default_factory=set)

    def line_src(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


@dataclass
class ProjectContext:
    """Everything a cross-file check needs: every parsed file + the root."""

    root: str
    files: list[FileContext]

    def get(self, path: str) -> FileContext | None:
        for f in self.files:
            if f.path == path:
                return f
        return None


#: name -> fn(FileContext) -> Iterable[Finding]
FILE_CHECKS: dict[str, Callable[[FileContext], Iterable[Finding]]] = {}
#: name -> fn(ProjectContext) -> Iterable[Finding]
PROJECT_CHECKS: dict[str, Callable[[ProjectContext], Iterable[Finding]]] = {}


def file_check(name: str):
    def deco(fn):
        FILE_CHECKS[name] = fn
        fn.check_name = name
        return fn
    return deco


def project_check(name: str):
    def deco(fn):
        PROJECT_CHECKS[name] = fn
        fn.check_name = name
        return fn
    return deco


def all_check_names() -> list[str]:
    return sorted(set(FILE_CHECKS) | set(PROJECT_CHECKS))


# -- suppression scanning -----------------------------------------------------

def _scan_suppressions(source: str) -> tuple[dict[int, set[str]], set[str]]:
    """Comment tokens -> (line -> disabled checks, file-level checks).

    A comment on a code line suppresses that line; a comment on a line of
    its own suppresses the next line as well (the clang-tidy NOLINTNEXTLINE
    convention, without needing a second spelling).
    """
    line_disables: dict[int, set[str]] = {}
    file_disables: set[str] = set()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return line_disables, file_disables
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        checks = {c.strip() for c in m.group(2).split(",") if c.strip()}
        if m.group(1) == "disable-file":
            file_disables |= checks
            continue
        lineno = tok.start[0]
        line_disables.setdefault(lineno, set()).update(checks)
        # comment-only line: also covers the line below
        if tok.line.strip().startswith("#"):
            line_disables.setdefault(lineno + 1, set()).update(checks)
    return line_disables, file_disables


def _is_suppressed(finding: Finding, ctx: FileContext) -> bool:
    for scope in (ctx.file_disables, ctx.line_disables.get(finding.line, ())):
        if finding.check in scope or "all" in scope:
            return True
    return False


# -- fingerprints & baseline --------------------------------------------------

def _fingerprint(check: str, path: str, norm_line: str, index: int) -> str:
    blob = f"{check}|{path}|{norm_line}|{index}".encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def assign_fingerprints(findings: list[Finding],
                        files: dict[str, FileContext]) -> None:
    seen: dict[tuple, int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.check)):
        ctx = files.get(f.path)
        norm = ctx.line_src(f.line).strip() if ctx else ""
        bucket = (f.check, f.path, norm)
        index = seen.get(bucket, 0)
        seen[bucket] = index + 1
        f.fingerprint = _fingerprint(f.check, f.path, norm, index)


def load_baseline(path: str) -> list[dict[str, Any]]:
    if not os.path.exists(path):
        return []
    with open(path) as fp:
        data = json.load(fp)
    return list(data.get("findings", []))


def write_baseline(path: str, findings: list[Finding]) -> None:
    data = {
        "comment": "cephlint grandfathered findings; shrink me toward "
                   "empty, never grow me by hand (tools/lint.py "
                   "--baseline-update)",
        "findings": [f.as_dict() for f in
                     sorted(findings, key=Finding.key)],
    }
    with open(path, "w") as fp:
        json.dump(data, fp, indent=1, sort_keys=True)
        fp.write("\n")


# -- runner -------------------------------------------------------------------

@dataclass
class LintReport:
    findings: list[Finding]          # every unsuppressed finding
    new: list[Finding]               # not covered by the baseline
    baselined: list[Finding]         # matched a baseline fingerprint
    stale_baseline: list[dict]       # baseline entries that no longer fire
    suppressed: int                  # findings silenced by comments
    files: int
    checks: list[str]

    @property
    def ok(self) -> bool:
        return not self.new

    def summary(self) -> dict[str, Any]:
        per_check: dict[str, int] = {}
        for f in self.findings:
            per_check[f.check] = per_check.get(f.check, 0) + 1
        return {
            "files": self.files,
            "checks_run": len(self.checks),
            "findings": len(self.findings),
            "new": len(self.new),
            "baselined": len(self.baselined),
            "stale_baseline": len(self.stale_baseline),
            "suppressed": self.suppressed,
            "per_check": dict(sorted(per_check.items())),
            "ok": self.ok,
        }


def collect_files(paths: Iterable[str], root: str) -> list[str]:
    """Expand files/dirs into a sorted list of .py paths (repo-relative)."""
    out: set[str] = set()
    for p in paths:
        absp = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(absp):
            out.add(os.path.relpath(absp, root))
            continue
        for dirpath, dirnames, filenames in os.walk(absp):
            dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
            for name in filenames:
                if name.endswith(".py"):
                    out.add(os.path.relpath(os.path.join(dirpath, name), root))
    return sorted(o.replace(os.sep, "/") for o in out)


def parse_file(relpath: str, root: str) -> FileContext:
    abspath = os.path.join(root, relpath)
    with open(abspath, encoding="utf-8", errors="replace") as fp:
        source = fp.read()
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError:
        tree = None
    line_dis, file_dis = _scan_suppressions(source)
    return FileContext(
        path=relpath.replace(os.sep, "/"), abspath=abspath, source=source,
        tree=tree, lines=source.splitlines(),
        line_disables=line_dis, file_disables=file_dis,
    )


def run_lint(paths: Iterable[str], root: str | None = None,
             baseline: list[dict[str, Any]] | None = None,
             only: Iterable[str] | None = None) -> LintReport:
    """Lint `paths` (files/dirs, relative to `root`) and diff the result
    against `baseline` entries. `only` restricts the checks run."""
    root = os.path.abspath(root or os.getcwd())
    selected = set(only) if only else None

    contexts = [parse_file(p, root) for p in collect_files(paths, root)]
    by_path = {c.path: c for c in contexts}

    raw: list[Finding] = []
    checks_run: list[str] = []
    for name, fn in sorted(FILE_CHECKS.items()):
        if selected and name not in selected:
            continue
        checks_run.append(name)
        for ctx in contexts:
            if ctx.tree is None:
                continue
            raw.extend(fn(ctx))
    project = ProjectContext(root=root, files=contexts)
    for name, fn in sorted(PROJECT_CHECKS.items()):
        if selected and name not in selected:
            continue
        checks_run.append(name)
        raw.extend(fn(project))

    for ctx in contexts:
        if ctx.tree is None and ctx.path.endswith(".py"):
            raw.append(Finding("parse", ctx.path, 1, 0,
                               "file does not parse"))

    kept: list[Finding] = []
    suppressed = 0
    for f in raw:
        ctx = by_path.get(f.path)
        if ctx is not None and _is_suppressed(f, ctx):
            suppressed += 1
        else:
            kept.append(f)
    kept.sort(key=Finding.key)
    assign_fingerprints(kept, by_path)

    base_fps = {e.get("fingerprint"): e for e in (baseline or [])}
    new = [f for f in kept if f.fingerprint not in base_fps]
    old = [f for f in kept if f.fingerprint in base_fps]
    live_fps = {f.fingerprint for f in kept}
    stale = [e for e in (baseline or [])
             if e.get("fingerprint") not in live_fps]

    return LintReport(findings=kept, new=new, baselined=old,
                      stale_baseline=stale, suppressed=suppressed,
                      files=len(contexts), checks=checks_run)
