"""Driver benchmark: RS(8,3) erasure-code encode + decode on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
`value` is the encode throughput; `decode_gbps` rides along as an extra key
so the decode number is driver-recorded too (VERDICT round-1 items 1 and 3).

Workload: the north-star configuration from BASELINE.md — RS(8,3), the chunk
data of many concurrent objects packed chunk-planar into a (k, N) uint8 =
(k, N/4) int32 HBM tensor (256 MiB of data per launch), encoded/decoded by the
fused packed-lane Pallas kernel (ceph_tpu.ops.gf_pallas). The reference
measures the same workload with `ceph_erasure_code_benchmark -p isa -P k=8 -P
m=3` (/root/reference/src/erasure-code/isa/README). Decode rebuilds 3 erased
data chunks from the 8 surviving chunks (worst-case full-parity repair).

Timing methodology: the device sits behind a tunnel where a device->host fetch
costs ~100 ms and block_until_ready does not actually block, so per-call wall
timing is useless. The op is iterated inside one jitted lax.fori_loop at two
trip counts; the time delta over the trip delta gives per-op device time with
dispatch+fetch overhead cancelled. Each iteration is made data-dependent on
the previous one by (a) folding one output element per grid block into a
scalar (so every block must be computed) and (b) poking that scalar back into
the input words (so XLA cannot hoist or elide the op).

vs_baseline divides by a MEASURED single-thread CPU baseline: 2.19 GB/s for
the bit-plane XOR-schedule C encoder (tools/ec_cpu_baseline.c, the reference's
jerasure-bitmatrix algorithm class) on this repo's 1-core Xeon 2.1 GHz host —
see BASELINE.md for the measurement and for the ISA-L AVX512 context.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# measured by tools/cpu_ec_baseline.py on the repo host (see BASELINE.md)
BASELINE_GBPS = 2.19

K, M = 8, 3
N4 = 8 * 1024 * 1024  # int32 words per chunk row: k * N4 * 4 = 256 MiB data
PROBE_STRIDE = 65536  # matches gf_pallas.DEFAULT_TILE_WORDS: 1 probe per block


def measure_seconds(fn, words, n_lo: int = 10, n_hi: int = 110) -> float:
    """Per-op seconds via the two-trip-count delta method (see module doc)."""
    import jax
    import jax.numpy as jnp

    def make_chain(n):
        @jax.jit
        def chain(d):
            def body(_, carry):
                d, s = carry
                p = fn(d)
                s = s ^ p[0, ::PROBE_STRIDE].sum()  # touch every grid block
                d = jax.lax.dynamic_update_slice(
                    d, s[None, None].astype(d.dtype), (0, 0)
                )
                return d, s

            _, s = jax.lax.fori_loop(0, n, body, (d, jnp.int32(0)))
            return s

        return chain

    lo, hi = make_chain(n_lo), make_chain(n_hi)

    def run(chain):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = chain(words)
            np.asarray(out)  # force completion through the tunnel
            best = min(best, time.perf_counter() - t0)
        return best

    run(lo), run(hi)  # compile both
    return max(1e-9, (run(hi) - run(lo)) / (n_hi - n_lo))


def _store_bench_line() -> None:
    """Optional second JSON line: a quick BlockStore store-bench so the
    BENCH trajectory tracks store MB/s alongside EC GB/s. Guarded (off
    unless --store-bench / CEPH_TPU_BENCH_STORE=1) and non-fatal — the
    driver's one-line contract for the EC metric is never at risk."""
    try:
        import io
        import tempfile
        from contextlib import redirect_stderr, redirect_stdout

        from tools import store_bench

        with tempfile.TemporaryDirectory(prefix="bench_store_") as d:
            out = os.path.join(d, "store.json")
            with redirect_stdout(io.StringIO()), \
                    redirect_stderr(io.StringIO()):
                store_bench.main([
                    "--backend", "blockstore",
                    "--sizes", "65536",
                    "--small-sizes", "1024",
                    "--bytes-per-case", str(4 << 20),
                    "--dir", d,
                    "--out", out,
                ])
            with open(out) as f:
                results = json.load(f)["results"]
        rw = next(r for r in results if r["workload"] == "rw")
        small = next(r for r in results if r["workload"] == "small-write")
        print(
            json.dumps({
                "metric": "blockstore_reread_throughput",
                "value": round(rw["reread_mbps"], 1),
                "unit": "MB/s",
                "write_mbps": round(rw["write_mbps"], 1),
                "read_mbps": round(rw["read_mbps"], 1),
                "small_write_iops": round(small["write_iops"], 1),
                "deferred_flushes": small["perf"]["deferred_flushes"],
                "buffer_hit_rate": round(
                    rw["perf"]["buffer_hit_rate"], 3
                ),
            })
        )
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def _fault_overhead_line() -> None:
    """Optional JSON line: BlockStore throughput with every device-fault
    knob at 0 (the shipped default) plus the measured per-site cost of a
    DISARMED injection check — one cached flag read, the same
    disabled-cost rule the tracer follows. Pass
    CEPH_TPU_FAULT_BASELINE_MBPS to assert reread parity (<2%) against a
    recorded pre-fault-layer number. Guarded (--fault-overhead /
    CEPH_TPU_BENCH_FAULT=1) and non-fatal."""
    try:
        import io
        import tempfile
        from contextlib import redirect_stderr, redirect_stdout

        from ceph_tpu.common.config import Config
        from ceph_tpu.common.kv import MemDB
        from ceph_tpu.osd.blockstore import BlockStore
        from tools import store_bench

        # the disarmed site check itself, in ns (the read hot path's
        # single `_inj_read_armed` flag)
        store = BlockStore(MemDB(), config=Config())
        n = 200_000
        sink = 0
        t0 = time.perf_counter()
        for _ in range(n):
            if store._inj_read_armed:
                sink += 1
        site_ns = (time.perf_counter() - t0) / n * 1e9
        store.umount()

        with tempfile.TemporaryDirectory(prefix="bench_fault_") as d:
            out = os.path.join(d, "store.json")
            with redirect_stdout(io.StringIO()), \
                    redirect_stderr(io.StringIO()):
                store_bench.main([
                    "--backend", "blockstore",
                    "--sizes", "65536",
                    "--small-sizes", "1024",
                    "--bytes-per-case", str(4 << 20),
                    "--dir", d,
                    "--out", out,
                ])
            with open(out) as f:
                results = json.load(f)["results"]
        rw = next(r for r in results if r["workload"] == "rw")
        line = {
            "metric": "fault_injection_overhead",
            "value": round(site_ns, 1),
            "unit": "ns/site",
            "write_mbps": round(rw["write_mbps"], 1),
            "read_mbps": round(rw["read_mbps"], 1),
            "reread_mbps": round(rw["reread_mbps"], 1),
        }
        baseline = os.environ.get("CEPH_TPU_FAULT_BASELINE_MBPS")
        if baseline is not None:
            drift = (
                abs(rw["reread_mbps"] - float(baseline)) / float(baseline)
            )
            line["baseline_mbps"] = float(baseline)
            line["within_noise"] = bool(drift < 0.02)
        print(json.dumps(line))
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def _trace_overhead_line() -> None:
    """Optional JSON line: daemon_bench throughput with the tracer
    disabled vs enabled-at-rate-1. The disabled figure is the pre-PR
    parity claim — a disabled span site is one cached flag check, so
    disabled throughput must sit within noise (<2%) of the pre-PR
    number (pass it via CEPH_TPU_TRACE_BASELINE_GBPS when the driver
    has one recorded; the enabled/disabled delta is always reported).
    Guarded (--trace-overhead / CEPH_TPU_BENCH_TRACE=1) and non-fatal."""
    try:
        import subprocess

        from ceph_tpu.common.config import Config
        from ceph_tpu.common.tracer import Tracer

        # the disabled span-site cost itself, in ns/check
        tracer = Tracer("bench", config=Config())
        n = 200_000
        t0 = time.perf_counter()
        for _ in range(n):
            tracer.child("site")
        site_ns = (time.perf_counter() - t0) / n * 1e9

        def run_bench(tracer_on: bool) -> float:
            env = dict(os.environ)
            env["CEPH_TPU_TRACER_ENABLED"] = (
                "true" if tracer_on else "false"
            )
            env["CEPH_TPU_TRACER_SAMPLE_RATE"] = "1.0"
            out = subprocess.run(
                [sys.executable, "tools/daemon_bench.py", "--cpu",
                 "--osds", "6", "--size", "65536", "--objects", "48",
                 "--concurrency", "12"],
                capture_output=True, timeout=600, env=env, check=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            return float(json.loads(out.stdout)["write_gbps"])

        disabled = run_bench(False)
        enabled = run_bench(True)
        baseline = os.environ.get("CEPH_TPU_TRACE_BASELINE_GBPS")
        line = {
            "metric": "tracer_overhead",
            "value": round(100 * (disabled - enabled) / disabled, 2),
            "unit": "%",
            "disabled_gbps": round(disabled, 3),
            "enabled_gbps": round(enabled, 3),
            "disabled_site_ns": round(site_ns, 1),
        }
        if baseline is not None:
            drift = abs(disabled - float(baseline)) / float(baseline)
            line["baseline_gbps"] = float(baseline)
            line["within_noise"] = bool(drift < 0.02)
        print(json.dumps(line))
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def _trace_tail_line() -> None:
    """Optional JSON line: daemon_bench throughput with the tracer
    DISABLED vs enabled at sample_rate=0 — the always-on flight
    recorder's hot-path cost. At rate 0 every op still records spans
    into the bounded flight ring (tail keep/drop at completion) but
    exports nothing and, with no slow/error ops in a clean bench,
    promotes nothing; the enabled/disabled delta is therefore exactly
    the flight-ring overhead the tail-sampling design budgets at <2%.
    Guarded (--trace-tail / CEPH_TPU_BENCH_TRACE_TAIL=1), non-fatal."""
    try:
        import subprocess

        def run_bench(tracer_on: bool) -> float:
            env = dict(os.environ)
            env["CEPH_TPU_TRACER_ENABLED"] = (
                "true" if tracer_on else "false"
            )
            env["CEPH_TPU_TRACER_SAMPLE_RATE"] = "0.0"
            out = subprocess.run(
                [sys.executable, "tools/daemon_bench.py", "--cpu",
                 "--osds", "6", "--size", "65536", "--objects", "48",
                 "--concurrency", "12"],
                capture_output=True, timeout=600, env=env, check=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            return float(json.loads(out.stdout)["write_gbps"])

        disabled = run_bench(False)
        flight = run_bench(True)
        overhead = 100 * (disabled - flight) / disabled
        print(json.dumps({
            "metric": "flight_ring_overhead",
            "value": round(overhead, 2),
            "unit": "%",
            "disabled_gbps": round(disabled, 3),
            "flight_gbps": round(flight, 3),
            "within_budget": bool(overhead < 2.0),
        }))
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def _wire_line() -> None:
    """Optional JSON line: daemon-path throughput with the wire fast
    path on (binary MESSAGE_SEG envelopes + corked BATCH frames +
    sub-op coalescing, the shipped defaults) vs the fallback knobs
    (ms_envelope_format=json, ms_cork_max_frames=1, ms_subop_batch
    off). The fallback run still carries this PR's knob-independent
    work (shared watchdog, event-driven map refresh, single-buffer
    frame checksums, region-op EC fallback, parallel shard fetch), so
    the knob delta understates the PR; the pre-PR daemon-path figure
    for the same workload is recorded in README.md's perf table and
    can ride along via CEPH_TPU_WIRE_BASELINE_GBPS for the full
    before/after ratio. frames_per_op counts coalesced sub-op frames
    per EC write — the fan-out claim is frames_per_op < k+m. Guarded
    (--wire / CEPH_TPU_BENCH_WIRE=1) and non-fatal."""
    try:
        import subprocess

        def run_bench(fast: bool) -> dict:
            argv = [sys.executable, "tools/daemon_bench.py", "--cpu",
                    "--osds", "6", "--k", "4", "--m", "2",
                    "--size", "262144", "--objects", "96",
                    "--concurrency", "24"]
            if not fast:
                argv += ["--envelope-format", "json",
                         "--cork-max", "1", "--subop-batch", "off"]
            out = subprocess.run(
                argv, capture_output=True, timeout=600, check=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            return json.loads(out.stdout)

        fast = run_bench(True)
        slow = run_bench(False)
        line = {
            "metric": "wire_fastpath_write_throughput",
            "value": round(fast["write_gbps"], 4),
            "unit": "GB/s",
            "read_gbps": round(fast["read_gbps"], 4),
            "fallback_write_gbps": round(slow["write_gbps"], 4),
            "fallback_read_gbps": round(slow["read_gbps"], 4),
            "knob_write_speedup": round(
                fast["write_gbps"] / slow["write_gbps"], 3),
            "knob_read_speedup": round(
                fast["read_gbps"] / slow["read_gbps"], 3),
            "frames_per_op": round(fast["frames_per_op"], 2),
            "fallback_frames_per_op": round(slow["frames_per_op"], 2),
            "frames_per_op_lt_k_plus_m": bool(
                fast["frames_per_op"] < 4 + 2),
            "bytes_coalesced": fast["bytes_coalesced"],
        }
        baseline = os.environ.get("CEPH_TPU_WIRE_BASELINE_GBPS")
        if baseline is not None:
            line["pre_pr_write_gbps"] = float(baseline)
            line["vs_pre_pr"] = round(
                fast["write_gbps"] / float(baseline), 3)
        print(json.dumps(line))
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def _wire_local_line() -> None:
    """Optional JSON line: PosixStack (TCP loopback) vs LocalStack
    (uds + shared-memory ring, the co-located default) on the same
    daemon-path workload. Runs tools/daemon_bench.py twice — once with
    --stack tcp, once with --stack auto — and reports the read/write
    ratio plus how many payload bytes the receive side took as
    zero-copy ring loans. Larger objects than _wire_line's run: the
    EC-encode share shrinks and the transport delta dominates.
    Guarded (--wire-local / CEPH_TPU_BENCH_WIRE=1) and non-fatal."""
    try:
        import subprocess

        def run_bench(stack: str) -> dict:
            argv = [sys.executable, "tools/daemon_bench.py", "--cpu",
                    "--osds", "3", "--k", "2", "--m", "1",
                    "--size", "2097152", "--objects", "48",
                    "--concurrency", "24", "--stack", stack]
            out = subprocess.run(
                argv, capture_output=True, timeout=600, check=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            return json.loads(out.stdout)

        local = run_bench("auto")
        tcp = run_bench("tcp")
        line = {
            "metric": "wire_local_stack_read_throughput",
            "value": round(local["read_gbps"], 4),
            "unit": "GB/s",
            "write_gbps": round(local["write_gbps"], 4),
            "stack": local["stack"],
            "tcp_read_gbps": round(tcp["read_gbps"], 4),
            "tcp_write_gbps": round(tcp["write_gbps"], 4),
            "read_speedup": round(
                local["read_gbps"] / tcp["read_gbps"], 3),
            "write_speedup": round(
                local["write_gbps"] / tcp["write_gbps"], 3),
            "frames_per_op": round(local["frames_per_op"], 2),
            "bytes_zero_copy": local["bytes_zero_copy"],
        }
        print(json.dumps(line))
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def _read_scaling_line() -> None:
    """Optional JSON line: the scale-out read A/B. Three multiprocess
    daemon_bench runs — real OS processes per daemon and per client, so
    a hot primary is a genuine CPU bottleneck — over a hot object set:

      * rep pool, rados_read_policy=primary — every read of a hot
        object lands on its one primary process;
      * rep pool, policy=balance — the same reads spread across all
        clean acting members (the tentpole claim: aggregate read GB/s
        scales with replicas, expected >= 1.5x on a 3-replica pool);
      * EC pool, policy=balance — full-object reads take the
        direct-shard path (k parallel ranged shard reads, no primary
        gather/decode) vs the same pool at policy=primary.

    read_distribution (per-OSD op_r / read_balanced / read_shard_direct
    deltas for the read leg) rides along so the spread itself is
    visible, not just the ratio. The speedup needs real cores to scale
    into: on a single-core host the processes timeshare and the ratio
    degenerates toward 1x even though the spread happens — ncores rides
    in the line so the reader can tell. Guarded (--read-scaling /
    CEPH_TPU_BENCH_READ=1) and non-fatal."""
    try:
        import subprocess

        def run_bench(pool: str, policy: str) -> dict:
            argv = [sys.executable, "tools/daemon_bench.py",
                    "--multiprocess", "--osds", "6", "--clients", "4",
                    "--pool", pool, "--k", "2", "--m", "2",
                    "--size", "262144", "--objects", "64",
                    "--concurrency", "24", "--hot-set", "3",
                    "--read-policy", policy]
            out = subprocess.run(
                argv, capture_output=True, timeout=900, check=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            return json.loads(out.stdout)

        rep_primary = run_bench("rep", "primary")
        rep_balance = run_bench("rep", "balance")
        ec_primary = run_bench("ec", "primary")
        ec_direct = run_bench("ec", "balance")
        line = {
            "metric": "balanced_read_throughput",
            "value": round(rep_balance["read_gbps"], 4),
            "unit": "GB/s",
            "primary_read_gbps": round(rep_primary["read_gbps"], 4),
            "balance_speedup": round(
                rep_balance["read_gbps"] / rep_primary["read_gbps"], 3),
            "ec_direct_read_gbps": round(ec_direct["read_gbps"], 4),
            "ec_primary_read_gbps": round(ec_primary["read_gbps"], 4),
            "ec_direct_speedup": round(
                ec_direct["read_gbps"] / ec_primary["read_gbps"], 3),
            "clients": rep_balance["clients"],
            "ncores": rep_balance["ncores"],
            "read_distribution": rep_balance["read_distribution"],
            "ec_read_distribution": ec_direct["read_distribution"],
        }
        print(json.dumps(line))
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def _ckpt_line() -> None:
    """Optional JSON line: checkpoint save/restore GB/s through the full
    stack (CkptStore -> RADOS client -> OSD daemons -> EC encode), via
    tools/ckpt_tool.py's in-process bench — now including the async
    fast path: blocking time (train-visible stall of save_async) vs the
    persist wall time, and the incremental-dedup ratio of an unchanged-
    majority second save. Guarded (--ckpt / CEPH_TPU_BENCH_CKPT=1) and
    non-fatal."""
    try:
        import subprocess

        out = subprocess.run(
            [sys.executable, "tools/ckpt_tool.py", "bench",
             "--mb", os.environ.get("CEPH_TPU_BENCH_CKPT_MB", "16"),
             "--arrays", "8", "--pool-kind", "ec",
             "--async", "--incremental"],
            capture_output=True, timeout=600, check=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        r = json.loads(out.stdout.strip().splitlines()[-1])
        print(json.dumps({
            "metric": "ckpt_save_throughput",
            "value": r["save_gbps"],
            "unit": "GB/s",
            "restore_gbps": r["restore_gbps"],
            "bytes": r["bytes"],
            "chunks": r["chunks"],
            "pool": r["pool"],
            # async fast path: train-visible stall vs persist wall time
            "block_s": r["block_s"],
            "wall_s": r["wall_s"],
            "sync_save_s": r["second_save_s"],
            "blocking_speedup": r["blocking_speedup"],
            # incremental dedup on the unchanged-majority second save
            "dedup_ratio": r["dedup_ratio"],
            "chunks_reused": r["chunks_reused"],
        }))
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def _data_line() -> None:
    """Optional JSON line: dataset ingest + sustained shuffled-read
    throughput through the full stack (DataStore -> prefetching
    iterator -> ranged striper reads -> OSD EC decode), via
    tools/data_tool.py's in-process bench. The line carries both read
    modes — block-granular readahead pipeline vs the
    data_prefetch_batches=0 fetch-on-demand baseline — so the prefetch
    speedup is self-contained. Guarded (--data / CEPH_TPU_BENCH_DATA=1)
    and non-fatal."""
    try:
        import subprocess

        out = subprocess.run(
            [sys.executable, "tools/data_tool.py", "bench",
             "--mb", os.environ.get("CEPH_TPU_BENCH_DATA_MB", "16"),
             "--record-kb", "64", "--shards", "8",
             "--pool-kind", "ec"],
            capture_output=True, timeout=600, check=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        r = json.loads(out.stdout.strip().splitlines()[-1])
        print(json.dumps({
            "metric": "data_read_throughput",
            "value": r["read_gbps"],
            "unit": "GB/s",
            "ingest_gbps": r["ingest_gbps"],
            "records_per_s": r["records_per_s"],
            "bytes": r["bytes"],
            "records": r["records"],
            "shards": r["shards"],
            "pool": r["pool"],
            # prefetch pipeline vs fetch-on-demand baseline
            "noprefetch_gbps": r["read_noprefetch_gbps"],
            "prefetch_speedup": r["prefetch_speedup"],
            "prefetch_hit_rate": r["prefetch_hit_rate"],
        }))
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def _fleet_line() -> None:
    """Optional JSON line: coordination-subsystem costs through the
    full stack — barrier round-trip latency across a multi-host fleet
    (arrive locks + watch/notify wakeup on the roster's primary OSD)
    and the per-rank sharded restore aggregate vs one host restoring
    the whole tree, via tools/fleet_tool.py's in-process bench.
    Guarded (--fleet / CEPH_TPU_BENCH_FLEET=1) and non-fatal."""
    try:
        import subprocess

        out = subprocess.run(
            [sys.executable, "tools/fleet_tool.py", "bench",
             "--hosts", os.environ.get("CEPH_TPU_BENCH_FLEET_HOSTS", "4"),
             "--rounds", "20",
             "--mb", os.environ.get("CEPH_TPU_BENCH_FLEET_MB", "16")],
            capture_output=True, timeout=600, check=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        r = json.loads(out.stdout.strip().splitlines()[-1])
        print(json.dumps({
            "metric": "fleet_barrier_latency",
            "value": r["barrier_p50_ms"],
            "unit": "ms",
            "p99_ms": r["barrier_p99_ms"],
            "hosts": r["hosts"],
            "rounds": r["rounds"],
            # multi-host restore: every rank fetches only its slab
            "bytes": r["bytes"],
            "restore_whole_gbps": r["restore_whole_gbps"],
            "restore_sharded_gbps": r["restore_sharded_gbps"],
            "sharded_speedup": r["sharded_speedup"],
        }))
        # mesh-native fleet-parallel save: N real writer processes,
        # each putting only its slab-aligned shards, vs the N-host
        # single-committer baseline (remote shards gathered through
        # the store, one host serializing + putting every byte)
        out = subprocess.run(
            [sys.executable, "tools/fleet_tool.py", "bench",
             "--parallel-save",
             "--hosts", os.environ.get(
                 "CEPH_TPU_BENCH_PSAVE_HOSTS", "3"),
             "--mb", os.environ.get("CEPH_TPU_BENCH_PSAVE_MB", "48")],
            capture_output=True, timeout=600, check=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        r = json.loads(out.stdout.strip().splitlines()[-1])
        print(json.dumps({
            "metric": "fleet_parallel_save",
            "value": r["parallel_save_speedup"],
            "unit": "x",
            "parallel_save_speedup": r["parallel_save_speedup"],
            "peak_host_bytes_frac": r["peak_host_bytes_frac"],
            "hosts": r["hosts"],
            "bytes": r["bytes"],
            "single_save_s": r["single_save_s"],
            "parallel_save_s": r["parallel_save_s"],
        }))
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def _balance_line() -> None:
    """Optional JSON line: placement balancing at reference scale. Runs
    a 1024-OSD psim scenario whose pools carry ~1M PG instances
    (rep 262144x3 + EC 32768x6) through one churn epoch and the batched
    calc_pg_upmaps, reporting PGs mapped per second as the headline
    value plus balancer convergence (spread before/after, moves,
    rounds, launches). A batched-vs-scalar speedup rides along, timed
    steady-state (map launches pre-compiled — the mgr re-balances the
    same map shape every tick) with an identical move budget, at a
    scale where the reference baseline's per-PG host CRUSH walks
    dominate. Guarded (--balance / CEPH_TPU_BENCH_BALANCE=1) and
    non-fatal."""
    try:
        from ceph_tpu.crush import balance
        from ceph_tpu.sim import build_cluster, run_scenario

        n_osd = int(os.environ.get("CEPH_TPU_BENCH_BALANCE_OSDS", "1024"))
        report = run_scenario(
            n_osd=n_osd,
            rep_pg_num=n_osd * 256,  # x3 replicas
            ec_pg_num=n_osd * 32,  # x6 shards -> ~1M instances at 1024
            seed=1, epochs=1, max_changes=2048, measure=True,
        )
        bal, timing = report["balance"], report["timing"]

        # batched-vs-scalar: same map shape, same budget, wall time
        # each. The batched map is warmed once (jit compile is a
        # per-shape one-time cost, amortized across balancer ticks);
        # the scalar side's O(PGs) python walks ARE its steady-state
        # cost, so it is timed cold.
        h_osd = min(n_osd, 512)
        budget = 64
        m = build_cluster(h_osd, rep_pg_num=h_osd * 32, ec_pg_num=h_osd * 4)
        for pid in m.pools:
            m.pool_mappings(pid)
        t0 = time.perf_counter()
        r = balance.calc_pg_upmaps(m, max_changes=budget)
        batched_s = time.perf_counter() - t0
        m = build_cluster(h_osd, rep_pg_num=h_osd * 32, ec_pg_num=h_osd * 4)
        t0 = time.perf_counter()
        scalar_changes = balance.calc_pg_upmaps_scalar(
            m, max_changes=budget)
        scalar_s = time.perf_counter() - t0

        print(json.dumps({
            "metric": "balancer_pgs_mapped_throughput",
            "value": round(timing["pgs_mapped_per_s"], 1),
            "unit": "PGs/s",
            "osds": report["osds"],
            "pg_instances": report["pg_instances"],
            "spread_before": round(bal["spread_before"], 2),
            "spread_after": round(bal["spread_after"], 2),
            "converged": bal["converged"],
            "moves": bal["changes"],
            "rounds": bal["rounds"],
            "launches": bal["launches"],
            "balance_seconds": round(timing["balance_seconds"], 3),
            "total_seconds": round(timing["total_seconds"], 3),
            # warm-map head-to-head at an equal move budget
            "speedup_vs_scalar": round(scalar_s / batched_s, 2),
            "speedup_batched_s": round(batched_s, 3),
            "speedup_scalar_s": round(scalar_s, 3),
            "speedup_moves": [r.changes, scalar_changes],
        }))
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def _telemetry_line() -> None:
    """Optional JSON line: the telemetry tax. Two daemon_bench runs —
    without and with an active mgr (every OSD pushing perf-counter
    delta reports on mgr_report_interval) — report the write-throughput
    overhead of always-on telemetry (target < 2%), plus the scrape-cost
    A/B the push store exists for: rendering /metrics from the mgr's
    time-series store vs the old per-scrape `perf dump` pull fan-out
    at the same 6-OSD fleet. Guarded (--telemetry /
    CEPH_TPU_BENCH_TELEMETRY=1) and non-fatal."""
    try:
        import subprocess

        def run_bench(with_mgr: bool) -> dict:
            argv = [sys.executable, "tools/daemon_bench.py", "--cpu",
                    "--osds", "6", "--size", "65536", "--objects", "48",
                    "--concurrency", "12"]
            if with_mgr:
                argv.append("--mgr")
            out = subprocess.run(
                argv, capture_output=True, timeout=600, check=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            return json.loads(out.stdout)

        quiet = run_bench(False)
        telem = run_bench(True)
        mgr = telem["mgr"]
        overhead = 100 * (
            quiet["write_gbps"] - telem["write_gbps"]
        ) / quiet["write_gbps"]
        print(json.dumps({
            "metric": "telemetry_overhead",
            "value": round(overhead, 2),
            "unit": "%",
            "quiet_write_gbps": round(quiet["write_gbps"], 4),
            "telemetry_write_gbps": round(telem["write_gbps"], 4),
            "within_target": bool(overhead < 2.0),
            "daemons_reporting": mgr["daemons_reporting"],
            # the scrape A/B: push store vs per-scrape pull fan-out
            "scrape_push_ms": mgr["scrape_push_ms"],
            "scrape_pull_ms": mgr["scrape_pull_ms"],
            "scrape_speedup": round(
                mgr["scrape_pull_ms"] / max(1e-9, mgr["scrape_push_ms"]),
                2),
            "push_series": mgr["push_series"],
            "pull_series": mgr["pull_series"],
        }))
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def _lint_line() -> None:
    """Optional JSON line: cephlint summary counts (files, checks run,
    findings, suppressions, baseline size) so the BENCH trajectory also
    tracks static-analysis debt shrinking toward zero. Guarded (--lint /
    CEPH_TPU_BENCH_LINT=1) and non-fatal."""
    try:
        from ceph_tpu.lint import load_baseline, run_lint

        root = os.path.dirname(os.path.abspath(__file__))
        baseline = load_baseline(
            os.path.join(root, "tools", "lint_baseline.json"))
        t0 = time.perf_counter()
        rep = run_lint(["ceph_tpu", "tests"], root=root, baseline=baseline)
        s = rep.summary()
        print(json.dumps({
            "metric": "cephlint_findings",
            "value": s["findings"],
            "unit": "findings",
            "new": s["new"],
            "baselined": s["baselined"],
            "suppressed": s["suppressed"],
            "files": s["files"],
            "checks_run": s["checks_run"],
            "seconds": round(time.perf_counter() - t0, 2),
        }))
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def _recovery_line() -> None:
    """Optional JSON line: the batched recovery engine A/B — degraded
    objects healed/s with sub-op-frame batching vs the one-object-at-a-
    time baseline (osd_recovery_batch_max=1), plus client p99 during
    the recovery storm under the mclock recovery class. Guarded
    (--recovery / CEPH_TPU_BENCH_RECOVERY=1) and non-fatal."""
    try:
        import subprocess

        out = subprocess.run(
            [sys.executable, "tools/daemon_bench.py", "--recovery",
             "--cpu",
             "--recovery-objects",
             os.environ.get("CEPH_TPU_BENCH_RECOVERY_OBJECTS", "400")],
            capture_output=True, text=True, timeout=600, check=True,
        )
        r = json.loads(out.stdout.strip().splitlines()[-1])
        print(json.dumps({
            "metric": "recovery_heal_rate",
            "value": r["batched"]["healed_obj_per_s"],
            "unit": "objects/s",
            "vs_serial": r["speedup"],
            "serial_obj_per_s": r["serial"]["healed_obj_per_s"],
            "batch_max": r["batched"]["batch_max"],
            "client_p99_s": r["batched"]["client_p99_s"],
            "client_p99_s_serial": r["serial"]["client_p99_s"],
        }))
    except Exception:  # noqa: BLE001 - strictly best-effort
        pass


def main() -> None:
    import jax

    from ceph_tpu.ec.registry import factory

    ec = factory("isa", {"k": str(K), "m": str(M), "technique": "cauchy"})
    rng = np.random.default_rng(0)
    data = rng.integers(0, 2**31, size=(K, N4), dtype=np.int32)
    words = jax.device_put(data)
    nbytes = K * N4 * 4

    enc_s = measure_seconds(ec.encode_words, words)
    enc_gbps = nbytes / 1e9 / enc_s

    # decode: data chunks 0..2 lost; survivors are logical chunks 3..10
    present = list(range(3, K + M))

    def dec(d):
        return ec.decode_words(present, [0, 1, 2], d)

    dec_s = measure_seconds(dec, words)  # (8, N4) survivors -> 3 rebuilt rows
    dec_gbps = nbytes / 1e9 / dec_s

    print(
        json.dumps(
            {
                "metric": "rs(8,3)_encode_throughput",
                "value": round(enc_gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(enc_gbps / BASELINE_GBPS, 3),
                "decode_gbps": round(dec_gbps, 3),
                "decode_vs_baseline": round(dec_gbps / BASELINE_GBPS, 3),
                "cpu_baseline_gbps": BASELINE_GBPS,
            }
        )
    )
    if "--store-bench" in sys.argv[1:] or os.environ.get(
        "CEPH_TPU_BENCH_STORE"
    ):
        _store_bench_line()
    if "--trace-overhead" in sys.argv[1:] or os.environ.get(
        "CEPH_TPU_BENCH_TRACE"
    ):
        _trace_overhead_line()
    if "--trace-tail" in sys.argv[1:] or os.environ.get(
        "CEPH_TPU_BENCH_TRACE_TAIL"
    ):
        _trace_tail_line()
    if "--fault-overhead" in sys.argv[1:] or os.environ.get(
        "CEPH_TPU_BENCH_FAULT"
    ):
        _fault_overhead_line()
    if "--wire" in sys.argv[1:] or os.environ.get("CEPH_TPU_BENCH_WIRE"):
        _wire_line()
    if "--wire-local" in sys.argv[1:] or os.environ.get(
        "CEPH_TPU_BENCH_WIRE"
    ):
        _wire_local_line()
    if "--read-scaling" in sys.argv[1:] or os.environ.get(
        "CEPH_TPU_BENCH_READ"
    ):
        _read_scaling_line()
    if "--ckpt" in sys.argv[1:] or os.environ.get("CEPH_TPU_BENCH_CKPT"):
        _ckpt_line()
    if "--data" in sys.argv[1:] or os.environ.get("CEPH_TPU_BENCH_DATA"):
        _data_line()
    if "--fleet" in sys.argv[1:] or os.environ.get(
        "CEPH_TPU_BENCH_FLEET"
    ):
        _fleet_line()
    if "--balance" in sys.argv[1:] or os.environ.get(
        "CEPH_TPU_BENCH_BALANCE"
    ):
        _balance_line()
    if "--telemetry" in sys.argv[1:] or os.environ.get(
        "CEPH_TPU_BENCH_TELEMETRY"
    ):
        _telemetry_line()
    if "--recovery" in sys.argv[1:] or os.environ.get(
        "CEPH_TPU_BENCH_RECOVERY"
    ):
        _recovery_line()
    if "--lint" in sys.argv[1:] or os.environ.get("CEPH_TPU_BENCH_LINT"):
        _lint_line()


if __name__ == "__main__":
    main()
