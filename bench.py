"""Driver benchmark: RS(8,3) erasure-code encode throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

This is the north-star configuration from BASELINE.md — the reference measures
the same workload with `ceph_erasure_code_benchmark -p isa -P k=8 -P m=3`
(/root/reference/src/erasure-code/isa/README), whose output is
`elapsed_seconds \t KiB_processed` (ceph_erasure_code_benchmark.cc:179).
Here the workload is stripes from many concurrent 4 KiB objects packed into one
(batch, k, chunk) uint8 tensor in HBM, encoded by the bit-plane MXU kernel.

Timing methodology: the device is reached through a tunnel where a single
device->host fetch costs ~100 ms and block_until_ready does not actually block,
so per-call wall timing is useless. Instead the encode is iterated inside one
jitted lax.fori_loop (with a data dependency between iterations so XLA cannot
hoist it) at two different trip counts; the time delta divided by the trip
delta gives the per-encode device time with the constant dispatch+fetch
overhead cancelled.

vs_baseline compares against ISA-L-class AVX512 single-core RS(8,3) encode
throughput (~5 GB/s), the reference plugin this backend replaces; BASELINE.md
records the assumption until a measured CPU baseline lands in-repo.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_GBPS = 5.0  # ISA-L AVX512 RS(8,3) single-core class (see module docstring)


def measure_encode_seconds(ec, data, n_lo: int = 5, n_hi: int = 25) -> float:
    """Per-encode seconds via the two-trip-count delta method."""
    import jax
    import jax.numpy as jnp

    m = ec.m

    def make_chain(n):
        @jax.jit
        def chain(x):
            def body(_, d):
                parity = ec.encode_array(d)
                # feed parity back into the data so iterations are dependent
                return jnp.concatenate([d[:, :m] ^ parity, d[:, m:]], axis=1)

            return jax.lax.fori_loop(0, n, body, x)

        return chain

    def run(chain):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = chain(data)
            np.asarray(out[0, 0, :1])  # force completion through the tunnel
            best = min(best, time.perf_counter() - t0)
        return best

    lo, hi = make_chain(n_lo), make_chain(n_hi)
    run(lo), run(hi)  # compile both
    return max(1e-9, (run(hi) - run(lo)) / (n_hi - n_lo))


def main() -> None:
    import jax

    from ceph_tpu.ec.registry import factory

    k, m, chunk = 8, 3, 512  # 4 KiB objects -> 512 B chunks (isa chunk rule)
    batch = 1 << 16  # 64 Ki stripes = 256 MiB of data per launch
    ec = factory("isa", {"k": str(k), "m": str(m), "technique": "cauchy"})

    rng = np.random.default_rng(0)
    data = jax.device_put(rng.integers(0, 256, size=(batch, k, chunk), dtype=np.uint8))

    seconds = measure_encode_seconds(ec, data)
    value = data.size / 1e9 / seconds
    print(
        json.dumps(
            {
                "metric": "rs(8,3)_encode_throughput",
                "value": round(value, 3),
                "unit": "GB/s",
                "vs_baseline": round(value / BASELINE_GBPS, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
