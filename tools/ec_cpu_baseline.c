/* Measured CPU erasure-code baseline: bit-plane XOR-schedule encode.
 *
 * This is the same algorithm class as the reference's jerasure bitmatrix
 * techniques (cauchy_good + jerasure_schedule_encode, vendored jerasure; see
 * /root/reference/src/erasure-code/jerasure/ErasureCodeJerasure.cc:305
 * prepare_schedule): the GF(2^8) coding matrix is expanded to an (8m x 8k)
 * {0,1} bit-matrix and each output bit-plane (a `packetsize`-byte packet) is
 * the XOR of the selected input planes, processed in 64-bit words. It is the
 * strongest simple single-thread CPU formulation (pure cache-resident XOR
 * streaming), standing in for the unbuilt ISA-L submodule.
 *
 * stdin protocol:
 *   k m packetsize iterations chunk_bytes
 *   8m*8k matrix entries (0/1, row-major)
 * Random data is generated internally. Output: elapsed seconds, one float.
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

int main(void) {
    int k, m, psize, iters;
    long chunk;
    if (scanf("%d %d %d %d %ld", &k, &m, &psize, &iters, &chunk) != 5)
        return 1;
    int rows = 8 * m, cols = 8 * k;
    unsigned char *bits = malloc((size_t)rows * cols);
    for (int i = 0; i < rows * cols; i++) {
        int v;
        if (scanf("%d", &v) != 1) return 1;
        bits[i] = (unsigned char)v;
    }
    if (chunk % (8 * psize)) {
        fprintf(stderr, "chunk must be a multiple of 8*packetsize\n");
        return 1;
    }
    size_t words_per_packet = (size_t)psize / 8;
    size_t packets = (size_t)chunk / psize / 8; /* packet groups per chunk */
    uint64_t **data = malloc(k * sizeof(*data));
    uint64_t **parity = malloc(m * sizeof(*parity));
    srand(1234);
    for (int j = 0; j < k; j++) {
        data[j] = malloc(chunk);
        unsigned char *p = (unsigned char *)data[j];
        for (long i = 0; i < chunk; i++) p[i] = (unsigned char)rand();
    }
    for (int i = 0; i < m; i++) parity[i] = malloc(chunk);

    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    for (int it = 0; it < iters; it++) {
        /* layout: chunk j = 8 interleaved planes of `packets` packets:
         * plane b of packet g starts at word (g*8 + b) * words_per_packet */
        for (size_t g = 0; g < packets; g++) {
            for (int oi = 0; oi < rows; oi++) {
                uint64_t *dst =
                    parity[oi / 8] + (g * 8 + (size_t)(oi % 8)) * words_per_packet;
                int first = 1;
                const unsigned char *mrow = bits + (size_t)oi * cols;
                for (int ij = 0; ij < cols; ij++) {
                    if (!mrow[ij]) continue;
                    const uint64_t *src =
                        data[ij / 8] + (g * 8 + (size_t)(ij % 8)) * words_per_packet;
                    if (first) {
                        memcpy(dst, src, words_per_packet * 8);
                        first = 0;
                    } else {
                        for (size_t w = 0; w < words_per_packet; w++)
                            dst[w] ^= src[w];
                    }
                }
                if (first) memset(dst, 0, words_per_packet * 8);
            }
        }
    }
    clock_gettime(CLOCK_MONOTONIC, &t1);
    double el = (t1.tv_sec - t0.tv_sec) + 1e-9 * (t1.tv_nsec - t0.tv_nsec);
    /* defeat dead-code elimination */
    uint64_t sink = 0;
    for (int i = 0; i < m; i++) sink ^= parity[i][0];
    fprintf(stderr, "sink %llu\n", (unsigned long long)sink);
    printf("%.6f\n", el);
    return 0;
}
