"""crush_bench — the BASELINE CRUSH benchmark, reproducibly.

Measures BASELINE.md config 5 ("crushtool --test: straw2 mapping of 1M PGs
over a 10k-OSD map") on both implementations:

  * the reference C mapper, single thread, via the test oracle shim's
    `benchrun` command (only when /root/reference and gcc are available);
  * this framework's vectorized JAX mapper on the default device.

Prints one JSON line per measurement, plus the ratio. The JAX output is
validated bit-exact against the C oracle on a prefix before timing.

    python tools/crush_bench.py [--pgs 1000000] [--osds 10000] [--replicas 3]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_map(n_osds: int, osds_per_host: int = 50):
    from ceph_tpu.crush import builder as cb
    from ceph_tpu.crush.types import BucketAlg, CrushMap, Tunables

    cmap = CrushMap(tunables=Tunables.jewel())
    host_ids, host_w = [], []
    osd = 0
    n_hosts = n_osds // osds_per_host
    for h in range(n_hosts):
        items = list(range(osd, osd + osds_per_host))
        osd += osds_per_host
        b = cb.make_bucket(
            cmap, -(h + 2), BucketAlg.STRAW2, 1, items, [0x10000] * osds_per_host
        )
        host_ids.append(b.id)
        host_w.append(b.weight)
    cb.make_bucket(cmap, -1, BucketAlg.STRAW2, 10, host_ids, host_w)
    cb.make_simple_rule(cmap, 0, -1, 1, "firstn", 0)
    return cmap


def bench_c(cmap, n_pgs: int, replicas: int, weight) -> float | None:
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests"))
    try:
        from crush_oracle import build_shim, map_to_protocol
    except ImportError:
        return None
    shim = build_shim()
    if shim is None:
        return None
    wtxt = " ".join(str(w) for w in weight)
    text = (
        map_to_protocol(cmap)
        + f"\nbenchrun 0 0 {n_pgs} {replicas} {len(weight)} {wtxt}\n"
    )
    t0 = time.perf_counter()
    proc = subprocess.run(
        [shim], input=text, capture_output=True, text=True, check=True
    )
    wall = time.perf_counter() - t0
    # prefer the shim's self-timed mapping loop (excludes spawn + map parse);
    # an elapsed that rounds to 0 (e.g. --pgs 0) falls back to wall clock
    for line in proc.stdout.splitlines():
        if line.startswith("elapsed "):
            parsed = float(line.split()[1])
            if parsed > 0:
                return parsed
    return wall


def bench_c_mt(cmap, n_pgs: int, replicas: int, weight,
               threads: int | None = None) -> tuple[float, int] | None:
    """The honest CPU comparator: the reference's thread-pool mapping
    (ParallelPGMapper, src/osd/OSDMapMapping.h:18) — every hardware
    thread running the same crush_do_rule loop over a shard of x."""
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests"))
    try:
        from crush_oracle import build_shim, map_to_protocol
    except ImportError:
        return None
    shim = build_shim()
    if shim is None:
        return None
    threads = threads or (os.cpu_count() or 1)
    wtxt = " ".join(str(w) for w in weight)
    text = (
        map_to_protocol(cmap)
        + f"\nbenchrunmt {threads} 0 0 {n_pgs} {replicas} "
        + f"{len(weight)} {wtxt}\n"
    )
    proc = subprocess.run(
        [shim], input=text, capture_output=True, text=True, check=True
    )
    for line in proc.stdout.splitlines():
        if line.startswith("elapsed "):
            parsed = float(line.split()[1])
            if parsed > 0:
                return parsed, threads
    return None


def validate(cmap, compiled, jax_out, replicas, weight, n_check: int):
    from crush_oracle import build_shim, oracle_do_rule

    from ceph_tpu.crush.types import CRUSH_ITEM_NONE

    if build_shim() is None:
        return None
    want = oracle_do_rule(cmap, 0, range(n_check), weight, replicas)
    want_arr = np.full((n_check, jax_out.shape[1]), -1, dtype=np.int64)
    for i, row in enumerate(want):
        want_arr[i, : len(row)] = row
    got = np.where(jax_out[:n_check] == CRUSH_ITEM_NONE, -1, jax_out[:n_check])
    bad = np.nonzero((got != want_arr).any(axis=1))[0]
    if bad.size:
        x = int(bad[0])
        raise SystemExit(
            f"MISMATCH vs reference C at x={x}: "
            f"got {got[x].tolist()} want {want_arr[x].tolist()}"
        )
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pgs", type=int, default=1_000_000)
    ap.add_argument("--osds", type=int, default=10_000)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--skip-c", action="store_true")
    ap.add_argument("--repeats", type=int, default=3,
                    help="TPU timing repeats (chip is shared; best-of wins)")
    ap.add_argument("--validate", type=int, default=-1,
                    help="PGs to check bit-exact vs the C oracle "
                    "(-1 = all of --pgs)")
    args = ap.parse_args(argv)

    from ceph_tpu.crush import jax_mapper as jm

    cmap = build_map(args.osds)
    weight = [0x10000] * args.osds
    compiled = jm.compile_map(cmap)
    xs = np.arange(args.pgs)

    jm.map_rule(compiled, 0, xs[: jm.DEFAULT_CHUNK], weight, args.replicas)  # warm the compile cache
    jax_s = float("inf")
    for _ in range(max(args.repeats, 1)):
        t0 = time.perf_counter()
        out = jm.map_rule(compiled, 0, xs, weight, args.replicas)
        jax_s = min(jax_s, time.perf_counter() - t0)
    print(json.dumps({
        "metric": "crush_straw2_mappings_per_s_tpu",
        "value": round(args.pgs / jax_s, 1),
        "unit": "mappings/s",
        "pgs": args.pgs, "osds": args.osds,
    }))

    c_s = None if args.skip_c else bench_c(cmap, args.pgs, args.replicas, weight)
    if c_s is not None:
        print(json.dumps({
            "metric": "crush_straw2_mappings_per_s_reference_c",
            "value": round(args.pgs / c_s, 1),
            "unit": "mappings/s",
        }))
        print(json.dumps({"metric": "crush_vs_reference_c",
                          "value": round(c_s / jax_s, 3), "unit": "x"}))
        mt = bench_c_mt(cmap, args.pgs, args.replicas, weight)
        if mt is not None:
            mt_s, threads = mt
            print(json.dumps({
                "metric": "crush_straw2_mappings_per_s_reference_c_mt",
                "value": round(args.pgs / mt_s, 1),
                "unit": "mappings/s", "threads": threads,
            }))
            print(json.dumps({
                "metric": "crush_vs_reference_c_mt",
                "value": round(mt_s / jax_s, 3), "unit": "x",
            }))
        n_check = args.pgs if args.validate < 0 else min(args.validate, args.pgs)
        checked = validate(cmap, compiled, out, args.replicas, weight, n_check)
        if checked:
            print(json.dumps({"metric": "bit_exact_vs_c",
                              "value": n_check, "unit": "mappings"}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
