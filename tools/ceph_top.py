"""ceph top — live, sorted per-daemon / per-pool cluster activity.

The `ceph top`/`rados top` role: ask the ACTIVE mgr's metrics module
(fed by every daemon's push reports, see ceph_tpu/mgr/metrics.py) for
its top document and render it. No daemon is touched by this tool —
the numbers come straight out of the mgr's time-series store.

    python tools/ceph_top.py --mon-host 127.0.0.1:6789 [options]

    --json        emit the raw top document (tests consume this)
    --slo         show SLO rule verdicts instead of the activity table
    --watch N     refresh every N seconds until interrupted
    --sort KEY    daemon sort column: ops (default), write_bps,
                  read_bps, queue_depth, inflight

Columns: ops/s, write/read MB/s, queue depth, in-flight ops (OpTracker),
buffer-cache hit rate, seconds since the daemon's last report. Daemons
silent for more than 3 x mgr_report_interval have aged out server-side.
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class TopClient:
    """Thin client for the mgr's report endpoint: resolves the active
    mgr's address from the MgrMap, then drives the tiny
    mgr_command/mgr_command_reply protocol over the messenger."""

    def __init__(self, monmap, config=None, name: str = "client.top"):
        from ceph_tpu.common.config import Config
        from ceph_tpu.mon.client import MonClient
        from ceph_tpu.msg import Dispatcher, Messenger

        self.config = config if config is not None else Config()

        client = self

        class _ReplyCatcher(Dispatcher):
            async def ms_dispatch(self, conn, msg) -> None:
                from ceph_tpu.msg.frames import payload_of

                if msg.type == "mgr_command_reply":
                    fut = client._waiters.pop(msg.tid, None)
                    if fut is not None and not fut.done():
                        fut.set_result(payload_of(msg))

        self._waiters: dict[int, asyncio.Future] = {}
        self._tids = itertools.count(1)
        self.messenger = Messenger(name, config=self.config)
        self.messenger.dispatcher = _ReplyCatcher()
        # MonClient chains itself in front of the catcher and forwards
        # what it doesn't handle — one messenger serves both protocols
        self.mon = MonClient(
            name, monmap, config=self.config, messenger=self.messenger
        )

    async def fetch(self, cmd: str = "top", timeout: float = 10.0,
                    **params) -> dict:
        from ceph_tpu.msg import Message, Policy

        rep = await self.mon.command("mgr map", timeout=timeout)
        mm = rep.get("mgrmap") or {}
        active = mm.get("active")
        addr = (mm.get("addrs") or {}).get(active)
        if not active or not addr:
            raise RuntimeError(
                "no active mgr with an advertised report endpoint "
                f"(mgrmap: {mm})"
            )
        conn = self.messenger.connect(tuple(addr), Policy.lossy_client())
        tid = next(self._tids)
        fut = asyncio.get_event_loop().create_future()
        self._waiters[tid] = fut
        conn.send_message(
            Message(type="mgr_command", tid=tid,
                    payload={"cmd": cmd, **params})
        )
        try:
            reply = await asyncio.wait_for(fut, timeout)
        finally:
            self._waiters.pop(tid, None)
        if not reply.get("ok"):
            raise RuntimeError(f"mgr refused {cmd!r}: {reply.get('error')}")
        return reply["result"]

    async def close(self) -> None:
        await self.messenger.shutdown()


def _fmt_rate(v: float) -> str:
    return f"{v:9.1f}"


def _fmt_mb(v: float) -> str:
    return f"{v / 1e6:8.2f}"


def render_top(doc: dict, sort: str = "ops") -> str:
    lines = [
        f"window {doc.get('window', 0):.1f}s   "
        f"daemons {len(doc.get('daemons', []))}   "
        f"pools {len(doc.get('pools', []))}",
        f"{'NAME':<12} {'OPS/S':>9} {'WR_MB/S':>8} {'RD_MB/S':>8} "
        f"{'QDEPTH':>6} {'INFLT':>5} {'CACHE%':>6} {'AGE':>5}",
    ]
    rows = sorted(
        doc.get("daemons", []),
        key=lambda r: r.get(sort) or 0,
        reverse=True,
    )
    for r in rows:
        hit = r.get("cache_hit_rate")
        lines.append(
            f"{r['daemon']:<12} {_fmt_rate(r['ops'])} "
            f"{_fmt_mb(r['write_bps'])} {_fmt_mb(r['read_bps'])} "
            f"{r['queue_depth']:>6.0f} {r['inflight']:>5} "
            f"{(hit * 100 if hit is not None else 0):>6.1f} "
            f"{r['age']:>5.1f}"
        )
    if doc.get("pools"):
        lines.append("")
        lines.append(f"{'POOL':<6} {'OPS/S':>9} {'OPS_TOTAL':>10}")
        for p in doc["pools"]:
            lines.append(
                f"{p['pool']:<6} {_fmt_rate(p['ops'])} "
                f"{p['ops_total']:>10}"
            )
    rec = doc.get("recovery") or {}
    if rec.get("degraded_objects"):
        lines.append("")
        lines.append(
            f"RECOVERY: {rec['degraded_objects']} object copies "
            f"degraded, healing at {rec.get('rate', 0):g} obj/s"
        )
        for d in rec.get("detail", []):
            lines.append(f"  {d}")
    if doc.get("slo"):
        lines.append("")
        lines.append("SLO (worst margins first):")
        for r in doc["slo"]:
            state = "ok" if r["ok"] else "VIOLATED"
            lines.append(
                f"  [{state:>8}] {r['rule']}  margin "
                f"{r['margin']:+.3f}  worst {r['daemon']} "
                f"= {r['value']:.6g}"
            )
    if doc.get("traces"):
        lines.append("")
        lines.append(
            "TRACES (tail-promoted, newest first — "
            "`ceph trace show <id>` for the span tree):"
        )
        for t in doc["traces"]:
            lines.append(
                f"  {t['trace_id']}  {t.get('reason', '?'):<10} "
                f"{t.get('duration_ms', 0):>9.1f}ms  "
                f"{t.get('num_spans', 0):>3} spans  "
                f"{','.join(t.get('daemons', []))}"
            )
    return "\n".join(lines)


def render_slo(doc: dict) -> str:
    lines = [
        f"{doc.get('daemons_reporting', 0)} daemons reporting, "
        f"{doc.get('violated', 0)} rule(s) violated",
    ]
    for r in doc.get("rules", []):
        state = "ok" if r["ok"] else "VIOLATED"
        val = "n/a" if r["value"] is None else f"{r['value']:.6g}"
        lines.append(
            f"  [{state:>8}] {r['rule']}  measured {val} "
            f"(threshold {r['op']} {r['threshold']:g})"
        )
    return "\n".join(lines)


async def _amain(args) -> int:
    from ceph_tpu.mon import MonMap

    addrs = []
    for hostport in args.mon_host.split(","):
        host, _, port = hostport.rpartition(":")
        addrs.append((host or "127.0.0.1", int(port)))
    client = TopClient(MonMap(addrs=addrs), name=args.name)
    cmd = "slo" if args.slo else "top"
    try:
        while True:
            doc = await client.fetch(cmd, timeout=args.timeout)
            if args.json:
                print(json.dumps(doc, indent=2, sort_keys=True))
            elif args.slo:
                print(render_slo(doc))
            else:
                if args.watch:
                    print("\x1b[2J\x1b[H", end="")
                print(render_top(doc, sort=args.sort))
            if not args.watch:
                return 0
            await asyncio.sleep(args.watch)
    except KeyboardInterrupt:
        return 0
    finally:
        await client.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ceph_top")
    ap.add_argument("--mon-host", required=True,
                    help="comma-separated mon host:port list")
    ap.add_argument("--name", default="client.top")
    ap.add_argument("--json", action="store_true",
                    help="emit the raw top/slo document as JSON")
    ap.add_argument("--slo", action="store_true",
                    help="show SLO verdicts instead of activity")
    ap.add_argument("--watch", type=float, default=0.0,
                    help="refresh every N seconds")
    ap.add_argument("--sort", default="ops",
                    choices=["ops", "write_bps", "read_bps",
                             "queue_depth", "inflight"])
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    return asyncio.run(_amain(args))


if __name__ == "__main__":
    sys.exit(main())
