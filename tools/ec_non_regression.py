"""Erasure-code non-regression corpus tool (--create / --check).

Re-expresses the reference's golden-chunk gate
(/root/reference/src/test/erasure-code/ceph_erasure_code_non_regression.cc:
ErasureCodeNonRegression, run_create 152 / run_check 225) — the mechanism the
reference uses, backed by its ceph-erasure-code-corpus submodule, to guarantee
that every (plugin, profile)'s encoded chunks stay bit-exact across versions.

--create writes, per profile, a directory named
  "plugin=<p> stripe-width=<w> <k=v> <k=v>..."
containing `content` (the encoded payload) and `chunk.N` golden files.
--check re-encodes `content` and fails if any chunk byte drifted, then
re-decodes every single erasure (and every pair, where the code can) and
fails if recovery is not bit-exact.

The repo commits the corpus under tests/corpus/; tests/test_non_regression.py
runs --check over it, so any drift in matrix construction, padding, chunk
layout, or kernel math fails CI. Content payload is a deterministic 37-byte
repeating alphabet string (the reference uses a random one but stores it; we
store it too, so determinism only helps review).
"""

from __future__ import annotations

import argparse
import itertools
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ceph_tpu.ec.interface import ErasureCodeError  # noqa: E402
from ceph_tpu.ec.registry import factory  # noqa: E402

#: the corpus profile matrix: (plugin, profile, stripe_width)
DEFAULT_PROFILES: list[tuple[str, dict, int]] = [
    ("jerasure", {"k": "4", "m": "2", "technique": "reed_sol_van"}, 4096),
    ("jerasure", {"k": "7", "m": "3", "technique": "reed_sol_van"}, 4096),
    ("jerasure", {"k": "4", "m": "2", "technique": "reed_sol_r6_op"}, 4096),
    ("jerasure", {"k": "4", "m": "2", "technique": "cauchy_orig"}, 4096),
    ("jerasure", {"k": "4", "m": "2", "technique": "cauchy_good"}, 4096),
    ("jerasure", {"k": "4", "m": "2", "w": "5", "technique": "liberation",
                  "packetsize": "32"}, 4096),
    ("jerasure", {"k": "4", "m": "2", "w": "6", "technique": "blaum_roth",
                  "packetsize": "32"}, 4096),
    ("jerasure", {"k": "6", "m": "2", "technique": "liber8tion",
                  "packetsize": "32"}, 4096),
    ("isa", {"k": "8", "m": "3", "technique": "cauchy"}, 4096),
    ("isa", {"k": "8", "m": "3", "technique": "reed_sol_van"}, 4096),
    ("shec", {"k": "4", "m": "3", "c": "2"}, 4096),
    ("shec", {"k": "6", "m": "4", "c": "3"}, 4096),
    ("lrc", {"k": "4", "m": "2", "l": "3"}, 4096),
    ("clay", {"k": "4", "m": "2", "d": "5"}, 4096),
    ("clay", {"k": "8", "m": "4", "d": "11"}, 98304),
    ("tpu", {"k": "8", "m": "3"}, 4096),
    ("native", {"k": "6", "m": "3", "technique": "cauchy"}, 4096),
]


def plugin_available(plugin: str) -> bool:
    """The native plugin needs a C++ toolchain (or a prebuilt .so); every
    other plugin is pure Python."""
    if plugin != "native":
        return True
    import shutil

    from ceph_tpu.native.build import plugin_path

    return bool(
        shutil.which("g++") or shutil.which("c++")
        or os.path.exists(plugin_path("native"))
    )


def profile_dir(base: str, plugin: str, profile: dict, stripe_width: int) -> str:
    parts = [f"plugin={plugin}", f"stripe-width={stripe_width}"]
    parts += [f"{k}={v}" for k, v in profile.items()]
    return os.path.join(base, " ".join(parts))


def payload(stripe_width: int) -> bytes:
    unit = bytes((ord("a") + i % 26) for i in range(37))
    data = (unit * (stripe_width // len(unit) + 1))[:stripe_width]
    return data


def create(base: str, plugin: str, profile: dict, stripe_width: int) -> str:
    ec = factory(plugin, dict(profile))
    d = profile_dir(base, plugin, profile, stripe_width)
    os.makedirs(d, exist_ok=True)
    content = payload(stripe_width)
    with open(os.path.join(d, "content"), "wb") as f:
        f.write(content)
    encoded = ec.encode(range(ec.get_chunk_count()), content)
    for i, chunk in encoded.items():
        with open(os.path.join(d, f"chunk.{i}"), "wb") as f:
            f.write(chunk)
    return d


def check(base: str, plugin: str, profile: dict, stripe_width: int) -> list[str]:
    errors: list[str] = []
    ec = factory(plugin, dict(profile))
    d = profile_dir(base, plugin, profile, stripe_width)
    if not os.path.isdir(d):
        return [f"{d}: missing corpus directory"]
    with open(os.path.join(d, "content"), "rb") as f:
        content = f.read()
    n = ec.get_chunk_count()
    golden = {}
    for i in range(n):
        with open(os.path.join(d, f"chunk.{i}"), "rb") as f:
            golden[i] = f.read()
    encoded = ec.encode(range(n), content)
    for i in range(n):
        if encoded[i] != golden[i]:
            errors.append(f"{d}: chunk {i} drifted from golden bytes")
    # recovery gate: every single erasure, and every pair the code can repair
    combos = [(i,) for i in range(n)]
    combos += list(itertools.combinations(range(n), 2))
    # only locally-repairable codes may legitimately fail on some pairs;
    # an MDS plugin failing ANY <=m-erasure decode is a regression
    lenient_pairs = plugin in ("shec", "lrc")
    for lost in combos:
        avail = {i: golden[i] for i in range(n) if i not in lost}
        try:
            decoded = ec.decode(set(lost), avail)
        except ErasureCodeError:
            if len(lost) == 1 or not lenient_pairs:
                errors.append(f"{d}: erasure {lost} unrecoverable")
            continue
        for i in lost:
            if decoded[i] != golden[i]:
                errors.append(f"{d}: erasure {lost}: chunk {i} mis-decoded")
    return errors


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--base", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests", "corpus"))
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--create", action="store_true")
    mode.add_argument("--check", action="store_true")
    args = ap.parse_args()

    failures: list[str] = []
    for plugin, profile, sw in DEFAULT_PROFILES:
        if not plugin_available(plugin):
            print(f"skip plugin={plugin} (no toolchain)")
            continue
        if args.create:
            print("create", create(args.base, plugin, profile, sw))
        else:
            errs = check(args.base, plugin, profile, sw)
            failures.extend(errs)
            status = "FAIL" if errs else "ok"
            print(f"check {profile_dir(args.base, plugin, profile, sw)}: {status}")
    for e in failures:
        print(e, file=sys.stderr)
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()
