"""Dataset operator CLI (the ckpt_tool role for ceph_tpu.data).

    python tools/data_tool.py --mon-host 127.0.0.1:6789 --pool 2 <cmd>

Commands:

    ingest <name> --npz file.npz      ingest an .npz's arrays as the
                                      dataset's records (sorted by key;
                                      equal dtype/shape -> tensor schema)
    ls <name>                         committed HEAD + every ingest
                                      present (aborted ingests show
                                      committed=false)
    verify <name> [--ingest-id ID]    fetch + crc-check every record
    iterate <name> [--seed S]         drain one epoch, print per-host
            [--batch-size B]          record counts + iterator perf
            [--num-hosts N] [--host H]
    bench [--mb N] [--record-kb K]    ingest + sustained-read GB/s and
          [--shards N] [--batch-size B]  records/s, one JSON line; reads
                                      run twice — prefetch pipeline on
                                      vs data_prefetch_batches=0 — and
                                      report the speedup + hit rate

Output is JSON per command, like tools/ckpt_tool.py."""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


async def _store(args):
    from ceph_tpu.common.config import Config
    from ceph_tpu.data import DataStore
    from ceph_tpu.mon import MonMap
    from ceph_tpu.rados.client import Rados

    addrs = []
    for hostport in args.mon_host.split(","):
        host, _, port = hostport.rpartition(":")
        addrs.append((host or "127.0.0.1", int(port)))
    rados = Rados(args.name_id, MonMap(addrs=addrs), config=Config())
    await rados.connect()
    return rados, DataStore(rados.io_ctx(args.pool), args.dataset_name)


def _records_from_npz(path: str) -> list:
    import numpy as np

    with np.load(path) as npz:
        return [np.asarray(npz[k]) for k in sorted(npz.files)]


async def _amain(args) -> int:
    if args.command == "bench":
        result = await _bench(args)
        print(json.dumps(result, sort_keys=True))
        return 0
    rados, store = await _store(args)
    try:
        if args.command == "ingest":
            ingest_id = await store.ingest(_records_from_npz(args.npz))
            result = {"ingest_id": ingest_id, "perf": store.perf_dump()}
        elif args.command == "ls":
            result = await store.ls()
        elif args.command == "verify":
            result = await store.verify(args.ingest_id)
        elif args.command == "iterate":
            it = await store.iterator(
                seed=args.seed, num_hosts=args.num_hosts,
                host=args.host, batch_size=args.batch_size,
            )
            records = batches = 0
            async for batch in it:
                records += len(batch)
                batches += 1
            result = {
                "records": records,
                "batches": batches,
                "perf": store.perf_dump(),
            }
        else:
            raise SystemExit(f"unknown command {args.command!r}")
        print(json.dumps(result, indent=2, sort_keys=True))
        return 0
    finally:
        await rados.shutdown()


async def _drain(store, *, seed: int, batch_size: int):
    """One full epoch; returns (seconds, records, bytes, perf delta)."""
    before = dict(store.perf.dump())
    t0 = time.perf_counter()
    it = await store.iterator(seed=seed, batch_size=batch_size)
    records = 0
    async for batch in it:
        records += len(batch)
    secs = time.perf_counter() - t0
    after = store.perf.dump()
    delta = {
        k: after[k] - before[k]
        for k in ("fetch_bytes", "prefetch_hits", "prefetch_waits")
    }
    return secs, records, delta


async def _bench(args) -> dict:
    """Ingest + sustained-read throughput against an in-process
    cluster, the `bench.py --data` engine. The read runs twice — with
    the prefetch pipeline and with data_prefetch_batches=0 — so the
    line carries its own serial baseline (the >= 2x acceptance bar)."""
    import numpy as np

    from tests.test_cluster_live import Cluster, EC_POOL, REP_POOL
    from ceph_tpu.data import DataStore
    from ceph_tpu.rados.client import Rados

    pool = EC_POOL if args.pool_kind == "ec" else REP_POOL
    cluster = Cluster()
    await cluster.start()
    rados = Rados("client.databench", cluster.monmap, config=cluster.cfg)
    await rados.connect()
    await cluster.create_pools(rados)
    try:
        total = args.mb * (1 << 20)
        rec_bytes = args.record_kb << 10
        n_records = max(1, total // rec_bytes)
        # size shards so the dataset spans the requested shard count
        cluster.cfg.set(
            "data_shard_bytes", max(4096, total // max(args.shards, 1))
        )
        rng = np.random.default_rng(0)
        records = [
            rng.integers(0, 256, rec_bytes, np.uint8)
            for _ in range(n_records)
        ]
        store = DataStore(rados.io_ctx(pool), "bench-data")
        t0 = time.perf_counter()
        await store.ingest(records)
        t_ingest = time.perf_counter() - t0
        total = n_records * rec_bytes

        prefetch = cluster.cfg.get("data_prefetch_batches")
        read_s, n_read, d = await _drain(
            store, seed=1, batch_size=args.batch_size
        )
        assert n_read == n_records, (n_read, n_records)
        cluster.cfg.set("data_prefetch_batches", 0)
        base_s, n_base, _ = await _drain(
            store, seed=1, batch_size=args.batch_size
        )
        assert n_base == n_records
        cluster.cfg.set("data_prefetch_batches", prefetch)
        asked = d["prefetch_hits"] + d["prefetch_waits"]
        return {
            "bench": "data",
            "pool": args.pool_kind,
            "bytes": total,
            "records": n_records,
            "shards": args.shards,
            "ingest_s": round(t_ingest, 6),
            "ingest_gbps": round(total / t_ingest / 1e9, 4),
            "read_s": round(read_s, 6),
            "read_gbps": round(total / read_s / 1e9, 4),
            "records_per_s": round(n_records / read_s, 1),
            "read_noprefetch_s": round(base_s, 6),
            "read_noprefetch_gbps": round(total / base_s / 1e9, 4),
            "prefetch_speedup": round(base_s / max(read_s, 1e-9), 2),
            "prefetch_hit_rate": round(
                d["prefetch_hits"] / max(asked, 1), 4
            ),
        }
    finally:
        await rados.shutdown()
        await cluster.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="data_tool")
    ap.add_argument("--mon-host", default="127.0.0.1:6789")
    ap.add_argument("--pool", type=int, default=1)
    ap.add_argument("--name", dest="name_id", default="client.data")
    ap.add_argument("--npz", default="")
    ap.add_argument("--ingest-id", default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-hosts", type=int, default=1)
    ap.add_argument("--host", type=int, default=0)
    ap.add_argument("--mb", type=int, default=16)
    ap.add_argument("--record-kb", type=int, default=64)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--pool-kind", choices=("rep", "ec"), default="ec")
    ap.add_argument("command",
                    choices=("ingest", "ls", "verify", "iterate",
                             "bench"))
    ap.add_argument("dataset_name", nargs="?", default="dataset")
    args = ap.parse_args(argv)
    return asyncio.run(_amain(args))


if __name__ == "__main__":
    sys.exit(main())
