#!/usr/bin/env python3
"""tools/lint.py — cephlint entry point (see ceph_tpu/lint/).

    python tools/lint.py                      # lint ceph_tpu + tests
    python tools/lint.py ceph_tpu tests       # explicit paths
    python tools/lint.py --json               # summary counters as JSON
    python tools/lint.py --baseline-update    # regrandfather findings

Exits non-zero on NEW findings (not comment-suppressed, not in
tools/lint_baseline.json).  Suppress in place with
`# cephlint: disable=<check>`; the runtime race detector rides along as
CEPH_TPU_RACECHECK=1 (see ceph_tpu/lint/racecheck.py).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ceph_tpu.lint.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
