"""ceph-dencoder analogue: encode/decode the framework's versioned wire
structs from the shell, for golden-corpus generation and format debugging.

    python tools/dencoder.py list_types
    python tools/dencoder.py decode <type> < blob.bin        # -> JSON
    python tools/dencoder.py encode <type> < doc.json        # -> blob
    python tools/dencoder.py round_trip <type> < blob.bin    # re-encode,
                                                             # fail on drift

Types cover what travels on the wire or sits in stores: osdmap,
osdmap_incremental, kv_transaction, message, frame. The reference's
ceph-dencoder + ceph-object-corpus guard cross-version format stability
the same way (SURVEY §4 tier 2); tests/test_encoding.py holds the
committed golden blobs.
"""

from __future__ import annotations

import json
import sys


def _osdmap_to_json(m) -> dict:
    return {
        "epoch": m.epoch,
        "max_osd": m.max_osd,
        "pools": {
            str(k): {"pg_num": p.pg_num, "size": p.size, "type": p.type,
                     "crush_rule": p.crush_rule,
                     "erasure_code_profile": p.erasure_code_profile}
            for k, p in sorted(m.pools.items())
        },
        "num_up": int(m.osd_up.sum()),
        "erasure_code_profiles": m.erasure_code_profiles,
        "pg_upmap_items": {
            f"{k[0]}.{k[1]}": v for k, v in sorted(m.pg_upmap_items.items())
        },
        "osd_addrs": {str(k): list(v) for k, v in sorted(m.osd_addrs.items())},
    }


def _types():
    from ceph_tpu.common.kv import KVTransaction
    from ceph_tpu.msg.frames import Frame, Message, read_frame  # noqa: F401
    from ceph_tpu.osd.osdmap import Incremental, OSDMap

    def dec_message(raw):
        m = Message.decode(raw)
        return {"type": m.type, "tid": m.tid, "seq": m.seq,
                "epoch": m.epoch, "data_len": len(m.data)}

    def dec_kv(raw):
        t = KVTransaction.decode(raw)
        return {"ops": [
            {"op": op, "prefix": pfx.decode(errors="replace"),
             "key": key.decode(errors="replace"), "value_len": len(val)}
            for op, pfx, key, val in t.ops
        ]}

    def dec_inc(raw):
        inc = Incremental.decode(raw)
        return {
            "epoch": inc.epoch,
            "new_up": inc.new_up, "new_down": inc.new_down,
            "new_weight": {str(k): v for k, v in inc.new_weight.items()},
            "new_pools": sorted(inc.new_pools),
            "has_crush": inc.new_crush_text is not None,
            "new_pg_temp": {
                f"{k[0]}.{k[1]}": v for k, v in inc.new_pg_temp.items()
            },
            "new_osd_addrs": {
                str(k): list(v) for k, v in inc.new_osd_addrs.items()
            },
        }

    return {
        "osdmap": (
            lambda raw: _osdmap_to_json(OSDMap.decode(raw)),
            lambda raw: OSDMap.decode(raw).encode(),
        ),
        "osdmap_incremental": (
            dec_inc,
            lambda raw: Incremental.decode(raw).encode(),
        ),
        "kv_transaction": (
            dec_kv,
            lambda raw: KVTransaction.decode(raw).encode(),
        ),
        "message": (
            dec_message,
            lambda raw: Message.decode(raw).encode(),
        ),
    }


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__)
        return 1
    cmd = argv[0]
    types = _types()
    if cmd == "list_types":
        print(json.dumps(sorted(types)))
        return 0
    if cmd in ("decode", "round_trip"):
        tname = argv[1]
        if tname not in types:
            print(f"unknown type {tname!r}", file=sys.stderr)
            return 1
        raw = sys.stdin.buffer.read()
        to_json, reencode = types[tname]
        if cmd == "decode":
            print(json.dumps(to_json(raw), indent=2, sort_keys=True))
            return 0
        again = reencode(raw)
        if again != raw:
            print(
                f"DRIFT: {tname} re-encoded to {len(again)} bytes, "
                f"input was {len(raw)}", file=sys.stderr,
            )
            return 2
        print(json.dumps({"type": tname, "bytes": len(raw),
                          "round_trip": "exact"}))
        return 0
    print(f"unknown command {cmd!r}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
