"""chaos_tool — run a seeded chaos script against a LIVE MiniCluster.

The live half of the chaos harness (the qa Thrasher +
msgr-failures-fragment role): `ceph_tpu.sim.chaos.chaos_script(seed)`
compiles the seed into a deterministic timeline — OSD flaps, asymmetric
partitions, a kill -9 of the backfill source mid-push, wire-fault
storms — and this tool executes it against real daemons over real TCP
while a client workload runs throughout, then settles and judges three
oracles:

* zero acked-data loss — every acked write reads back (failed writes
  may land either way, the RadosModel either/or discipline);
* convergence to clean — every OSD back up, no backfill in flight,
  deep scrub of every pool reports zero inconsistencies;
* bounded client p99 — op latency through the storm stays under
  --p99-budget, and no step fully starves the client.

Replayable: the same --seed produces the same scripted timeline (wire
faults draw from per-pair streams seeded by ms_inject_chaos_seed).

    python tools/chaos_tool.py --seed 7 [--steps 8] [--json]

Exit status 0 = all oracles hold; 1 = a violation (details on stderr).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

N_OSDS = 6
REP_POOL = 1
EC_POOL = 2


def chaos_config():
    from ceph_tpu.common.config import Config

    cfg = Config()
    cfg.set("mon_lease", 0.1)
    cfg.set("mon_election_timeout", 0.4)
    cfg.set("osd_heartbeat_interval", 0.15)
    cfg.set("osd_heartbeat_grace", 2)
    cfg.set("osd_min_pg_log_entries", 20)  # trim -> backfill in play
    return cfg


class LiveCluster:
    """In-process mons + osds sharing ONE Config object, so a single
    `cfg.set("ms_inject_chaos_schedule", ...)` arms every messenger at
    once (the rules' src/dst globs confine the blast radius)."""

    def __init__(self, cfg):
        from ceph_tpu.mon import MonMap

        self.cfg = cfg
        self.monmap = MonMap(addrs=[("127.0.0.1", 0)] * 3)
        self.mons = []
        self.osds = {}

    async def start(self):
        from ceph_tpu.mon import Monitor
        from ceph_tpu.vstart import initial_osdmap

        base = initial_osdmap(N_OSDS)
        self.mons = [
            Monitor(r, self.monmap, base, config=self.cfg)
            for r in range(3)
        ]
        for m in self.mons:
            await m.bind()
        for m in self.mons:
            m.go()
        for osd_id in range(N_OSDS):
            await self.start_osd(osd_id)

    async def start_osd(self, osd_id, db=None):
        from ceph_tpu.osd.daemon import OSDService

        osd = OSDService(osd_id, self.monmap, db=db, config=self.cfg)
        await osd.start()
        self.osds[osd_id] = osd
        return osd

    async def kill_osd(self, osd_id):
        """Process-kill semantics: the daemon dies mid-whatever, the
        store object survives for revival (qa Thrasher kill_osd)."""
        osd = self.osds.pop(osd_id)
        db = osd.store.db
        await osd.stop()
        return db

    async def create_pools(self, rados):
        await rados.mon_command(
            "osd erasure-code-profile set",
            {"name": "k2m2",
             "profile": {"plugin": "tpu", "k": "2", "m": "2"}},
        )
        await rados.mon_command(
            "osd pool create",
            {"pool_id": REP_POOL, "crush_rule": 1, "size": 3,
             "pg_num": 8},
        )
        await rados.mon_command(
            "osd pool create",
            {"pool_id": EC_POOL, "crush_rule": 0,
             "erasure_code_profile": "k2m2", "pg_num": 8},
        )

    async def stop(self):
        for osd in list(self.osds.values()):
            await osd.stop()
        for m in self.mons:
            await m.stop()


async def wait_until(pred, timeout=60.0):
    from ceph_tpu.msg.messenger import next_dispatch_event

    loop = asyncio.get_event_loop()
    end = loop.time() + timeout
    while not pred():
        remaining = end - loop.time()
        if remaining <= 0:
            raise TimeoutError
        try:
            await asyncio.wait_for(
                next_dispatch_event(), min(0.25, remaining)
            )
        except asyncio.TimeoutError:
            pass


def backfill_source(cluster):
    """The OSD currently pushing a backfill (primary of a PG with
    backfill targets), or None when nothing is in flight."""
    for osd_id, osd in sorted(cluster.osds.items()):
        for pg in osd.pgs.values():
            if pg.backfill_targets:
                return osd_id
    return None


async def run_chaos_live(seed, steps=8, step_seconds=2.0,
                         p99_budget=8.0, progress=print):
    """Execute chaos_script(seed) against a live cluster; returns the
    oracle report dict (raises nothing — violations are in the dict)."""
    from ceph_tpu.rados.client import ObjectNotFound, Rados, RadosError
    from ceph_tpu.sim.chaos import chaos_script

    script = chaos_script(seed, n_osd=N_OSDS, steps=steps)
    cluster = LiveCluster(chaos_config())
    await cluster.start()
    cluster.cfg.set("ms_inject_chaos_seed", int(seed))
    rados = Rados("client.chaos", cluster.monmap, config=cluster.cfg)
    await rados.connect()
    await cluster.create_pools(rados)

    loop = asyncio.get_event_loop()
    #: (pool, name) -> set of acceptable payloads (RadosModel either/or)
    model: dict[tuple[int, str], set] = {}
    lat: list[float] = []
    step_ok = []          # successful client ops per step
    import random as _random

    wrng = _random.Random(seed ^ 0xC0FFEE)

    async def one_op():
        pool = wrng.choice([REP_POOL, EC_POOL])
        name = f"c{wrng.randrange(24)}"
        data = bytes([wrng.randrange(256)]) * wrng.randrange(64, 2048)
        key = (pool, name)
        t0 = loop.time()
        try:
            await rados.objecter.op_submit(
                pool, name, "write", data, timeout=8.0
            )
            model[key] = {data}
            lat.append(loop.time() - t0)
            return True
        except RadosError:
            model[key] = model.get(key, {None}) | {data}
            return False

    dead: dict[int, object] = {}       # osd -> saved db (None=amnesiac)
    revive_at: dict[int, int] = {}
    armed: list[tuple[str, int]] = []  # (schedule, expires_step)
    executed = []

    def arm():
        cluster.cfg.set(
            "ms_inject_chaos_schedule",
            ";".join(s for s, _ in armed),
        )

    by_step: dict[int, list[dict]] = {}
    for e in script["events"]:
        by_step.setdefault(e["step"], []).append(e)

    total_steps = script["steps"] + 3  # tail drains holds + revivals
    for step in range(total_steps):
        # revivals and schedule expiry due this step
        for osd in [o for o, s in revive_at.items() if s <= step]:
            del revive_at[osd]
            await cluster.start_osd(osd, db=dead.pop(osd))
        if any(s <= step for _, s in armed):
            armed = [(x, s) for x, s in armed if s > step]
            arm()

        for e in by_step.get(step, ()):
            kind = e["kind"]
            if kind == "flap":
                if e["osd"] in cluster.osds:
                    dead[e["osd"]] = await cluster.kill_osd(e["osd"])
                    revive_at[e["osd"]] = step + 1 + e["down_steps"]
                    executed.append(["flap", e["osd"]])
            elif kind == "kill_backfill_source":
                # provoke a backfill: amnesiac-kill the fallback, write
                # through the hole, revive it EMPTY -> backfill starts,
                # then kill -9 whichever source is pushing to it
                v = e["fallback_osd"]
                if v in cluster.osds and len(dead) < 2:
                    await cluster.kill_osd(v)  # db discarded: amnesiac
                    for _ in range(12):
                        await one_op()
                    await cluster.start_osd(v)
                    try:
                        await wait_until(
                            lambda: backfill_source(cluster) is not None,
                            timeout=20,
                        )
                    except TimeoutError:
                        pass
                    src = backfill_source(cluster)
                    if src is None:
                        src = next(
                            o for o in sorted(cluster.osds) if o != v
                        )
                    dead[src] = await cluster.kill_osd(src)
                    revive_at[src] = step + 1 + e["down_steps"]
                    executed.append(["kill_backfill_source", src])
            else:  # partitions and storms: arm the wire schedule
                armed.append((e["schedule"], step + e["hold_steps"]))
                arm()
                executed.append([kind, e["schedule"]])

        # client workload rides through the whole step
        ok = 0
        end = loop.time() + step_seconds
        while loop.time() < end:
            ok += 1 if await one_op() else 0
        step_ok.append(ok)
        progress(
            f"step {step}: ok_ops={ok} dead={sorted(dead)} "
            f"armed={len(armed)}"
        )

    # settle: disarm, revive everything, wait for clean
    armed = []
    arm()
    for osd in list(dead):
        await cluster.start_osd(osd, db=dead.pop(osd))
    revive_at.clear()
    await wait_until(
        lambda: all(
            not any(o.osdmap.is_down(i) for i in range(N_OSDS))
            for o in cluster.osds.values()
        ),
        timeout=90,
    )
    await wait_until(
        lambda: backfill_source(cluster) is None, timeout=90
    )

    # oracle 1: zero acked-data loss
    lost = []
    for (pool, name), want in sorted(model.items()):
        try:
            rep = await rados.objecter.op_submit(
                pool, name, "read", timeout=15.0
            )
            got = rep["_raw"]
        except ObjectNotFound:
            got = None
        if got not in want:
            lost.append([pool, name])

    # oracle 2: convergence to clean — deep scrub everything (polled:
    # stray copies from the churn settle over a few peering passes)
    async def scrub_errors():
        errs = []
        for o in list(cluster.osds.values()):
            for pool in (REP_POOL, EC_POOL):
                rep = await rados.objecter.osd_admin(
                    o.id, "scrub", {"pool": pool, "deep": True}
                )
                errs.extend(rep["errors"])
        return errs

    deadline = loop.time() + 90
    errors = await scrub_errors()
    while errors and loop.time() < deadline:
        await asyncio.sleep(1)
        errors = await scrub_errors()

    # oracle 3: bounded client p99, never fully starved
    p99 = sorted(lat)[int(len(lat) * 0.99)] if lat else 0.0
    starved = [i for i, n in enumerate(step_ok) if n == 0]

    await rados.shutdown()
    await cluster.stop()
    return {
        "seed": int(seed),
        "script_events": len(script["events"]),
        "executed": executed,
        "client_ops": len(lat) + len(lost),
        "acked_keys": len(model),
        "lost": lost,
        "scrub_errors": len(errors),
        "p99_s": round(p99, 4),
        "p99_budget_s": p99_budget,
        "starved_steps": starved,
        "ok": (not lost and not errors and p99 <= p99_budget
               and not starved),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="chaos_tool")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--step-seconds", type=float, default=2.0)
    ap.add_argument("--p99-budget", type=float, default=8.0,
                    help="max acceptable client p99 (seconds)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    progress = (lambda *_: None) if args.quiet or args.json else print
    report = asyncio.run(run_chaos_live(
        args.seed, steps=args.steps, step_seconds=args.step_seconds,
        p99_budget=args.p99_budget, progress=progress,
    ))
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(
            f"seed {report['seed']}: {report['client_ops']} client ops, "
            f"p99 {report['p99_s']}s, lost={len(report['lost'])}, "
            f"scrub_errors={report['scrub_errors']}, "
            f"starved_steps={report['starved_steps']}"
        )
    if not report["ok"]:
        print(f"ORACLE VIOLATION: {report}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
