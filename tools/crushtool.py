"""crushtool — compile/decompile/test crush maps (reference CLI parity).

Mirrors /root/reference/src/tools/crushtool.cc's surface for the workflows the
framework supports:

    crushtool -c map.txt -o map.bin        # compile text -> stored map
    crushtool -d map.bin [-o map.txt]      # decompile stored map -> text
    crushtool -i map.bin --test [...]      # CrushTester placement engine
    crushtool -i map.bin --tree            # hierarchy dump

Tester flags (crushtool.cc:535+): --min-x/--max-x/--x, --num-rep/--min-rep/
--max-rep, --rule, --ruleset, --weight <devno> <w>,
--show-mappings, --show-bad-mappings, --show-utilization,
--show-utilization-all, --show-statistics, --pool-id.

The stored-map container is JSON (schema below), NOT the reference's binary
bufferlist encoding — reading maps produced by the C crushtool is not
supported (decode of its wire format is future work); text maps are the
interchange format. `-i`/`-d` sniff text crushmaps and accept them directly,
so `crushtool -i map.txt --test` works on reference fixture files.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ceph_tpu.crush.compiler import (  # noqa: E402
    CompileError,
    compile_crushmap,
    decompile_crushmap,
)
from ceph_tpu.crush.tester import CrushTester  # noqa: E402
from ceph_tpu.crush.types import CrushMap  # noqa: E402

STORE_VERSION = 1


def store_map(cmap: CrushMap) -> str:
    """Serialize via the text form inside a versioned JSON envelope: the text
    grammar is the canonical (and reference-compatible) representation."""
    return json.dumps(
        {"ceph_tpu_crushmap": STORE_VERSION, "text": decompile_crushmap(cmap)}
    )


def load_map(path: str) -> CrushMap:
    data = open(path, "rb").read()
    try:
        text = data.decode()
    except UnicodeDecodeError as e:
        raise CompileError(
            f"{path}: binary crushmaps from the reference crushtool are not "
            "supported; use the text form"
        ) from e
    stripped = text.lstrip()
    if stripped.startswith("{"):
        doc = json.loads(text)
        if doc.get("ceph_tpu_crushmap") != STORE_VERSION:
            raise CompileError(f"{path}: not a ceph_tpu crushmap store")
        return compile_crushmap(doc["text"])
    return compile_crushmap(text)


def dump_tree(cmap: CrushMap, out) -> None:
    """`crushtool --tree` style hierarchy dump over the shared
    CrushTreeDumper walk (ceph_tpu.crush.tree)."""
    from ceph_tpu.crush.tree import dump_items

    print("ID\tWEIGHT\tTYPE NAME", file=out)
    for node in dump_items(cmap):
        indent = "\t" * node["depth"]
        label = (
            node["name"] if node["type"] == "osd"
            else f"{node['type']} {node['name']}"
        )
        print(
            f"{node['id']}\t{node['weight']:.5f}\t{indent}{label}",
            file=out,
        )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="crushtool", add_help=True)
    ap.add_argument("-i", "--infn", metavar="map")
    ap.add_argument("-c", "--compile", metavar="map.txt", dest="srcfn")
    ap.add_argument("-d", "--decompile", metavar="map")
    ap.add_argument("-o", "--outfn", metavar="out")
    ap.add_argument("--test", action="store_true")
    ap.add_argument("--tree", action="store_true")
    ap.add_argument("--check", action="store_true",
                    help="validate map structure (cycles, dangling "
                         "items, weight sums)")
    ap.add_argument("--min-x", type=int, default=-1)
    ap.add_argument("--max-x", type=int, default=-1)
    ap.add_argument("--x", type=int, default=None)
    ap.add_argument("--num-rep", type=int, default=None)
    ap.add_argument("--min-rep", type=int, default=-1)
    ap.add_argument("--max-rep", type=int, default=-1)
    ap.add_argument("--rule", type=int, default=-1)
    ap.add_argument("--ruleset", type=int, default=-1)
    ap.add_argument("--pool-id", type=int, default=-1)
    ap.add_argument("--weight", nargs=2, action="append", default=[],
                    metavar=("devno", "weight"))
    for tun in ("choose-local-tries", "choose-local-fallback-tries",
                "choose-total-tries", "chooseleaf-descend-once",
                "chooseleaf-vary-r", "chooseleaf-stable",
                "straw-calc-version"):
        ap.add_argument(f"--set-{tun}", type=int, default=None,
                        dest=f"set_{tun.replace('-', '_')}")
    ap.add_argument("--show-mappings", action="store_true")
    ap.add_argument("--show-bad-mappings", action="store_true")
    ap.add_argument("--show-utilization", action="store_true")
    ap.add_argument("--show-utilization-all", action="store_true")
    ap.add_argument("--show-statistics", action="store_true")
    args = ap.parse_args(argv)

    try:
        if args.srcfn:  # -c: compile
            cmap = compile_crushmap(open(args.srcfn).read())
            if args.outfn:
                with open(args.outfn, "w") as f:
                    f.write(store_map(cmap))
            return 0

        if args.decompile:  # -d
            cmap = load_map(args.decompile)
            text = decompile_crushmap(cmap)
            if args.outfn:
                with open(args.outfn, "w") as f:
                    f.write(text)
            else:
                sys.stdout.write(text)
            return 0

        if not args.infn:
            ap.error("no action specified (use -i/-c/-d)")
        cmap = load_map(args.infn)

        for tun in ("choose_local_tries", "choose_local_fallback_tries",
                    "choose_total_tries", "chooseleaf_descend_once",
                    "chooseleaf_vary_r", "chooseleaf_stable",
                    "straw_calc_version"):
            val = getattr(args, f"set_{tun}")
            if val is not None:
                setattr(cmap.tunables, tun, val)
                if tun == "straw_calc_version":
                    # straw lengths are a build-time product of this tunable
                    from ceph_tpu.crush.builder import calc_straws
                    from ceph_tpu.crush.types import BucketAlg

                    for b in cmap.buckets.values():
                        if b.alg == BucketAlg.STRAW:
                            b.straws = calc_straws(b.item_weights, val)

        if args.tree:
            dump_tree(cmap, sys.stdout)
            return 0

        if args.check:
            from ceph_tpu.crush.tree import validate

            problems = validate(cmap)
            for p in problems:
                print(p, file=sys.stderr)
            return 1 if problems else 0

        if args.test:
            tester = CrushTester(cmap)
            tester.min_x, tester.max_x = args.min_x, args.max_x
            if args.x is not None:
                tester.min_x = tester.max_x = args.x
            tester.min_rep, tester.max_rep = args.min_rep, args.max_rep
            if args.num_rep is not None:
                tester.min_rep = tester.max_rep = args.num_rep
            if args.rule >= 0:
                tester.min_rule = tester.max_rule = args.rule
            tester.ruleset = args.ruleset
            tester.pool_id = args.pool_id
            for devno, w in args.weight:
                # crushtool parses weight as float (1.0 = 0x10000)
                tester.device_weight[int(devno)] = int(float(w) * 0x10000)
            tester.output_mappings = args.show_mappings
            tester.output_bad_mappings = args.show_bad_mappings
            tester.output_utilization = args.show_utilization
            tester.output_utilization_all = args.show_utilization_all
            tester.output_statistics = args.show_statistics
            # the reference CLI folds utilization output into statistics
            # mode (crushtool.cc:1271-1274)
            if tester.output_utilization or tester.output_utilization_all:
                tester.output_statistics = True
            return tester.test()

        ap.error("nothing to do with -i (use --test/--tree/-d)")
    except CompileError as e:
        print(e, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
