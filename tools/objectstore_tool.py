#!/usr/bin/env python
"""ceph-objectstore-tool analogue: OFFLINE surgery on an OSD's store.

Operates directly on a stopped OSD's durable store (the FileDB
directory; KStore or BlockStore, autodetected like the reference probes
the store type from the data dir), the way the reference tool opens a
stopped OSD's BlueStore/FileStore (src/tools/ceph_objectstore_tool.cc):

    python tools/objectstore_tool.py --data-path <dir> --op list
    python tools/objectstore_tool.py --data-path <dir> --op list --pgid 2.3
    python tools/objectstore_tool.py --data-path <dir> --op info \
        --pgid 2.3 --obj <name>
    python tools/objectstore_tool.py --data-path <dir> --op get \
        --pgid 2.3 --obj <name> --out <file>
    python tools/objectstore_tool.py --data-path <dir> --op export \
        --pgid 2.3 --out <file>
    python tools/objectstore_tool.py --data-path <dir> --op import \
        --file <file>
    python tools/objectstore_tool.py --data-path <dir> --op log --pgid 2.3
    python tools/objectstore_tool.py --data-path <dir> --op fsck [--deep]

export/import move one PG's complete contents (objects + attrs + omap +
the pg-meta log) between stores as a JSON bundle — the disaster-recovery
flow the reference tool exists for (yank a PG off a dead OSD's disk,
inject it into a fresh one); the bundle is store-agnostic, so a PG
exported from a KStore OSD imports into a BlockStore OSD and vice versa.
`--op fsck` runs the store's own consistency check (`--deep` re-reads
every blob against its at-rest checksums on BlockStore) and exits
nonzero when errors are found, like `ceph-objectstore-tool --op fsck`.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from ceph_tpu.common.kv import FileDB  # noqa: E402
from ceph_tpu.osd.objectstore import (  # noqa: E402
    KStore,
    StoreError,
    Transaction,
)

PGMETA = ".pgmeta"


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


def _attrs_jsonable(attrs: dict) -> dict:
    from ceph_tpu.osd.ecutil import HashInfo

    out = {}
    for k, v in attrs.items():
        if isinstance(v, HashInfo):
            out[k] = {"__hinfo__": [v.total_chunk_size,
                                    list(v.cumulative_shard_hashes)]}
        elif isinstance(v, bytes):
            out[k] = {"__b64__": _b64(v)}
        else:
            out[k] = v
    return out


def _attrs_restore(raw: dict) -> dict:
    from ceph_tpu.osd.ecutil import HashInfo

    out = {}
    for k, v in raw.items():
        if isinstance(v, dict) and "__hinfo__" in v:
            out[k] = HashInfo(v["__hinfo__"][0], list(v["__hinfo__"][1]))
        elif isinstance(v, dict) and "__b64__" in v:
            out[k] = _unb64(v["__b64__"])
        else:
            out[k] = v
    return out


def _coll_of(pgid: str) -> str:
    pool, _, ps = pgid.partition(".")
    return f"pg_{int(pool)}_{int(ps)}"


def open_store(data_path: str, type_: str = "auto"):
    """(store, backend-name) over a stopped OSD's FileDB dir. `auto`
    probes for BlockStore's pinned-geometry row / block file, the way
    the reference sniffs the store type from the data dir."""
    db = FileDB(data_path)
    if type_ == "auto":
        type_ = (
            "blockstore"
            if db.get(b"bmt", b"geometry") is not None
            or os.path.exists(os.path.join(data_path, "block"))
            else "kstore"
        )
    if type_ == "blockstore":
        from ceph_tpu.osd.blockstore import BlockStore

        return BlockStore(db), "blockstore"
    return KStore(db), "kstore"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="objectstore_tool")
    ap.add_argument("--data-path", required=True)
    ap.add_argument("--op", required=True,
                    choices=["list", "info", "get", "log", "export",
                             "import", "fsck"])
    ap.add_argument("--type", default="auto",
                    choices=["auto", "kstore", "blockstore"],
                    help="store backend (auto probes the data dir)")
    ap.add_argument("--deep", action="store_true",
                    help="fsck: re-read every blob against its stored "
                         "checksums")
    ap.add_argument("--pgid")
    ap.add_argument("--obj")
    ap.add_argument("--out")
    ap.add_argument("--file")
    args = ap.parse_args(argv)

    store, backend = open_store(args.data_path, args.type)
    db = store.db
    try:
        if args.op == "fsck":
            errors = store.fsck(deep=args.deep)
            print(json.dumps({
                "backend": backend,
                "deep": args.deep,
                "error_count": len(errors),
                "errors": errors,
            }, indent=2))
            return 1 if errors else 0
        if args.op == "list":
            colls = (
                [_coll_of(args.pgid)] if args.pgid
                else sorted(store.list_collections())
            )
            for coll in colls:
                for name in sorted(store.list_objects(coll)):
                    if name == PGMETA:
                        continue
                    print(json.dumps({"pgid": coll, "name": name}))
            return 0
        if args.op == "info":
            coll = _coll_of(args.pgid)
            attrs = store.getattrs(coll, args.obj)
            data = store.read(coll, args.obj)
            print(json.dumps({
                "name": args.obj,
                "size": len(data),
                "attrs": _attrs_jsonable(attrs),
                "omap_keys": len(store.omap_get(coll, args.obj)),
            }, indent=2))
            return 0
        if args.op == "get":
            data = store.read(_coll_of(args.pgid), args.obj)
            if args.out in (None, "-"):
                sys.stdout.buffer.write(data)
            else:
                with open(args.out, "wb") as f:
                    f.write(data)
            return 0
        if args.op == "log":
            omap = store.omap_get(_coll_of(args.pgid), PGMETA)
            entries = [
                json.loads(v) for k, v in sorted(omap.items())
                if k.startswith(b"log/")
            ]
            print(json.dumps({"log": entries}, indent=2))
            return 0
        if args.op == "export":
            coll = _coll_of(args.pgid)
            bundle = {"pgid": args.pgid, "objects": []}
            for name in sorted(store.list_objects(coll)):
                entry = {
                    "name": name,
                    "data": _b64(store.read(coll, name)),
                    "attrs": _attrs_jsonable(store.getattrs(coll, name)),
                    "omap": {
                        _b64(k): _b64(v)
                        for k, v in store.omap_get(coll, name).items()
                    },
                }
                bundle["objects"].append(entry)
            out = args.out or f"{args.pgid}.export"
            with open(out, "w") as f:
                json.dump(bundle, f)
            print(json.dumps(
                {"exported": len(bundle["objects"]), "to": out}
            ))
            return 0
        if args.op == "import":
            with open(args.file) as f:
                bundle = json.load(f)
            coll = _coll_of(bundle["pgid"])
            txn = Transaction()
            if not store.collection_exists(coll):
                txn.create_collection(coll)
            for entry in bundle["objects"]:
                txn.write(
                    coll, entry["name"], _unb64(entry["data"]),
                    attrs=_attrs_restore(entry["attrs"]),
                )
                if entry["omap"]:
                    txn.omap_setkeys(coll, entry["name"], {
                        _unb64(k): _unb64(v)
                        for k, v in entry["omap"].items()
                    })
            store.queue_transaction(txn)
            print(json.dumps(
                {"imported": len(bundle["objects"]),
                 "pgid": bundle["pgid"]}
            ))
            return 0
        return 2
    except StoreError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        # offline tooling must never mutate the store on the way out:
        # BlockStore.close() skips the deferred flush umount() would do
        if hasattr(store, "close"):
            store.close()
        else:
            db.close()


if __name__ == "__main__":
    sys.exit(main())
