"""Measure the in-repo CPU erasure-code baselines (VERDICT round-1 item 7).

Two measured numbers for RS(8,3) encode on this host, single thread:

  1. `numpy`  — the pure-numpy GF(2^8) oracle (ceph_tpu.ops.gf.gf_matmul),
     log/antilog table gathers: the slow correctness reference.
  2. `c-xor`  — tools/ec_cpu_baseline.c: bit-plane XOR-schedule encode in
     64-bit words, the same algorithm class as the reference's jerasure
     bitmatrix techniques (ErasureCodeJerasure.cc:305 prepare_schedule).
     This is the honest single-core CPU number the TPU path is compared
     against in bench.py / BASELINE.md.

Usage: python tools/cpu_ec_baseline.py [--size BYTES_PER_CHUNK] [--iters N]
Prints one JSON line with both GB/s figures.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ceph_tpu.ec import matrices  # noqa: E402
from ceph_tpu.ops import gf  # noqa: E402

K, M = 8, 3


def measure_numpy(chunk: int, iters: int) -> float:
    rng = np.random.default_rng(0)
    parity = matrices.build_parity_matrix("isa_cauchy", K, M)
    data = rng.integers(0, 256, (K, chunk), np.uint8)
    gf.gf_matmul(parity, data)  # warm tables
    t0 = time.perf_counter()
    for _ in range(iters):
        gf.gf_matmul(parity, data)
    dt = time.perf_counter() - t0
    return K * chunk * iters / dt / 1e9


def measure_c(chunk: int, iters: int) -> float | None:
    here = os.path.dirname(os.path.abspath(__file__))
    src = os.path.join(here, "ec_cpu_baseline.c")
    out = os.path.join(tempfile.mkdtemp(prefix="ec_base_"), "ec_base")
    try:
        subprocess.run(
            ["gcc", "-O3", "-march=native", src, "-o", out],
            check=True, capture_output=True,
        )
    except (subprocess.CalledProcessError, FileNotFoundError):
        return None
    parity = matrices.build_parity_matrix("isa_cauchy", K, M)
    bits = gf.matrix_to_bitmatrix(parity)
    psize = 2048  # jerasure default packetsize (ErasureCodeJerasure.h:140)
    feed = f"{K} {M} {psize} {iters} {chunk}\n" + " ".join(
        str(int(v)) for v in bits.reshape(-1)
    )
    best = None
    for _ in range(3):
        proc = subprocess.run(
            [out], input=feed, capture_output=True, text=True, check=True
        )
        el = float(proc.stdout.strip())
        best = el if best is None else min(best, el)
    return K * chunk * iters / best / 1e9


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=1 << 22,
                    help="bytes per chunk (default 4 MiB)")
    ap.add_argument("--iters", type=int, default=8)
    args = ap.parse_args()
    numpy_gbps = measure_numpy(args.size, max(1, args.iters // 4))
    c_gbps = measure_c(args.size, args.iters)
    print(json.dumps({
        "config": f"RS({K},{M}) encode, {args.size} B chunks, single thread",
        "numpy_gbps": round(numpy_gbps, 3),
        "c_xor_gbps": round(c_gbps, 3) if c_gbps else None,
    }))


if __name__ == "__main__":
    main()
