"""ec_bench — drop-in CLI for the reference's ceph_erasure_code_benchmark.

Accepts the same flags (/root/reference/src/test/erasure-code/
ceph_erasure_code_benchmark.cc:40-66) and emits the same output format:
`elapsed_seconds \t KiB_processed` (.cc:179,310), so the reference's sweep
scripts (qa/workunits/erasure-code/bench.sh) can drive the TPU backend
unmodified:

    python tools/ec_bench.py -p isa -P k=8 -P m=3 -P technique=cauchy \
        -s 1048576 -i 100 -w encode

TPU extension: --batch N packs N objects into one (N, k, chunk) device launch
(the HBM stripe-packing mode BASELINE.md measures); default 1 keeps the
reference's one-object-at-a-time behavior.

Workloads:
  encode — encode `iterations` times, print wall seconds and KiB encoded.
  decode — encode once; per iteration erase chunks (at random, from --erased,
           or exhaustively over all combinations with -E exhaustive, verifying
           rebuilt content each time) and decode.
"""

from __future__ import annotations

import argparse
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_args(argv):
    p = argparse.ArgumentParser(
        prog="ec_bench", description="erasure code benchmark (TPU backend)"
    )
    p.add_argument("-v", "--verbose", action="store_true", help="explain what happens")
    p.add_argument("-s", "--size", type=int, default=1024 * 1024,
                   help="size of the buffer to be encoded")
    p.add_argument("-i", "--iterations", type=int, default=1,
                   help="number of encode/decode runs")
    p.add_argument("-p", "--plugin", default="jerasure",
                   help="erasure code plugin name")
    p.add_argument("-w", "--workload", default="encode",
                   choices=["encode", "decode"], help="run either encode or decode")
    p.add_argument("-e", "--erasures", type=int, default=1,
                   help="number of erasures when decoding")
    p.add_argument("--erased", type=int, action="append", default=[],
                   help="erased chunk (repeat if more than one chunk is erased)")
    p.add_argument("-E", "--erasures-generation", default="random",
                   choices=["random", "exhaustive"], dest="erasures_generation")
    p.add_argument("-P", "--parameter", action="append", default=[],
                   help="add a parameter to the erasure code profile (k=v)")
    p.add_argument("--batch", type=int, default=1,
                   help="TPU extension: objects packed per device launch")
    return p.parse_args(argv)


def build_profile(params: list[str]) -> dict:
    profile = {}
    for item in params:
        if item.count("=") != 1:
            print(
                f"--parameter {item} ignored because it does not contain "
                "exactly one =",
                file=sys.stderr,
            )
            continue
        key, value = item.split("=")
        profile[key] = value
    return profile


def display_chunks(chunks, chunk_count):
    out = "chunks "
    for chunk in range(chunk_count):
        out += f"({chunk})  " if chunk not in chunks else f" {chunk}   "
    print(out + "(X) is an erased chunk")


def run_encode(ec, args) -> float:
    import jax
    import numpy as np

    data = b"X" * args.size
    if args.batch > 1:
        chunks, _ = ec.encode_prepare(data)
        batch = np.repeat(chunks, args.batch, axis=0)
        batch = jax.device_put(batch)
        t0 = time.perf_counter()
        for _ in range(args.iterations):
            out = ec.encode_array(batch)
        np.asarray(out[0, 0, :1])
        return time.perf_counter() - t0
    want = set(range(ec.get_chunk_count()))
    t0 = time.perf_counter()
    for _ in range(args.iterations):
        ec.encode(want, data)
    return time.perf_counter() - t0


def decode_erasures(ec, all_chunks, chunks, start, want_erasures, verbose):
    """Exhaustive erasure enumeration with verification (.cc:196-244)."""
    n = ec.get_chunk_count()
    if want_erasures == 0:
        if verbose:
            display_chunks(chunks, n)
        want_to_read = {c for c in range(n) if c not in chunks}
        decoded = ec.decode(want_to_read, chunks)
        for c in want_to_read:
            # chunks absent from all_chunks (pre-erased via --erased) cannot
            # be verified; the reference dereferences map.end() here
            if c in all_chunks and decoded[c] != all_chunks[c]:
                raise SystemExit(
                    f"chunk {c} content and recovered content are different"
                )
        return
    for i in range(start, n):
        # the reference recurses even when i is already absent (erase is a
        # no-op but want_erasures still decrements, .cc:234-240)
        one_less = {c: v for c, v in chunks.items() if c != i}
        decode_erasures(ec, all_chunks, one_less, i + 1, want_erasures - 1, verbose)


def run_decode(ec, args) -> float:
    data = b"X" * args.size
    n = ec.get_chunk_count()
    encoded = ec.encode(range(n), data)
    want_to_read = set(range(n))

    if args.erased:
        for c in args.erased:
            encoded.pop(c, None)
        display_chunks(encoded, n)

    t0 = time.perf_counter()
    for _ in range(args.iterations):
        if args.erasures_generation == "exhaustive":
            decode_erasures(ec, encoded, encoded, 0, args.erasures, args.verbose)
        elif args.erased:
            ec.decode(want_to_read, encoded)
        else:
            chunks = dict(encoded)
            for _ in range(args.erasures):
                while True:
                    erasure = random.randrange(n)
                    if erasure in chunks:
                        break
                del chunks[erasure]
            ec.decode(want_to_read, chunks)
    return time.perf_counter() - t0


def main(argv=None) -> int:
    args = parse_args(argv if argv is not None else sys.argv[1:])
    from ceph_tpu.common.config import config
    from ceph_tpu.ec.registry import factory

    profile = build_profile(args.parameter)
    ec = factory(args.plugin, profile)

    def run():
        if args.workload == "encode":
            return run_encode(ec, args)
        return run_decode(ec, args)

    # profiling hook (SURVEY §5): config-driven jax.profiler trace capture,
    # the analogue of the reference's LTTng tracepoints around the op loop
    if config.get("bench_profile"):
        import jax

        trace_dir = config.get("bench_profile_trace_dir") or "/tmp/ceph_tpu_trace"
        with jax.profiler.trace(trace_dir):
            elapsed = run()
        print(f"# jax.profiler trace written to {trace_dir}", file=sys.stderr)
    else:
        elapsed = run()
    kib = args.iterations * (args.size // 1024) * max(1, args.batch)
    print(f"{elapsed:.6f}\t{kib}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
