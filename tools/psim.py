"""psim — the toy placement simulator (reference: src/tools/psim.cc).

Reads an osdmaptool-created map, drives 10 namespaces x 5000 files x 4
blocks of synthetic object names through the full object -> ps -> pg ->
acting pipeline, and prints per-osd placement counts with avg/stddev —
the reference's quick eyeball check of placement quality.

Where the reference maps each object's PG one call at a time, this version
hashes all 200k names host-side and maps every distinct PG in one batched
TPU launch (OSDMap.pool_mappings).

    python tools/osdmaptool.py .ceph_osdmap --createsimple 40 --with-default-pool
    python tools/psim.py .ceph_osdmap
"""

from __future__ import annotations

import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from ceph_tpu.common.hash import ceph_str_hash_rjenkins  # noqa: E402
from ceph_tpu.osd.osdmap import CRUSH_ITEM_NONE  # noqa: E402
from tools.osdmaptool import load_osdmap  # noqa: E402

NAMESPACES, FILES, BLOCKS = 10, 5000, 4


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    mapfn = args[0] if args else ".ceph_osdmap"
    if not os.path.exists(mapfn):
        print(
            f"{sys.argv[0]}: error reading {mapfn}: create one with "
            "osdmaptool --createsimple first",
            file=sys.stderr,
        )
        return 1
    osdmap = load_osdmap(mapfn)
    if not osdmap.pools:
        print(f"{mapfn} has no pools (use --with-default-pool)",
              file=sys.stderr)
        return 1
    pool_id = sorted(osdmap.pools)[0]
    pool = osdmap.pools[pool_id]

    # object name -> ps for the whole synthetic workload: 200k distinct
    # "<file>.<block>" names (the reference's 10 namespaces x 5000 files x 4
    # blocks, psim.cc:52-60; the ps hash covers the object name)
    pg_obj_count = np.zeros(pool.pg_num, dtype=np.int64)
    for f in range(NAMESPACES * FILES):
        for b in range(BLOCKS):
            ps = pool.raw_pg_to_pg(ceph_str_hash_rjenkins(f"{f}.{b}"))
            pg_obj_count[ps] += 1

    ups = osdmap.pool_mappings(pool_id)  # one batched launch
    n = osdmap.max_osd
    count = np.zeros(n, dtype=np.int64)
    first_count = np.zeros(n, dtype=np.int64)
    primary_count = np.zeros(n, dtype=np.int64)
    # acting/primary overrides (pg_temp/primary_temp) are sparse; take the
    # scalar pipeline's word for affected PGs (psim.cc uses
    # pg_to_acting_osds) and the batched up sets for everything else
    overridden = {
        pg[1] for pg in list(osdmap.pg_temp) + list(osdmap.primary_temp)
        if pg[0] == pool_id
    }
    for ps in range(pool.pg_num):
        if ps in overridden:
            _, _, acting, primary = osdmap.pg_to_up_acting_osds(pool_id, ps)
            osds = [int(o) for o in acting if o != CRUSH_ITEM_NONE]
        else:
            osds = [int(o) for o in ups[ps] if o != CRUSH_ITEM_NONE]
            primary = osds[0] if osds else -1
        for o in osds:
            count[o] += pg_obj_count[ps]
        if osds:
            first_count[osds[0]] += pg_obj_count[ps]
        if primary >= 0:
            primary_count[primary] += pg_obj_count[ps]

    for o in range(n):
        print(f"osd.{o}\t{count[o]}\t{first_count[o]}\t{primary_count[o]}")
    avg = int(count.sum()) // n
    dev = math.sqrt(float(((count - avg) ** 2).mean()))
    print(f"avg {avg} stddev {dev:g}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
