"""psim — the placement simulator CLI (reference: src/tools/psim.cc,
grown into the ceph_tpu.sim front end).

Two modes:

* **Map-file mode** (the reference's psim.cc): read an osdmaptool-created
  map, drive 10 namespaces x 5000 files x 4 blocks of synthetic object
  names through the full object -> ps -> pg -> acting pipeline, and print
  per-osd placement counts with avg/stddev. Where the reference maps each
  object's PG one call at a time, this hashes all 200k names host-side
  and maps every distinct PG in one batched TPU launch
  (OSDMap.pool_mappings).

      python tools/osdmaptool.py .ceph_osdmap --createsimple 40 --with-default-pool
      python tools/psim.py .ceph_osdmap

* **Scenario mode** (`--scenario`, ceph_tpu.sim): build a synthetic
  cluster (host/rack hierarchy, replicated + EC pools), run a seeded
  deterministic event script (OSD flaps out/in, reweights, map churn
  epochs) with per-epoch backfill-storm estimates, then converge the
  batched balancer and report spread before/after, moves, launches —
  JSON with --json, wall-clock timings only with --measure.

      python tools/psim.py --scenario --osds 1024 --seed 1 --json
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from ceph_tpu.common.config import Config  # noqa: E402
from ceph_tpu.common.hash import ceph_str_hash_rjenkins  # noqa: E402
from ceph_tpu.osd.osdmap import CRUSH_ITEM_NONE  # noqa: E402
from tools.osdmaptool import load_osdmap  # noqa: E402

NAMESPACES, FILES, BLOCKS = 10, 5000, 4


def run_mapfile(mapfn: str) -> int:
    """The reference psim.cc flow over an existing map file."""
    if not os.path.exists(mapfn):
        print(
            f"{sys.argv[0]}: error reading {mapfn}: create one with "
            "osdmaptool --createsimple first",
            file=sys.stderr,
        )
        return 1
    osdmap = load_osdmap(mapfn)
    if not osdmap.pools:
        print(f"{mapfn} has no pools (use --with-default-pool)",
              file=sys.stderr)
        return 1
    pool_id = sorted(osdmap.pools)[0]
    pool = osdmap.pools[pool_id]

    # object name -> ps for the whole synthetic workload: 200k distinct
    # "<file>.<block>" names (the reference's 10 namespaces x 5000 files x 4
    # blocks, psim.cc:52-60; the ps hash covers the object name)
    pg_obj_count = np.zeros(pool.pg_num, dtype=np.int64)
    for f in range(NAMESPACES * FILES):
        for b in range(BLOCKS):
            ps = pool.raw_pg_to_pg(ceph_str_hash_rjenkins(f"{f}.{b}"))
            pg_obj_count[ps] += 1

    ups = osdmap.pool_mappings(pool_id)  # one batched launch
    n = osdmap.max_osd
    count = np.zeros(n, dtype=np.int64)
    first_count = np.zeros(n, dtype=np.int64)
    primary_count = np.zeros(n, dtype=np.int64)
    # acting/primary overrides (pg_temp/primary_temp) are sparse; take the
    # scalar pipeline's word for affected PGs (psim.cc uses
    # pg_to_acting_osds) and the batched up sets for everything else
    overridden = {
        pg[1] for pg in list(osdmap.pg_temp) + list(osdmap.primary_temp)
        if pg[0] == pool_id
    }
    for ps in range(pool.pg_num):
        if ps in overridden:
            _, _, acting, primary = osdmap.pg_to_up_acting_osds(pool_id, ps)
            osds = [int(o) for o in acting if o != CRUSH_ITEM_NONE]
        else:
            osds = [int(o) for o in ups[ps] if o != CRUSH_ITEM_NONE]
            primary = osds[0] if osds else -1
        for o in osds:
            count[o] += pg_obj_count[ps]
        if osds:
            first_count[osds[0]] += pg_obj_count[ps]
        if primary >= 0:
            primary_count[primary] += pg_obj_count[ps]

    for o in range(n):
        print(f"osd.{o}\t{count[o]}\t{first_count[o]}\t{primary_count[o]}")
    avg = int(count.sum()) // n
    dev = math.sqrt(float(((count - avg) ** 2).mean()))
    print(f"avg {avg} stddev {dev:g}")
    return 0


def run_scenario_cli(args) -> int:
    from ceph_tpu.sim import run_scenario

    cfg = Config()
    n_osd = args.osds if args.osds else cfg.get("psim_default_osds")
    seed = args.seed if args.seed is not None else cfg.get(
        "psim_default_seed"
    )
    bytes_per_pg = (
        args.bytes_per_pg if args.bytes_per_pg
        else cfg.get("psim_bytes_per_pg")
    )
    rep_pgs = args.rep_pgs if args.rep_pgs else max(64, n_osd * 32)
    ec_pgs = args.ec_pgs if args.ec_pgs is not None else max(
        32, n_osd * 8
    )
    report = run_scenario(
        n_osd=n_osd,
        osds_per_host=args.osds_per_host,
        hosts_per_rack=args.hosts_per_rack,
        rep_pg_num=rep_pgs,
        ec_pg_num=ec_pgs,
        seed=seed,
        epochs=args.epochs,
        bytes_per_pg=bytes_per_pg,
        balance_after=not args.no_balance,
        max_deviation=args.max_deviation,
        max_changes=args.max_changes,
        measure=args.measure,
    )
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
        return 0
    print(
        f"cluster: {report['osds']} osds / {report['hosts']} hosts / "
        f"{report['racks']} racks, {report['pg_instances']} pg instances"
    )
    for ep in report["epochs"]:
        names = ",".join(ev[0] for ev in ep["events"]) or "none"
        print(
            f"epoch {ep['epoch']}: events [{names}] moved "
            f"{ep['pgs_moved']} pgs (~{ep['bytes_moved'] >> 30} GiB "
            "backfill)"
        )
    bal = report.get("balance")
    if bal:
        print(
            f"balance: {bal['changes']} moves in {bal['rounds']} rounds "
            f"({bal['launches']} launches), spread "
            f"{bal['spread_before']:.2f} -> {bal['spread_after']:.2f} "
            f"{'CONVERGED' if bal['converged'] else 'NOT converged'}"
        )
    timing = report.get("timing")
    if timing:
        print(
            f"timing: {timing['pgs_mapped']} pgs mapped in "
            f"{timing['map_seconds']:.3f}s "
            f"({timing['pgs_mapped_per_s']:.0f}/s), balance "
            f"{timing.get('balance_seconds', 0.0):.3f}s, total "
            f"{timing['total_seconds']:.3f}s"
        )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="psim", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("mapfile", nargs="?", default=None,
                    help="osdmaptool map file (map-file mode)")
    ap.add_argument("--scenario", action="store_true",
                    help="run a ceph_tpu.sim synthetic-cluster scenario")
    ap.add_argument("--osds", type=int, default=0,
                    help="cluster size (default: psim_default_osds knob)")
    ap.add_argument("--osds-per-host", type=int, default=8)
    ap.add_argument("--hosts-per-rack", type=int, default=4)
    ap.add_argument("--rep-pgs", type=int, default=0,
                    help="replicated pool pg_num (default: osds*32)")
    ap.add_argument("--ec-pgs", type=int, default=None,
                    help="EC pool pg_num (default: osds*8; 0 disables)")
    ap.add_argument("--seed", type=int, default=None,
                    help="event RNG seed (default: psim_default_seed knob)")
    ap.add_argument("--epochs", type=int, default=3,
                    help="churn epochs to script")
    ap.add_argument("--bytes-per-pg", type=int, default=0,
                    help="backfill estimate scale "
                         "(default: psim_bytes_per_pg knob)")
    ap.add_argument("--no-balance", action="store_true",
                    help="skip the balancer convergence stage")
    ap.add_argument("--max-deviation", type=float, default=1.0)
    ap.add_argument("--max-changes", type=int, default=512)
    ap.add_argument("--measure", action="store_true",
                    help="include wall-clock timings (report is no "
                         "longer byte-deterministic)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])

    if args.scenario:
        return run_scenario_cli(args)
    return run_mapfile(args.mapfile if args.mapfile else ".ceph_osdmap")


if __name__ == "__main__":
    sys.exit(main())
