"""The operator CLI (src/ceph.in analogue): drive a live cluster's mon
quorum and daemons from the shell.

    python tools/ceph.py --mon-host 127.0.0.1:6789[,...] <command>

Commands mirror the reference surface:

    status | -s                      cluster status (quorum, epoch, osds)
    df                               cluster + per-osd utilization (incl.
                                     data_compressed / compress_ratio when
                                     blockstore compression is active)
    log last [n]                     tail of the mon cluster log (fence,
                                     read-EIO-repair, slow-request events)
    health                           health checks (OSD_DOWN, PG_DEGRADED,
                                     PG_DAMAGED, ...) with severities
    osd tree                         crush hierarchy with up/down + weights
    osd pool create <id> <rule> [--size N | --profile NAME] [--pg-num N]
    osd erasure-code-profile set <name> k=K m=M [plugin=tpu ...]
    osd down|out|in <osd>
    osd pg-upmap-items <pool.ps> <from:to> [...]
    pg dump [--pool N]               pg -> up/acting/primary
    trace ls | show <id>             tail-promoted traces from the mgr's
                                     flight-recorder store
    balancer run [--pools a,b]       one upmap-balancer pass
    daemon osd.<id> <cmd> [k=v...]   admin socket commands (perf dump,
                                     status, scrub pool=N deep=1, repair
                                     pool=N, dump_ops_in_flight, ...)

Output is JSON per command (the reference's `-f json`)."""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _parse_kv(pairs):
    out = {}
    for p in pairs:
        k, _, v = p.partition("=")
        out[k] = v
    return out


async def _amain(args) -> int:
    from ceph_tpu.common.config import Config
    from ceph_tpu.mon import MonMap
    from ceph_tpu.rados.client import Rados

    addrs = []
    for hostport in args.mon_host.split(","):
        host, _, port = hostport.rpartition(":")
        addrs.append((host or "127.0.0.1", int(port)))
    monmap = MonMap(addrs=addrs)
    rados = Rados(args.name, monmap, config=Config())
    await rados.connect()
    try:
        result = await _dispatch(rados, args)
        print(json.dumps(result, indent=2, sort_keys=True))
        return 0
    finally:
        await rados.shutdown()


async def _dispatch(rados, args) -> dict:
    cmd = args.command
    if cmd in ("status", "-s"):
        return await rados.mon_command("status")

    if cmd == "health":
        return await rados.mon_command("health")

    if cmd == "df":
        return await rados.mon_command("df")

    if cmd == "osd":
        sub = args.rest[0]
        if sub == "tree":
            return _osd_tree(rados.objecter.osdmap)
        if sub == "pool" and args.rest[1] == "create":
            pool_id = int(args.rest[2])
            rule = int(args.rest[3])
            payload = {"pool_id": pool_id, "crush_rule": rule}
            if args.profile:
                payload["erasure_code_profile"] = args.profile
            if args.size:
                payload["size"] = args.size
            if args.pg_num:
                payload["pg_num"] = args.pg_num
            return await rados.mon_command("osd pool create", payload)
        if sub == "erasure-code-profile" and args.rest[1] == "set":
            return await rados.mon_command(
                "osd erasure-code-profile set",
                {"name": args.rest[2],
                 "profile": _parse_kv(args.rest[3:])},
            )
        if sub in ("down", "out", "in"):
            return await rados.mon_command(
                f"osd {sub}", {"osd": int(args.rest[1])}
            )
        if sub == "pg-upmap-items":
            mappings = {
                args.rest[1]: [
                    [int(a) for a in pair.split(":")]
                    for pair in args.rest[2:]
                ]
            }
            return await rados.mon_command(
                "osd pg-upmap-items", {"mappings": mappings}
            )
        raise SystemExit(f"unknown osd subcommand {sub!r}")

    if cmd == "config":
        sub = args.rest[0]
        if sub == "set":
            return await rados.mon_command(
                "config set",
                {"name": args.rest[1], "value": args.rest[2]},
            )
        if sub == "get":
            return await rados.mon_command(
                "config get", {"name": args.rest[1]}
            )
        if sub == "rm":
            return await rados.mon_command(
                "config rm", {"name": args.rest[1]}
            )
        if sub == "dump":
            return await rados.mon_command("config dump", {})
        raise SystemExit(f"unknown config subcommand {sub!r}")
    if cmd == "log":
        sub = args.rest[0] if args.rest else "last"
        if sub == "last":
            n = int(args.rest[1]) if len(args.rest) > 1 else 20
            return await rados.mon_command("log last", {"n": n})
        raise SystemExit(f"unknown log subcommand {sub!r}")

    if cmd == "pg" and args.rest[0] == "dump":
        return _pg_dump(rados.objecter.osdmap, args.pool)

    if cmd == "prometheus":
        from ceph_tpu.mgr import PrometheusExporter

        text = await PrometheusExporter(rados.objecter).collect()
        return {"metrics": text}
    if cmd == "autoscaler":
        from ceph_tpu.mgr import PgAutoscaler

        apply = len(args.rest) > 0 and args.rest[0] == "apply"
        return await PgAutoscaler(rados.objecter).run_once(apply=apply)
    if cmd == "balancer" and args.rest[0] == "run":
        from ceph_tpu.mgr import BalancerModule

        pools = (
            {int(p) for p in args.pools.split(",")} if args.pools else None
        )
        return await BalancerModule(rados.objecter.mon).run_once(
            pools=pools
        )

    if cmd == "trace":
        # flight-recorder queries answered by the active mgr's trace
        # collector (tail-promoted traces; see ceph_tpu/mgr/traces.py)
        from ceph_tpu.mon import MonMap
        from tools.ceph_top import TopClient

        addrs = []
        for hostport in args.mon_host.split(","):
            host, _, port = hostport.rpartition(":")
            addrs.append((host or "127.0.0.1", int(port)))
        top = TopClient(MonMap(addrs=addrs), name=f"{args.name}.trace")
        try:
            sub = args.rest[0] if args.rest else "ls"
            if sub == "ls":
                return await top.fetch("trace ls")
            if sub == "show":
                if len(args.rest) < 2:
                    raise SystemExit("usage: trace show <trace_id>")
                return await top.fetch(
                    "trace show", trace_id=args.rest[1]
                )
            raise SystemExit(f"unknown trace subcommand {sub!r}")
        finally:
            await top.close()

    if cmd == "daemon":
        target = args.rest[0]
        if not target.startswith("osd."):
            raise SystemExit("daemon target must be osd.<id>")
        osd = int(target.split(".", 1)[1])
        admin_cmd = args.rest[1]
        if admin_cmd in ("perf", "dump") and args.rest[1:3] == [
            "perf", "dump"
        ]:
            admin_cmd = "perf dump"
            extra = _parse_kv(args.rest[3:])
        else:
            extra = _parse_kv(args.rest[2:])
        parsed = {
            k: (int(v) if v.isdigit() else v) for k, v in extra.items()
        }
        if "deep" in parsed:
            parsed["deep"] = bool(int(parsed["deep"]))
        return await rados.objecter.osd_admin(osd, admin_cmd, parsed)

    raise SystemExit(f"unknown command {cmd!r}")


def _osd_tree(osdmap) -> dict:
    """`ceph osd tree`: the CrushTreeDumper walk annotated with live
    daemon state (up/down + reweight)."""
    from ceph_tpu.crush.tree import dump_items

    cmap = osdmap.crush
    nodes = []
    for node in dump_items(cmap):
        if node["type"] == "osd":
            osd = node["id"]
            node = {
                **node,
                "status": "up" if osdmap.osd_up[osd] else "down",
                "reweight": float(osdmap.osd_weight[osd]) / 0x10000,
            }
            node.pop("weight", None)
        nodes.append(node)
    return {"nodes": nodes, "epoch": osdmap.epoch}


def _pg_dump(osdmap, pool: int | None) -> dict:
    pgs = []
    for pid, p in sorted(osdmap.pools.items()):
        if pool is not None and pid != pool:
            continue
        for ps in range(p.pg_num):
            up, upp, acting, primary = osdmap.pg_to_up_acting_osds(pid, ps)
            pgs.append({
                "pgid": f"{pid}.{ps}",
                "up": up,
                "acting": acting,
                "primary": primary,
            })
    return {"epoch": osdmap.epoch, "num_pgs": len(pgs), "pgs": pgs}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ceph")
    ap.add_argument("--mon-host", required=True,
                    help="comma-separated mon host:port list")
    ap.add_argument("--name", default="client.admin")
    ap.add_argument("--size", type=int, default=0)
    ap.add_argument("--pg-num", type=int, default=0)
    ap.add_argument("--profile", default="")
    ap.add_argument("--pool", type=int, default=None)
    ap.add_argument("--pools", default="")
    ap.add_argument("command")
    ap.add_argument("rest", nargs="*")
    args = ap.parse_args(argv)
    return asyncio.run(_amain(args))


if __name__ == "__main__":
    sys.exit(main())
