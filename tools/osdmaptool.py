"""osdmaptool — create/inspect/test OSD maps (reference CLI parity).

Mirrors /root/reference/src/tools/osdmaptool.cc for the workflows the
framework supports:

    osdmaptool --createsimple <numosd> map.json [--pg-bits B] \\
               [--with-default-pool] [--clobber]
    osdmaptool map.json --print
    osdmaptool map.json --tree
    osdmaptool map.json --test-map-pgs [--pool N] [--pg-num N]
    osdmaptool map.json --test-map-pgs-dump [--pool N]
    osdmaptool map.json --test-map-object <name> [--pool N]
    osdmaptool map.json --mark-out <osd>
    osdmaptool map.json --upmap out.txt [--upmap-max N] \\
               [--upmap-deviation D] [--upmap-save]

The whole-pool mapping behind --test-map-pgs is the batched TPU mapper
(OSDMap.pool_mappings) — the reference does this one PG at a time on one
thread (osdmaptool.cc test_map_pgs loop) or on a thread pool
(ParallelPGMapper); output formats (per-osd count table, avg/stddev, size
histogram, `ceph osd pg-upmap-items` command stream) mirror the reference.

Storage is a JSON envelope (crushmap as its canonical text form + pool/osd
state), not the reference's binary encoding; see tools/crushtool.py.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from ceph_tpu.crush import builder as cb  # noqa: E402
from ceph_tpu.crush.compiler import (  # noqa: E402
    compile_crushmap,
    decompile_crushmap,
)
from ceph_tpu.crush.types import BucketAlg, CrushMap, Tunables  # noqa: E402
from ceph_tpu.osd.osdmap import CRUSH_ITEM_NONE, OSDMap  # noqa: E402
from ceph_tpu.osd.types import TYPE_REPLICATED, PgPool  # noqa: E402

STORE_VERSION = 1


# -- storage -----------------------------------------------------------------


def save_map(osdmap: OSDMap, path: str) -> None:
    doc = {
        "ceph_tpu_osdmap": STORE_VERSION,
        "epoch": osdmap.epoch,
        "max_osd": osdmap.max_osd,
        "crush": decompile_crushmap(osdmap.crush),
        "pools": {
            str(pid): {
                "pg_num": p.pg_num, "pgp_num": p.pgp_num, "size": p.size,
                "min_size": p.min_size, "type": p.type,
                "crush_rule": p.crush_rule, "flags": p.flags,
                "erasure_code_profile": p.erasure_code_profile,
            }
            for pid, p in osdmap.pools.items()
        },
        "osd_exists": osdmap.osd_exists.astype(int).tolist(),
        "osd_up": osdmap.osd_up.astype(int).tolist(),
        "osd_weight": osdmap.osd_weight.tolist(),
        "osd_primary_affinity": (
            osdmap.osd_primary_affinity.tolist()
            if osdmap.osd_primary_affinity is not None
            else None
        ),
        "pg_upmap": [
            [list(pg), list(osds)] for pg, osds in osdmap.pg_upmap.items()
        ],
        "pg_upmap_items": [
            [list(pg), [list(pair) for pair in items]]
            for pg, items in osdmap.pg_upmap_items.items()
        ],
        "pg_temp": [
            [list(pg), list(osds)] for pg, osds in osdmap.pg_temp.items()
        ],
        "primary_temp": [
            [list(pg), osd] for pg, osd in osdmap.primary_temp.items()
        ],
    }
    with open(path, "w") as f:
        json.dump(doc, f)


def load_osdmap(path: str) -> OSDMap:
    doc = json.load(open(path))
    if doc.get("ceph_tpu_osdmap") != STORE_VERSION:
        raise SystemExit(f"{path}: not a ceph_tpu osdmap store")
    cmap = compile_crushmap(doc["crush"])
    m = OSDMap(crush=cmap, max_osd=doc["max_osd"], epoch=doc["epoch"])
    for pid, p in doc["pools"].items():
        pool = PgPool(
            pg_num=p["pg_num"], pgp_num=p["pgp_num"], size=p["size"],
            min_size=p["min_size"], type=p["type"],
            crush_rule=p["crush_rule"],
            erasure_code_profile=p.get("erasure_code_profile", ""),
        )
        if "flags" in p:
            pool.flags = p["flags"]
        m.pools[int(pid)] = pool
    m.osd_exists = np.asarray(doc["osd_exists"], dtype=bool)
    m.osd_up = np.asarray(doc["osd_up"], dtype=bool)
    m.osd_weight = np.asarray(doc["osd_weight"], dtype=np.int64)
    if doc.get("osd_primary_affinity") is not None:
        m.osd_primary_affinity = np.asarray(
            doc["osd_primary_affinity"], dtype=np.int64
        )
    for pg, osds in doc.get("pg_upmap", []):
        m.pg_upmap[tuple(pg)] = list(osds)
    for pg, items in doc.get("pg_upmap_items", []):
        m.pg_upmap_items[tuple(pg)] = [tuple(i) for i in items]
    for pg, osds in doc.get("pg_temp", []):
        m.pg_temp[tuple(pg)] = list(osds)
    for pg, osd in doc.get("primary_temp", []):
        m.primary_temp[tuple(pg)] = osd
    return m


# -- createsimple (OSDMap::build_simple) -------------------------------------


def build_simple(
    n_osd: int, pg_bits: int = 6, with_default_pool: bool = False,
    osds_per_host: int = 4,
) -> OSDMap:
    """A generic map: hosts of `osds_per_host` osds under one root, one
    replicated rule; optionally a default pool with n_osd << pg_bits PGs
    spread over it (the shape OSDMap::build_simple produces)."""
    cmap = CrushMap(tunables=Tunables.jewel())
    cmap.type_names = {0: "osd", 1: "host", 10: "root"}
    host_ids, host_ws = [], []
    osd = 0
    n_hosts = max(1, (n_osd + osds_per_host - 1) // osds_per_host)
    for h in range(n_hosts):
        items = list(range(osd, min(osd + osds_per_host, n_osd)))
        if not items:
            break
        osd += len(items)
        b = cb.make_bucket(
            cmap, -(h + 2), BucketAlg.STRAW2, 1, items,
            [0x10000] * len(items),
        )
        cmap.item_names[b.id] = f"host{h}"
        host_ids.append(b.id)
        host_ws.append(b.weight)
    root = cb.make_bucket(cmap, -1, BucketAlg.STRAW2, 10, host_ids, host_ws)
    cmap.item_names[root.id] = "default"
    for o in range(n_osd):
        cmap.item_names[o] = f"osd.{o}"
    cb.make_simple_rule(cmap, 0, -1, 1, "firstn", 0)
    cmap.rule_names[0] = "replicated_rule"
    m = OSDMap(crush=cmap, max_osd=n_osd)
    if with_default_pool:
        m.pools[1] = PgPool(
            pg_num=n_osd << pg_bits, size=3, type=TYPE_REPLICATED,
            crush_rule=0,
        )
    return m


# -- the map-pgs engine ------------------------------------------------------


def run_test_map_pgs(osdmap: OSDMap, pool: int, pg_num: int, dump: bool,
                 out) -> None:
    n = osdmap.max_osd
    count = np.zeros(n, dtype=np.int64)
    first_count = np.zeros(n, dtype=np.int64)
    primary_count = np.zeros(n, dtype=np.int64)
    size_hist: dict[int, int] = {}
    saved_geometry: dict[int, tuple[int, int]] = {}
    # the primary differs from up[0] only under primary-affinity or
    # primary_temp overrides; take the scalar pipeline's word then, and the
    # cheap first-osd answer otherwise
    affinity_default = (
        osdmap.osd_primary_affinity is None
        or bool((osdmap.osd_primary_affinity == 0x10000).all())
    )
    need_scalar_primary = bool(osdmap.primary_temp) or not affinity_default
    for pid in sorted(osdmap.pools):
        if pool != -1 and pid != pool:
            continue
        p = osdmap.pools[pid]
        if pg_num > 0:
            # a DIAGNOSTIC override: remember the real geometry (main
            # restores it before any save) and drop per-PG overrides that
            # point past the new pg_num
            saved_geometry[pid] = (p.pg_num, p.pgp_num)
            p.pg_num = pg_num
            p.pgp_num = pg_num
        print(f"pool {pid} pg_num {p.pg_num}", file=out)
        ups = osdmap.pool_mappings(pid)  # the batched TPU mapper
        for ps in range(p.pg_num):
            osds = [int(o) for o in ups[ps] if o != CRUSH_ITEM_NONE]
            if need_scalar_primary:
                _, _, _, primary = osdmap.pg_to_up_acting_osds(pid, ps)
            else:
                primary = osds[0] if osds else -1
            size_hist[len(osds)] = size_hist.get(len(osds), 0) + 1
            if dump:
                vec = "[" + ",".join(str(o) for o in osds) + "]"
                print(f"{pid}.{ps:x}\t{vec}\t{primary}", file=out)
            for o in osds:
                count[o] += 1
            if osds:
                first_count[osds[0]] += 1
            if primary >= 0:
                primary_count[primary] += 1

    weights = osdmap.osd_weight
    print("#osd\tcount\tfirst\tprimary\tc wt\twt", file=out)
    in_osds = []
    for o in range(n):
        if not osdmap.osd_exists[o] or weights[o] <= 0:
            continue
        in_osds.append(o)
        cw = _crush_weightf(osdmap.crush, o)
        print(
            f"osd.{o}\t{count[o]}\t{first_count[o]}\t{primary_count[o]}"
            f"\t{cw:g}\t{weights[o] / 65536:g}",
            file=out,
        )
    if not in_osds:
        return
    counts_in = count[in_osds]
    total = int(counts_in.sum())
    avg = total // len(in_osds)
    dev = math.sqrt(float(((avg - counts_in) ** 2).mean()))
    edev = math.sqrt(
        total / len(in_osds) * (1.0 - 1.0 / len(in_osds))
    )
    print(f" in {len(in_osds)}", file=out)
    print(
        f" avg {avg} stddev {dev:g} ({dev / avg if avg else 0:g}x) "
        f"(expected {edev:g} {edev / avg if avg else 0:g}x))",
        file=out,
    )
    nz = [o for o in in_osds if count[o]]
    if nz:
        mn = min(nz, key=lambda o: count[o])
        mx = max(nz, key=lambda o: count[o])
        print(f" min osd.{mn} {count[mn]}", file=out)
        print(f" max osd.{mx} {count[mx]}", file=out)
    for s in sorted(size_hist):
        print(f"size {s}\t{size_hist[s]}", file=out)
    # undo the diagnostic pg_num override so a later save cannot persist it
    for pid, (old_pg, old_pgp) in saved_geometry.items():
        osdmap.pools[pid].pg_num = old_pg
        osdmap.pools[pid].pgp_num = old_pgp


def _crush_weightf(cmap: CrushMap, osd: int) -> float:
    for b in cmap.buckets.values():
        if osd in b.items:
            return b.item_weights[b.items.index(osd)] / 65536.0
    return 0.0


def upmap_commands(osdmap: OSDMap, before: dict) -> list[str]:
    """`ceph osd pg-upmap-items` command stream for NEW entries
    (osdmaptool.cc:79-84)."""
    cmds = []
    for pg, items in sorted(osdmap.pg_upmap_items.items()):
        if before.get(pg) == items:
            continue
        pairs = " ".join(f"{a} {b}" for a, b in items)
        cmds.append(f"ceph osd pg-upmap-items {pg[0]}.{pg[1]:x} {pairs}")
    return cmds


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="osdmaptool")
    ap.add_argument("mapfn")
    ap.add_argument("--createsimple", type=int, metavar="numosd")
    ap.add_argument("--pg-bits", type=int, default=6)
    ap.add_argument("--with-default-pool", action="store_true")
    ap.add_argument("--clobber", action="store_true")
    ap.add_argument("--print", dest="do_print", action="store_true")
    ap.add_argument("--tree", action="store_true")
    ap.add_argument("--test-map-pgs", action="store_true")
    ap.add_argument("--test-map-pgs-dump", action="store_true")
    ap.add_argument("--test-map-object", metavar="name")
    ap.add_argument("--pool", type=int, default=-1)
    ap.add_argument("--pg-num", type=int, default=-1)
    ap.add_argument("--mark-out", type=int, default=None, metavar="osd")
    ap.add_argument("--upmap", metavar="file")
    ap.add_argument("--upmap-max", type=int, default=100)
    ap.add_argument("--upmap-deviation", type=float, default=5.0)
    ap.add_argument("--upmap-save", action="store_true")
    args = ap.parse_args(argv)

    if args.createsimple is not None:
        if os.path.exists(args.mapfn) and not args.clobber:
            print(
                f"osdmaptool: {args.mapfn} exists, --clobber to overwrite",
                file=sys.stderr,
            )
            return 1
        m = build_simple(
            args.createsimple, args.pg_bits, args.with_default_pool
        )
        save_map(m, args.mapfn)
        print(f"osdmaptool: writing epoch {m.epoch} to {args.mapfn}")
        return 0

    osdmap = load_osdmap(args.mapfn)
    dirty = False

    if args.mark_out is not None:
        osdmap.mark_out(args.mark_out)
        dirty = True

    if args.do_print:
        print(f"epoch {osdmap.epoch}")
        print(f"max_osd {osdmap.max_osd}")
        for pid in sorted(osdmap.pools):
            p = osdmap.pools[pid]
            kind = "replicated" if p.type == TYPE_REPLICATED else "erasure"
            print(
                f"pool {pid} '{kind}' size {p.size} min_size {p.min_size} "
                f"crush_rule {p.crush_rule} pg_num {p.pg_num} "
                f"pgp_num {p.pgp_num}"
            )
        for o in range(osdmap.max_osd):
            state = "up" if osdmap.osd_up[o] else "down"
            inout = "in" if osdmap.osd_weight[o] > 0 else "out"
            print(
                f"osd.{o} {state} {inout} "
                f"weight {osdmap.osd_weight[o] / 65536:g}"
            )

    if args.tree:
        from tools.crushtool import dump_tree

        dump_tree(osdmap.crush, sys.stdout)

    if args.test_map_pgs or args.test_map_pgs_dump:
        run_test_map_pgs(
            osdmap, args.pool, args.pg_num, args.test_map_pgs_dump,
            sys.stdout,
        )

    if args.test_map_object:
        from ceph_tpu.common.hash import ceph_str_hash_rjenkins

        if args.pool == -1 and not osdmap.pools:
            print("osdmaptool: map has no pools", file=sys.stderr)
            return 1
        pool = args.pool if args.pool != -1 else sorted(osdmap.pools)[0]
        if pool not in osdmap.pools:
            print(f"osdmaptool: There is no pool {pool}", file=sys.stderr)
            return 1
        p = osdmap.pools[pool]
        ps = p.raw_pg_to_pg(ceph_str_hash_rjenkins(args.test_map_object))
        up, up_primary, acting, _ = osdmap.pg_to_up_acting_osds(pool, ps)
        vec = "[" + ",".join(str(o) for o in acting) + "]"
        print(
            f" object '{args.test_map_object}' -> {pool}.{ps:x} -> {vec}"
        )

    if args.upmap:
        before = {
            pg: list(items) for pg, items in osdmap.pg_upmap_items.items()
        }
        changed = osdmap.calc_pg_upmaps(
            max_deviation=args.upmap_deviation,
            max_changes=args.upmap_max,
            pools=None if args.pool == -1 else {args.pool},
        )
        cmds = upmap_commands(osdmap, before)
        out = sys.stdout if args.upmap == "-" else open(args.upmap, "w")
        for c in cmds:
            print(c, file=out)
        if out is not sys.stdout:
            out.close()
        print(f"changed {changed} pgs", file=sys.stderr)
        if args.upmap_save:
            dirty = True

    if dirty:
        save_map(osdmap, args.mapfn)
        print(f"osdmaptool: writing epoch {osdmap.epoch} to {args.mapfn}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
